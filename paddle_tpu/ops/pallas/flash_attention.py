"""Pallas TPU flash attention.

The hot op of every BASELINE transformer config. Tiles Q/K/V blocks through
VMEM with online-softmax accumulation — the (T,T) score matrix never touches
HBM, so attention becomes MXU-bound instead of HBM-bound for long sequences.

Forward: Pallas kernel, grid (B*H, Tq/BQ, Tk/BK), f32 accumulators in VMEM
scratch persisting across the (innermost, sequential) k-block dimension;
emits a logsumexp residual alongside the output.
Backward: Pallas dK/dV and dQ kernels that recompute p = exp(s - lse)
per tile from the saved (out, lse) residuals — flash-attention-2 style, no
(T,T) matrix in HBM in either direction, with the additive mask applied
in-kernel. The mask cotangent (needed only for learned biases) is a
separate XLA expression that DCEs away when unused.

Layout contract: q, k, v are (B, H, T, D); additive mask broadcastable
(B, 1, 1, Tk) or (B, 1, Tq, Tk). On CPU (tests) the kernel runs in
interpret mode.
"""
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU_PALLAS = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_TPU_PALLAS = False

_NEG_INF = -1e30


def _dot_precision(dtype):
    """MXU precision for kernel matmuls given the user-facing dtype.

    f32 (and fp16: 10 mantissa bits > bf16's 7) inputs at DEFAULT
    precision run a single bf16 pass on the MXU (~1e-3 relative error) —
    a user asking for f32/fp16 attention gets full-precision math
    (HIGHEST = multi-pass), matching the reference's true-precision CUDA
    kernels. bf16 inputs stay on the fast path: their products are exact
    in the f32 accumulator, so DEFAULT already matches the oracle."""
    return (jax.lax.Precision.DEFAULT
            if jnp.dtype(dtype) == jnp.bfloat16 else
            jax.lax.Precision.HIGHEST)


def _causal_keep(qi, kj, causal_offset, block_q, block_k):
    """Bool (BQ, BK) tile of the bottom-right-aligned causal mask
    (query i sees keys j <= i + causal_offset) — shared by all kernels."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos + causal_offset >= k_pos


def _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
              qi, kj, *, scale, causal, causal_offset, block_q, block_k,
              mask_mode, precision):
    """Recompute the probability tile p = exp(s - lse) and the logit
    cotangent ds = p * (dO V^T - delta) from the forward residuals —
    the shared core of both backward kernels."""
    q = q_ref[0].astype(jnp.float32)            # (BQ, D)
    k = k_ref[0].astype(jnp.float32)            # (BK, D)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)          # (BQ, D)
    lse = lse_ref[0, 0].astype(jnp.float32)     # (BQ,) — row 0 is real
    delta = delta_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision) * scale  # (BQ, BK)
    if mask_mode == "qk":
        s = s + mask_ref[0, 0].astype(jnp.float32)
    elif mask_mode == "k":
        s = s + mask_ref[0, 0, 0][None, :].astype(jnp.float32)
    p = jnp.exp(s - lse[:, None])
    if causal:
        p = jnp.where(_causal_keep(qi, kj, causal_offset, block_q,
                                   block_k), p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision)                    # (BQ, BK)
    ds = p * (dp - delta[:, None])
    return q, k, do, p, ds


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, acc_ref,
                m_ref, l_ref, *, scale, causal, causal_offset, block_q,
                block_k, mask_mode, precision):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0].astype(jnp.float32)          # (BQ, D)
        k = k_ref[0].astype(jnp.float32)          # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision) * scale  # (BQ, BK)
        if mask_mode == "qk":
            s = s + mask_ref[0, 0].astype(jnp.float32)
        elif mask_mode == "k":
            s = s + mask_ref[0, 0, 0][None, :].astype(jnp.float32)
        if causal:
            # bottom-right aligned for Tq != Tk (matches _xla_attention's
            # tril(..., tk - tq)): query i sees keys j <= i + (tk - tq)
            s = jnp.where(_causal_keep(qi, kj, causal_offset, block_q,
                                       block_k), s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # (BQ, 1)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)                     # (BQ, BK)
        corr = jnp.exp(m_prev - m_new)             # (BQ, 1)
        l_new = corr * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision)                   # (BQ, D)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip k-blocks strictly above the (offset) diagonal
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1) +
                 causal_offset)
        def _():
            body()
    else:
        body()

    @pl.when(kj == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)
        # logsumexp residual for the Pallas backward: lse = m + log(l).
        # Stored with a sublane dim of 8 — Mosaic requires block last-two
        # dims divisible by (8, 128); row 0 is the real data
        lse = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))
        lse_ref[0] = jnp.broadcast_to(lse[None, :],
                                      lse_ref.shape[1:]).astype(lse_ref.dtype)


def _mask_spec(mask, h, q_dtype, block_q, block_k, kj_innermost):
    """(mask_mode, mask_input, BlockSpec) for an additive mask broadcastable
    (B,1,1,Tk) ["k" mode] or (B,1,Tq,Tk) ["qk"]. Grid index order is
    (bh, i, j) for the forward/dQ kernels (kj_innermost) and (bh, j, i)
    for dK/dV."""
    if mask is None:
        return "none", jnp.zeros((1, 1, 1, 1), q_dtype), pl.BlockSpec(
            (1, 1, 1, 1), lambda bb, a, b_: (0, 0, 0, 0))
    if mask.shape[2] == 1:
        if kj_innermost:
            def _idx(bb, i, j, hh=h):
                return (bb // hh, 0, 0, j)
        else:
            def _idx(bb, j, i, hh=h):
                return (bb // hh, 0, 0, j)
        return "k", mask, pl.BlockSpec((1, 1, 1, block_k), _idx)
    if kj_innermost:
        def _idx(bb, i, j, hh=h):
            return (bb // hh, 0, i, j)
    else:
        def _idx(bb, j, i, hh=h):
            return (bb // hh, 0, i, j)
    return "qk", mask, pl.BlockSpec((1, 1, block_q, block_k), _idx)


def _pallas_forward(q, k, v, mask, scale, causal, block_q, block_k,
                    interpret):
    if not _HAS_TPU_PALLAS:
        raise NotImplementedError("pallas tpu backend unavailable")
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)

    grid = (bh, tq // block_q, tk // block_k)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bb, i, j: (bb, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda bb, i, j: (bb, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda bb, i, j: (bb, j, 0)),
    ]
    mask_mode, mask_in, mask_spec = _mask_spec(mask, h, q.dtype, block_q,
                                               block_k, kj_innermost=True)
    in_specs.append(mask_spec)

    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
    ]

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          causal_offset=tk - tq, block_q=block_q,
                          block_k=block_k, mask_mode=mask_mode,
                          precision=_dot_precision(q.dtype)),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bb, i, j: (bb, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda bb, i, j: (bb, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, tq), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q3, k3, v3, mask_in)
    return out.reshape(b, h, tq, d), lse[:, 0, :].reshape(b, h, tq)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    mask_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, causal_offset, block_q, block_k,
                    mask_mode, precision):
    """dK/dV for one k-block, accumulating over q-blocks (innermost grid
    dim). Recomputes p = exp(s - lse) from residuals — no (T,T) in HBM."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def body():
        q, _, do, p, ds = _bwd_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
            qi, kj, scale=scale, causal=causal,
            causal_offset=causal_offset, block_q=block_q,
            block_k=block_k, mask_mode=mask_mode, precision=precision)
        # dv += p^T dO ; dk += scale * ds^T q
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        dk_acc[:] = dk_acc[:] + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    if causal:
        @pl.when(qi * block_q + (block_q - 1) + causal_offset >=
                 kj * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   mask_ref, dq_ref, dq_acc, *, scale, causal,
                   causal_offset, block_q, block_k, mask_mode, precision):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def body():
        _, k, _, _, ds = _bwd_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
            qi, kj, scale=scale, causal=causal,
            causal_offset=causal_offset, block_q=block_q,
            block_k=block_k, mask_mode=mask_mode, precision=precision)
        dq_acc[:] = dq_acc[:] + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    if causal:
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1) +
                 causal_offset)
        def _():
            body()
    else:
        body()

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _pallas_backward(q, k, v, mask, out, lse, g, scale, causal, block_q,
                     block_k, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    do3 = g.reshape(bh, tq, d)
    # lse/delta carry a sublane dim of 8 for Mosaic block alignment
    lse3 = jnp.broadcast_to(lse.reshape(bh, 1, tq), (bh, 8, tq))
    # delta = rowsum(dO * O): cheap elementwise pass in XLA
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, tq)
    delta = jnp.broadcast_to(delta, (bh, 8, tq))

    mask_mode, mask_in, dkv_mask_spec = _mask_spec(
        mask, h, q.dtype, block_q, block_k, kj_innermost=False)
    common = dict(scale=scale, causal=causal, causal_offset=tk - tq,
                  block_q=block_q, block_k=block_k, mask_mode=mask_mode,
                  precision=_dot_precision(q.dtype))
    dkv_specs = [
        pl.BlockSpec((1, block_q, d), lambda bb, j, i: (bb, i, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda bb, j, i: (bb, j, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda bb, j, i: (bb, j, 0)),   # v
        pl.BlockSpec((1, block_q, d), lambda bb, j, i: (bb, i, 0)),   # do
        pl.BlockSpec((1, 8, block_q), lambda bb, j, i: (bb, 0, i)),   # lse
        pl.BlockSpec((1, 8, block_q), lambda bb, j, i: (bb, 0, i)),   # delta
        dkv_mask_spec,
    ]
    dk3, dv3 = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(bh, tk // block_k, tq // block_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bb, j, i: (bb, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bb, j, i: (bb, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta, mask_in)

    _, _, dq_mask_spec = _mask_spec(mask, h, q.dtype, block_q, block_k,
                                    kj_innermost=True)
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bb, i, j: (bb, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda bb, i, j: (bb, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda bb, i, j: (bb, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda bb, i, j: (bb, i, 0)),
        pl.BlockSpec((1, 8, block_q), lambda bb, i, j: (bb, 0, i)),
        pl.BlockSpec((1, 8, block_q), lambda bb, i, j: (bb, 0, i)),
        dq_mask_spec,
    ]
    dq3 = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bb, i, j: (bb, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta, mask_in)

    return (dq3.reshape(b, h, tq, d), dk3.reshape(b, h, tk, d),
            dv3.reshape(b, h, tk, d))


def _xla_attention(q, k, v, mask, scale, causal):
    prec = _dot_precision(q.dtype)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32,
                        precision=prec) * scale
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        logits = jnp.where(cm, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                      precision=prec)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, mask, scale, causal, block_q, block_k, interpret):
    out, _ = _pallas_forward(q, k, v, mask, scale, causal, block_q, block_k,
                             interpret)
    return out


def _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k, interpret):
    out, lse = _pallas_forward(q, k, v, mask, scale, causal, block_q,
                               block_k, interpret)
    return out, (q, k, v, mask, out, lse)


def _xla_dmask(q, k, v, mask, out, lse, g, scale, causal):
    """Mask cotangent via the straight softmax-backward formula. This DOES
    materialize (B,H,Tq,Tk) — but it is emitted as a standalone expression,
    so when the mask grad is unused (padding masks, the BERT/ERNIE case)
    XLA dead-code-eliminates it and only the Pallas kernels remain."""
    prec = _dot_precision(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=prec) * scale
    s = s + mask.astype(jnp.float32)
    p = jnp.exp(s - lse[..., None])
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        p = jnp.where(jnp.tril(jnp.ones((tq, tk), bool), tk - tq), p, 0.0)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g.astype(jnp.float32),
                    v.astype(jnp.float32), precision=prec)
    ds = p * (dp - delta[..., None])
    reduce_axes = tuple(ax for ax in range(4)
                        if mask.shape[ax] == 1 and ds.shape[ax] > 1)
    return jnp.sum(ds, axis=reduce_axes, keepdims=True).astype(mask.dtype)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, mask, out, lse = res
    # Pallas backward: recompute p from (lse, delta) residuals with the
    # mask applied in-kernel — the (T,T) matrix never touches HBM for
    # dq/dk/dv in either direction
    dq, dk, dv = _pallas_backward(q, k, v, mask, out, lse, g, scale,
                                  causal, block_q, block_k, interpret)
    if mask is None:
        return dq, dk, dv, None
    dmask = _xla_dmask(q, k, v, mask, out, lse, g, scale, causal)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def _env_block(name, default=128):
    """Parse a block-size override; '' counts as unset (same contract as
    PADDLE_TPU_PALLAS_INTERPRET) and junk/too-small values fall back to
    the default LOUDLY — a bad tuning knob must not silently route every
    attention call to the XLA fallback via the auto-path try/except."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        val = -1
    # must be a power of two >= 128: anything else either trips Mosaic's
    # 128-lane block alignment or gets halved down by the divisibility
    # loop until the size guards route EVERY call to the XLA fallback
    if val < 128 or val & (val - 1):
        import warnings
        warnings.warn("%s=%r is not a power-of-two block size >= 128; "
                      "using %d" % (name, raw, default))
        return default
    return val


def flash_attention(q, k, v, mask=None, scale=1.0, causal=False,
                    block_q=None, block_k=None, interpret=None):
    """Flash attention entry. q,k,v: (B,H,T,D). Falls back to interpret
    mode off-TPU so tests exercise the same kernel, and to plain fused XLA
    attention when shapes are too small to tile.

    Block sizes default to 128x128; PADDLE_TPU_FLASH_BLOCK_Q/_K override
    fleet-wide (apply the winner of `bench.py flashtune`)."""
    if block_q is None:
        block_q = _env_block("PADDLE_TPU_FLASH_BLOCK_Q")
    if block_k is None:
        block_k = _env_block("PADDLE_TPU_FLASH_BLOCK_K")
    if interpret is None:
        env = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")
        if env is not None:
            interpret = env not in ("0", "false", "")
        else:
            # Decide from the EFFECTIVE default device, not the process-wide
            # backend list: jax.default_backend() reports "tpu" whenever a
            # chip is attached, even while a jax.default_device(cpu) pin is
            # routing every computation (including this one) to CPU.
            pinned = getattr(jax.config, "jax_default_device", None)
            if pinned is None:
                platform = jax.default_backend()
            elif isinstance(pinned, str):
                platform = pinned
            else:
                platform = getattr(pinned, "platform", None)
            interpret = platform not in ("tpu", "axon")
    tq, tk = q.shape[2], k.shape[2]
    if causal and tq > tk:
        # rows i < tq - tk see no keys at all; only the XLA reference
        # defines that edge (uniform over all-masked logits)
        return _xla_attention(q, k, v, mask, scale, causal)
    bq, bk = min(block_q, tq), min(block_k, tk)
    while tq % bq:
        bq //= 2
    while tk % bk:
        bk //= 2
    if bq < 8 or bk < 8 or q.shape[-1] % 8:
        return _xla_attention(q, k, v, mask, scale, causal)
    if not interpret and (bq < 128 or bk < 128):
        # Mosaic wants the last-two block dims 128-lane aligned (the lse
        # block puts block_q on the lane dim); sub-128 tiles are only
        # exercised in interpret mode — on device route them to XLA.
        return _xla_attention(q, k, v, mask, scale, causal)
    return _flash(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                  None if mask is None else jnp.asarray(mask),
                  scale, causal, bq, bk, interpret)
