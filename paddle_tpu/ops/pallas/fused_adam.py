"""Fused Adam update (Pallas TPU).

The per-parameter optimizer sweep: `optimizer_ops._adam` emits a chain
of ~10 elementwise XLA ops per parameter (two moment EMAs, sqrt, div,
subtract, three dtype casts). This kernel does the whole
read-modify-write — m/v/param in, m/v/param out — in ONE pass per
parameter tile, so each tensor is streamed through VMEM exactly once
per step instead of once per intermediate (the tensor-processing-
primitives argument from PAPERS.md applied to the update sweep).

Layout: the parameter is flattened, zero-padded to a (rows, 128) lane
layout and tiled over row blocks; the bias-corrected learning rate
``lr_t = lr * sqrt(1 - b2^t) / (1 - b1^t)`` is a traced (1, 1) scalar
input (beta powers update outside — they are O(1)). Math is f32 like
the XLA kernel: bf16 params round-trip through f32, moments stay f32.

On CPU the kernel runs in interpret mode (tier-1 exercises the real
kernel logic); `fused_adam` returns None when the parameter is too
small to tile, and the caller keeps the XLA chain.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .. import pallas_dispatch as pd

_LANES = 128


def _adam_kernel(lr_ref, p_ref, g_ref, m1_ref, m2_ref,
                 pn_ref, m1n_ref, m2n_ref, *, beta1, beta2, eps):
    lr_t = lr_ref[0, 0]
    g = g_ref[...].astype(jnp.float32)
    m1n = beta1 * m1_ref[...] + (1.0 - beta1) * g
    m2n = beta2 * m2_ref[...] + (1.0 - beta2) * g * g
    pn = p_ref[...].astype(jnp.float32) - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    pn_ref[...] = pn.astype(pn_ref.dtype)
    m1n_ref[...] = m1n
    m2n_ref[...] = m2n


def _to_lanes(x, rows, dtype):
    """Flatten to (rows, 128) with zero padding (padded cells update to
    zero under Adam-from-zero-state and are sliced off anyway)."""
    flat = x.reshape(-1).astype(dtype)
    pad = rows * _LANES - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    return flat.reshape(rows, _LANES)


def fused_adam(p, g, m1, m2, lr_t, beta1=0.9, beta2=0.999, eps=1e-8,
               block_rows=256, interpret=None):
    """One-pass Adam: returns (p_new, m1_new, m2_new) with p_new in
    p.dtype and f32 moments, or None when the parameter is too small to
    tile (< one (8, 128) f32 tile — the XLA chain is cheaper there).
    `lr_t` is the bias-corrected scalar learning rate (traced)."""
    if interpret is None:
        interpret = pd.default_interpret()
    n = int(p.size)
    rows = -(-n // _LANES)                      # ceil
    if rows < 8:
        return None
    # pad rows to a multiple of 8 first (f32 sublane tile), then to the
    # block multiple, so compiled blocks are always (8k, 128)-aligned;
    # padded cells update to zero and are sliced off below
    rows = -(-rows // 8) * 8
    br = min(block_rows, rows)
    if not interpret and br % 8:
        return None
    rows_p = -(-rows // br) * br
    p2 = _to_lanes(p, rows_p, p.dtype)
    g2 = _to_lanes(g, rows_p, jnp.float32)
    m12 = _to_lanes(m1, rows_p, jnp.float32)
    m22 = _to_lanes(m2, rows_p, jnp.float32)
    lr2 = jnp.asarray(lr_t, jnp.float32).reshape(1, 1)
    blk = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    pn, m1n, m2n = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=float(beta1),
                          beta2=float(beta2), eps=float(eps)),
        grid=(rows_p // br,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0)),
                  blk, blk, blk, blk],
        out_specs=[blk, blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, _LANES), p.dtype),
            jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32),
        ],
        interpret=bool(interpret),
    )(lr2, p2, g2, m12, m22)

    def _back(x, dtype):
        return x.reshape(-1)[:n].reshape(p.shape).astype(dtype)

    return (_back(pn, p.dtype), _back(m1n, jnp.float32),
            _back(m2n, jnp.float32))
