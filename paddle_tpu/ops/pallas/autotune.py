"""Persistent autotuning harness for the Pallas kernel library.

TVM-style per-shape tuning (PAPERS.md): a hand-fused blockwise kernel
only wins when its block/layout config matches the (op, shape, dtype,
topology, backend) it runs on, so the sweep and the kernels ship
together. For each kernel this module:

  1. enumerates its candidate block configs (``CANDIDATES``),
  2. times fwd+bwd of each candidate with the bounded-probe discipline
     bench.py uses (compile once, best-of-k timed calls, a per-candidate
     wall deadline so one pathological config can't eat the sweep),
  3. times the pure-XLA baseline the op registry would otherwise lower,
  4. persists the winner in a JSON cache keyed like the executor's step
     cache (op | shape | dtype | mesh axes | backend —
     ``pallas_dispatch.cache_key``). When the best Pallas candidate
     LOSES to XLA the entry records ``impl: "xla"`` and trace-time
     dispatch routes the op back to the XLA lowering.

At trace time `CompiledProgram` loads the cache (``BuildStrategy.
pallas_tune_cache``) into the dispatch scope; kernels consult it via
``pallas_dispatch.choose``. `tools/autotune.py` is the CLI; its
``--dry-run`` sweeps tiny shapes in interpret mode on CPU so tier-1
exercises the harness itself.

jax imports stay inside functions: loading the cache API must not drag
the kernel modules in.
"""
import json
import os
import time

from .. import pallas_dispatch as pd

DEFAULT_CACHE_ENV = "PADDLE_TPU_PALLAS_TUNE_CACHE"

#: candidate block configs per op — kwargs of the kernel entry points
CANDIDATES = {
    "softmax_with_cross_entropy": [
        {"block_t": bt, "block_v": bv}
        for bt in (128, 256) for bv in (256, 512, 1024)],
    "adam": [{"block_rows": r} for r in (64, 128, 256, 512)],
    # >= 128 rows per tile: the (8, block_rows) residual layout puts
    # block_rows on the lane dim, and compiled Mosaic wants it aligned
    "layer_norm": [{"block_rows": r} for r in (128, 256, 512)],
}

#: interpret-mode candidates for --dry-run / tier-1 (tiny tiles)
DRY_CANDIDATES = {
    "softmax_with_cross_entropy": [
        {"block_t": 8, "block_v": 64}, {"block_t": 16, "block_v": 128}],
    "adam": [{"block_rows": 8}, {"block_rows": 16}],
    "layer_norm": [{"block_rows": 8}, {"block_rows": 16}],
}

DRY_SHAPES = {
    "softmax_with_cross_entropy": (32, 128),
    "adam": (2048,),
    "layer_norm": (32, 128),
}

#: real-chip default sweep shapes (the ERNIE-base headline geometry)
DEFAULT_SHAPES = {
    "softmax_with_cross_entropy": (2560, 32768),
    "adam": (1024 * 1024,),
    "layer_norm": (16384, 768),
}


def default_cache_path():
    env = os.environ.get(DEFAULT_CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "pallas_autotune.json")


class AutotuneCache(object):
    """JSON-file persistence of sweep winners. Schema: one top-level
    dict ``{key: entry}`` where key is ``pallas_dispatch.cache_key`` and
    entry is ``{"impl": "pallas"|"xla", "config": {...}, "pallas_s":
    float, "xla_s": float, ...}``. Loads lazily, writes atomically
    (tmp + rename), tolerates a missing/corrupt file (treated empty —
    a torn write must not brick trace time)."""

    def __init__(self, path=None):
        self.path = path or default_cache_path()
        self._data = None
        self._dirty = False
        self._loaded_stat = None

    def _stat(self):
        try:
            st = os.stat(self.path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def load(self):
        """Parsed cache contents, re-read when the file changed on disk
        (a re-run of tools/autotune.py must be visible to a live
        process) — unless this object holds unsaved put()s."""
        st = self._stat()
        if self._data is None or (not self._dirty and
                                  st != self._loaded_stat):
            try:
                with open(self.path) as f:
                    data = json.load(f)
                self._data = data if isinstance(data, dict) else {}
            except (OSError, ValueError):
                self._data = {}
            self._loaded_stat = st
        return self._data

    def lookup(self, key):
        return self.load().get(key)

    def put(self, key, entry):
        self.load()[key] = entry
        self._dirty = True

    def save(self):
        data = self.load()
        d = os.path.dirname(os.path.abspath(self.path))
        if d and not os.path.isdir(d):
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)
        self._dirty = False
        self._loaded_stat = self._stat()
        return self.path

    def __len__(self):
        return len(self.load())


# ---------------------------------------------------------------------------
# bounded-probe timing (bench.py discipline)
# ---------------------------------------------------------------------------

def _time_fn(fn, probes, deadline_s):
    """Best-of-`probes` wall time of fn() (block_until_ready'd), after
    one untimed warmup call that pays the compile. Returns None when the
    candidate exceeds its wall deadline or fails to run."""
    import jax
    t_start = time.perf_counter()
    try:
        jax.block_until_ready(fn())      # compile + warm
    except Exception:
        return None
    best = None
    for _ in range(max(1, probes)):
        if time.perf_counter() - t_start > deadline_s:
            break
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _workloads(op, shape, dtype, interpret):
    """(pallas_fn(config) -> closure, xla_closure) for one op/shape: the
    timed unit is one fwd+bwd (fwd-only for adam — it has no vjp) jitted
    step, matching what the op contributes to the train step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    if op == "softmax_with_cross_entropy":
        from .blockwise_ce import blockwise_softmax_cross_entropy
        t, v = shape
        logits = jnp.asarray(rng.randn(t, v), dtype)
        labels = jnp.asarray(rng.randint(0, v, (t,)), jnp.int32)

        def ref_loss(lg):
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            picked = jnp.take_along_axis(logp, labels[:, None], axis=1)
            return jnp.sum(-picked)

        def make(config):
            cfg = dict(config or {})

            def loss(lg):
                out = blockwise_softmax_cross_entropy(
                    lg, labels, interpret=interpret, **cfg)
                if out is None:
                    raise ValueError("shape does not tile under %r" % cfg)
                return jnp.sum(out)
            g = jax.jit(jax.grad(loss))
            return lambda: g(logits)
        xla_g = jax.jit(jax.grad(ref_loss))
        return make, lambda: xla_g(logits)

    if op == "adam":
        from .fused_adam import fused_adam
        n = int(np.prod(shape))
        p = jnp.asarray(rng.randn(n), dtype)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        m1 = jnp.zeros((n,), jnp.float32)
        m2 = jnp.zeros((n,), jnp.float32)
        lr_t = jnp.float32(0.01)

        def make(config):
            cfg = dict(config or {})

            def step(p, g, m1, m2):
                out = fused_adam(p, g, m1, m2, lr_t,
                                 interpret=interpret, **cfg)
                if out is None:
                    raise ValueError("shape does not tile under %r" % cfg)
                return out
            j = jax.jit(step)
            return lambda: j(p, g, m1, m2)

        def xla_step(p, g, m1, m2):
            m1n = 0.9 * m1 + 0.1 * g
            m2n = 0.999 * m2 + 0.001 * g * g
            return (p - lr_t * m1n / (jnp.sqrt(m2n) + 1e-8), m1n, m2n)
        xj = jax.jit(xla_step)
        return make, lambda: xj(p, g, m1, m2)

    if op == "layer_norm":
        from .layer_norm import fused_layer_norm
        r, c = shape
        x = jnp.asarray(rng.randn(r, c), dtype)
        sc = jnp.asarray(rng.randn(c), jnp.float32)
        bi = jnp.asarray(rng.randn(c), jnp.float32)

        def ref(x, sc, bi):
            m = jnp.mean(x.astype(jnp.float32), -1, keepdims=True)
            v = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
            y = (x - m) * jax.lax.rsqrt(v + 1e-5) * sc[None, :] + bi
            return jnp.sum(y)

        def make(config):
            cfg = dict(config or {})

            def loss(x, sc, bi):
                y = fused_layer_norm(x, sc, bi,
                                     interpret=interpret, **cfg)
                if y is None:
                    raise ValueError("shape does not tile under %r" % cfg)
                return jnp.sum(y)
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            return lambda: g(x, sc, bi)
        xg = jax.jit(jax.grad(ref, argnums=(0, 1, 2)))
        return make, lambda: xg(x, sc, bi)

    raise ValueError("no autotune workload for op %r" % op)


def autotune_op(op, shape, dtype="float32", probes=3, interpret=None,
                cache=None, candidates=None, mesh_axes=None,
                backend=None, candidate_deadline_s=120.0):
    """Sweep one (op, shape, dtype): time every candidate and the XLA
    baseline, persist the winner (or the XLA fallback verdict) under
    the executor-style cache key, and return the summary dict."""
    import jax
    if interpret is None:
        interpret = pd.default_interpret()
    if backend is None:
        backend = jax.default_backend()
    if cache is None:
        cache = AutotuneCache()
    if candidates is None:
        candidates = (DRY_CANDIDATES if interpret else CANDIDATES)[op]
    make, xla_fn = _workloads(op, tuple(shape), dtype, interpret)
    results = {}
    best_cfg, best_s = None, None
    for config in candidates:
        tag = ",".join("%s=%s" % kv for kv in sorted(config.items()))
        dt = _time_fn(make(config), probes, candidate_deadline_s)
        results[tag] = round(dt, 6) if dt is not None else "failed"
        if dt is not None and (best_s is None or dt < best_s):
            best_cfg, best_s = dict(config), dt
    xla_s = _time_fn(xla_fn, probes, candidate_deadline_s)
    # Fall back to XLA when the best Pallas candidate loses (or none
    # ran). Interpret-mode sweeps NEVER conclude "xla" — not even when
    # every candidate failed: the interpreter's wall time says nothing
    # about Mosaic, so off-chip runs only pick among Pallas configs (a
    # config-less "pallas" entry means kernel defaults, whose own size
    # guards still fall back dynamically at trace time).
    pallas_wins = interpret or (best_s is not None and
                                (xla_s is None or best_s <= xla_s))
    key = pd.cache_key(op, shape, dtype, mesh_axes, backend)
    entry = {
        "impl": "pallas" if pallas_wins else "xla",
        "config": best_cfg if pallas_wins else None,
        "pallas_s": round(best_s, 6) if best_s is not None else None,
        "xla_s": round(xla_s, 6) if xla_s is not None else None,
        "probes": probes,
        "interpret": bool(interpret),
        "backend": backend,
    }
    cache.put(key, entry)
    cache.save()
    return {"op": op, "key": key, "entry": entry, "results": results,
            "cache": cache.path}
