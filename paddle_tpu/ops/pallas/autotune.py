"""Persistent autotuning harness for the Pallas kernel library.

TVM-style per-shape tuning (PAPERS.md): a hand-fused blockwise kernel
only wins when its block/layout config matches the (op, shape, dtype,
topology, backend) it runs on, so the sweep and the kernels ship
together. For each kernel this module:

  1. enumerates its candidate block configs (``CANDIDATES``),
  2. optionally PRUNES them through the analytic+fitted cost model
     (``costmodel.CostModel`` fit over every measured row already
     banked in the cache): ``top_k=K`` measures only the K
     best-predicted candidates instead of the full space — the
     TVM-style sweep compression ISSUE 13 exists for,
  3. times fwd+bwd of each surviving candidate with the bounded-probe
     discipline bench.py uses (compile once, best-of-k timed calls, a
     per-candidate wall deadline so one pathological config can't eat
     the sweep),
  4. times the pure-XLA baseline the op registry would otherwise lower,
  5. persists the winner AND every candidate's measured seconds in a
     versioned JSON cache keyed like the executor's step cache
     (``pallas_dispatch.cache_key``) — the per-candidate rows are what
     future cost-model fits learn from. When the best Pallas candidate
     LOSES to XLA the entry records ``impl: "xla"`` and trace-time
     dispatch routes the op back to the XLA lowering.

At trace time `CompiledProgram` loads the cache (``BuildStrategy.
pallas_tune_cache``, or the in-repo banked ``tools/tuned/{backend}.
json`` under ``kernel_policy="auto"``) into the dispatch scope; kernels
consult it via ``pallas_dispatch.choose``, and a cache MISS resolves to
a cost-model-predicted config instead of the hardcoded default.
`tools/autotune.py` is the CLI; its ``--dry-run`` sweeps tiny shapes in
interpret mode on CPU so tier-1 exercises the harness itself, and its
``--bank BACKEND`` refreshes the committed per-backend cache that
`tools/tunecheck.py` validates in tier-1.

jax imports stay inside functions: loading the cache API must not drag
the kernel modules in.
"""
import hashlib
import json
import os
import time

from . import costmodel as cm
from .. import pallas_dispatch as pd

DEFAULT_CACHE_ENV = "PADDLE_TPU_PALLAS_TUNE_CACHE"

#: banked-cache JSON format (AutotuneCache envelope): bump on schema
#: breaks. Unknown versions load EMPTY (trace time never bricks) and
#: fail tools/tunecheck.py loudly.
FORMAT_VERSION = 1

#: candidate block configs per op — kwargs of the kernel entry points.
#: Deliberately WIDE (TVM-style): the cost model prunes this space to
#: ``top_k`` measured candidates, so enumerating generously costs
#: prediction microseconds, not sweep minutes. Degenerate fits (a
#: block larger than its axis halves until it divides) mean some
#: candidates coincide on small shapes — the ranking dedups nothing,
#: the measurement loop just sees equal times.
CANDIDATES = {
    "softmax_with_cross_entropy": [
        {"block_t": bt, "block_v": bv}
        for bt in (128, 256, 512, 1024)
        for bv in (256, 512, 1024, 2048, 4096)],
    "adam": [{"block_rows": r}
             for r in (32, 64, 128, 256, 512, 1024, 2048, 4096,
                       8192, 16384)],
    # >= 128 rows per tile: the (8, block_rows) residual layout puts
    # block_rows on the lane dim, and compiled Mosaic wants it aligned
    "layer_norm": [{"block_rows": r}
                   for r in (128, 256, 384, 512, 768, 1024, 1536,
                             2048, 3072, 4096)],
    "fused_mlm_head_loss": [
        {"block_t": bt, "block_v": bv}
        for bt in (128, 256, 512, 1024)
        for bv in (256, 512, 1024, 2048)],
}

#: interpret-mode candidates for --dry-run / tier-1 (tiny tiles)
DRY_CANDIDATES = {
    "softmax_with_cross_entropy": [
        {"block_t": 8, "block_v": 64}, {"block_t": 16, "block_v": 128}],
    "adam": [{"block_rows": 8}, {"block_rows": 16}],
    "layer_norm": [{"block_rows": 8}, {"block_rows": 16}],
    "fused_mlm_head_loss": [
        {"block_t": 8, "block_v": 64}, {"block_t": 16, "block_v": 64}],
}

DRY_SHAPES = {
    "softmax_with_cross_entropy": (32, 128),
    "adam": (2048,),
    "layer_norm": (32, 128),
    "fused_mlm_head_loss": (32, 256),
}

#: real-chip default sweep shapes (the ERNIE-base headline geometry)
DEFAULT_SHAPES = {
    "softmax_with_cross_entropy": (2560, 32768),
    "adam": (1024 * 1024,),
    "layer_norm": (16384, 768),
    "fused_mlm_head_loss": (2560, 32768),
}

#: the cpu-interpret BANKING grid (tools/autotune.py --bank
#: cpu-interpret -> tools/tuned/cpu-interpret.json): several shapes
#: per family so the cost-model fit has cross-shape signal, candidate
#: tiles kept small enough that the interpreter's unrolled grids stay
#: tractable in CI. Real backends bank DEFAULT_SHAPES x CANDIDATES.
BANK_CANDIDATES = {
    "softmax_with_cross_entropy": [
        {"block_t": bt, "block_v": bv}
        for bt in (8, 16, 32) for bv in (32, 64, 128)],
    "adam": [{"block_rows": r} for r in (8, 16, 32, 64, 128)],
    "layer_norm": [{"block_rows": r} for r in (8, 16, 32, 64)],
    "fused_mlm_head_loss": [
        {"block_t": bt, "block_v": bv}
        for bt in (8, 16) for bv in (64, 128)],
}

BANK_SHAPES = {
    "softmax_with_cross_entropy": [(32, 128), (64, 128), (32, 256),
                                   (64, 256)],
    "adam": [(2048,), (8192,), (65536,)],
    "layer_norm": [(32, 128), (128, 256), (256, 512)],
    "fused_mlm_head_loss": [(32, 256), (64, 256), (32, 512)],
}


def candidates_for(op, interpret):
    """The candidate space trace-time prediction and banking rank over:
    the interpreter's small-tile grid off-chip, the full Mosaic grid on
    it."""
    return (BANK_CANDIDATES if interpret else CANDIDATES).get(op, [])


_SEL_FP = None


def selection_fingerprint():
    """Identity of the kernel-selection machinery (cost-model version +
    the full candidate space): joins the executor compile-cache token so
    changing either re-lowers instead of reusing a stale executable."""
    global _SEL_FP
    if _SEL_FP is None:
        h = hashlib.sha1()
        h.update(b"model-v%d|" % cm.MODEL_VERSION)
        h.update(json.dumps({"chip": CANDIDATES,
                             "interpret": BANK_CANDIDATES},
                            sort_keys=True).encode())
        _SEL_FP = h.hexdigest()[:12]
    return _SEL_FP


def default_cache_path():
    env = os.environ.get(DEFAULT_CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "pallas_autotune.json")


def tuned_dir():
    """The in-repo banked-cache directory (``tools/tuned/``)."""
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))), "tools", "tuned")


def banked_cache_name(backend):
    """Backend platform -> banked-cache basename: CPU verdicts are
    interpreter timings (Mosaic never ran), so the file says so."""
    return "cpu-interpret" if backend == "cpu" else str(backend)


def banked_cache_path(backend):
    """Path of the committed per-backend tuned cache CI/bench/serving
    replicas share (``tools/tuned/{backend}.json``)."""
    return os.path.join(tuned_dir(), banked_cache_name(backend) + ".json")


class AutotuneCache(object):
    """Versioned JSON-file persistence of sweep results.

    On-disk envelope (``FORMAT_VERSION``):
    ``{"format_version": 1, ...meta..., "entries": {key: entry}}``
    where key is ``pallas_dispatch.cache_key`` and entry is
    ``{"impl": "pallas"|"xla"|"pallas_q", "config": {...}, "pallas_s":
    float, "xla_s": float, "results": {tag: seconds}, ...}`` — the
    per-candidate ``results`` rows feed cost-model fits. Legacy flat
    ``{key: entry}`` files still load (read-only compat); every save
    writes the envelope.

    Concurrency contract: loads lazily and re-reads on file-stat
    change; :meth:`save` is a cross-process MERGE — it re-reads the
    file fresh, overlays only this object's unsaved puts and replaces
    atomically (tmp + fsync + ``os.replace``), so concurrent autotune
    runs and a serving replica sharing one cache file can neither tear
    the JSON nor erase each other's keys. A missing/corrupt/
    future-versioned file is treated empty (a torn write must not
    brick trace time; tunecheck is where it fails loudly)."""

    def __init__(self, path=None, meta=None):
        self.path = path or default_cache_path()
        self._data = None
        self.meta = dict(meta or {})
        self._dirty = {}          # unsaved put()s: key -> entry
        self._loaded_stat = None

    @staticmethod
    def parse_blob(raw):
        """(entries, meta) from a parsed JSON blob — versioned envelope
        or legacy flat dict. Unknown format versions yield empty
        entries with the meta preserved (so tunecheck can report WHAT
        it refused)."""
        if not isinstance(raw, dict):
            return {}, {}
        if "format_version" in raw:
            meta = {k: v for k, v in raw.items() if k != "entries"}
            try:
                ver = int(raw["format_version"])
            except (TypeError, ValueError):
                ver = None
            entries = raw.get("entries")
            if ver != FORMAT_VERSION or not isinstance(entries, dict):
                return {}, meta
            return dict(entries), meta
        return dict(raw), {}

    def _stat(self):
        try:
            st = os.stat(self.path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _read_disk(self):
        try:
            with open(self.path) as f:
                return self.parse_blob(json.load(f))
        except (OSError, ValueError):
            return {}, {}

    def load(self):
        """Parsed cache entries, re-read when the file changed on disk
        (a re-run of tools/autotune.py must be visible to a live
        process) — unless this object holds unsaved put()s."""
        st = self._stat()
        if self._data is None or (not self._dirty and
                                  st != self._loaded_stat):
            self._data, file_meta = self._read_disk()
            if file_meta:
                merged = dict(file_meta)
                merged.update(self.meta)
                self.meta = merged
            self._loaded_stat = st
        return self._data

    def lookup(self, key):
        return self.load().get(key)

    def put(self, key, entry):
        self.load()[key] = entry
        self._dirty[key] = entry

    def save(self):
        self.load()
        d = os.path.dirname(os.path.abspath(self.path))
        if d and not os.path.isdir(d):
            os.makedirs(d, exist_ok=True)
        # cross-process merge: overlay ONLY this object's unsaved puts
        # onto a fresh read, so two sweeps interleaving save() keep
        # both key sets (last-writer-wins per key, never per file)
        disk_entries, disk_meta = self._read_disk()
        merged = dict(disk_entries)
        if self._dirty:
            merged.update(self._dirty)
        else:
            merged.update(self._data or {})
        meta = dict(disk_meta)
        meta.update(self.meta)
        meta.pop("format_version", None)
        blob = {"format_version": FORMAT_VERSION}
        blob.update(sorted(meta.items()))
        blob["entries"] = merged
        tmp = self.path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(blob, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._data = merged
        self._dirty = {}
        self._loaded_stat = self._stat()
        return self.path

    def __len__(self):
        return len(self.load())


def fit_cost_model(cache=None, interpret=None):
    """A :class:`costmodel.CostModel` over this module's candidate
    grids, fit from every measured row ``cache`` banked (analytic-only
    when the cache is empty/absent). ``interpret`` selects which grid
    the model ranks by default (None = the dispatch default)."""
    if interpret is None:
        interpret = pd.default_interpret()
    model = cm.CostModel(candidates={
        op: candidates_for(op, interpret) for op in CANDIDATES})
    if cache is not None:
        model.fit_cache(cache)
    return model


# ---------------------------------------------------------------------------
# bounded-probe timing (bench.py discipline)
# ---------------------------------------------------------------------------

def _time_fn(fn, probes, deadline_s):
    """Best-of-`probes` wall time of fn() (block_until_ready'd), after
    one untimed warmup call that pays the compile. Returns None when the
    candidate exceeds its wall deadline or fails to run."""
    import jax
    t_start = time.perf_counter()
    try:
        jax.block_until_ready(fn())      # compile + warm
    except Exception:
        return None
    best = None
    for _ in range(max(1, probes)):
        if time.perf_counter() - t_start > deadline_s:
            break
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _workloads(op, shape, dtype, interpret):
    """(pallas_fn(config) -> closure, xla_closure) for one op/shape: the
    timed unit is one fwd+bwd (fwd-only for adam — it has no vjp) jitted
    step, matching what the op contributes to the train step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    if op == "softmax_with_cross_entropy":
        from .blockwise_ce import blockwise_softmax_cross_entropy
        t, v = shape
        logits = jnp.asarray(rng.randn(t, v), dtype)
        labels = jnp.asarray(rng.randint(0, v, (t,)), jnp.int32)

        def ref_loss(lg):
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            picked = jnp.take_along_axis(logp, labels[:, None], axis=1)
            return jnp.sum(-picked)

        def make(config):
            cfg = dict(config or {})

            def loss(lg):
                out = blockwise_softmax_cross_entropy(
                    lg, labels, interpret=interpret, **cfg)
                if out is None:
                    raise ValueError("shape does not tile under %r" % cfg)
                return jnp.sum(out)
            g = jax.jit(jax.grad(loss))
            return lambda: g(logits)
        xla_g = jax.jit(jax.grad(ref_loss))
        return make, lambda: xla_g(logits)

    if op == "fused_mlm_head_loss":
        from .blockwise_ce import fused_mlm_head_loss
        t, v = shape
        d = cm.HEAD_D["interpret" if interpret else "compiled"]
        hidden = jnp.asarray(rng.randn(t, d) * 0.3, dtype)
        weight = jnp.asarray(rng.randn(d, v) * 0.2, dtype)
        bias = jnp.asarray(rng.randn(v).astype(np.float32) * 0.1)
        labels = jnp.asarray(rng.randint(0, v, (t,)), jnp.int32)

        def ref_loss(h, w, b):
            logits = jnp.matmul(h, w, preferred_element_type=jnp.float32)
            logits = logits.astype(jnp.float32) + b[None, :]
            logp = jax.nn.log_softmax(logits, axis=-1)
            picked = jnp.take_along_axis(logp, labels[:, None], axis=1)
            return jnp.sum(-picked)

        def make(config):
            cfg = dict(config or {})

            def loss(h, w, b):
                out = fused_mlm_head_loss(h, w, labels, bias=b,
                                          interpret=interpret, **cfg)
                if out is None:
                    raise ValueError("shape does not tile under %r" % cfg)
                return jnp.sum(out)
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            return lambda: g(hidden, weight, bias)
        xla_g = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))
        return make, lambda: xla_g(hidden, weight, bias)

    if op == "adam":
        from .fused_adam import fused_adam
        n = int(np.prod(shape))
        p = jnp.asarray(rng.randn(n), dtype)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        m1 = jnp.zeros((n,), jnp.float32)
        m2 = jnp.zeros((n,), jnp.float32)
        lr_t = jnp.float32(0.01)

        def make(config):
            cfg = dict(config or {})

            def step(p, g, m1, m2):
                out = fused_adam(p, g, m1, m2, lr_t,
                                 interpret=interpret, **cfg)
                if out is None:
                    raise ValueError("shape does not tile under %r" % cfg)
                return out
            j = jax.jit(step)
            return lambda: j(p, g, m1, m2)

        def xla_step(p, g, m1, m2):
            m1n = 0.9 * m1 + 0.1 * g
            m2n = 0.999 * m2 + 0.001 * g * g
            return (p - lr_t * m1n / (jnp.sqrt(m2n) + 1e-8), m1n, m2n)
        xj = jax.jit(xla_step)
        return make, lambda: xj(p, g, m1, m2)

    if op == "layer_norm":
        from .layer_norm import fused_layer_norm
        r, c = shape
        x = jnp.asarray(rng.randn(r, c), dtype)
        sc = jnp.asarray(rng.randn(c), jnp.float32)
        bi = jnp.asarray(rng.randn(c), jnp.float32)

        def ref(x, sc, bi):
            m = jnp.mean(x.astype(jnp.float32), -1, keepdims=True)
            v = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
            y = (x - m) * jax.lax.rsqrt(v + 1e-5) * sc[None, :] + bi
            return jnp.sum(y)

        def make(config):
            cfg = dict(config or {})

            def loss(x, sc, bi):
                y = fused_layer_norm(x, sc, bi,
                                     interpret=interpret, **cfg)
                if y is None:
                    raise ValueError("shape does not tile under %r" % cfg)
                return jnp.sum(y)
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            return lambda: g(x, sc, bi)
        xg = jax.jit(jax.grad(ref, argnums=(0, 1, 2)))
        return make, lambda: xg(x, sc, bi)

    raise ValueError("no autotune workload for op %r" % op)


def autotune_op(op, shape, dtype="float32", probes=3, interpret=None,
                cache=None, candidates=None, mesh_axes=None,
                backend=None, candidate_deadline_s=120.0, top_k=None,
                cost_model=None, cost_model_only=False):
    """Tune one (op, shape, dtype): rank every candidate through the
    cost model, measure the ``top_k`` best-predicted ones (None =
    exhaustive legacy sweep) plus the XLA baseline, persist the winner
    AND the per-candidate rows under the executor-style cache key, and
    return the summary dict.

    ``cost_model_only=True`` measures NOTHING: the top-ranked predicted
    config is banked directly (entry ``source: "costmodel"``) — the
    zero-probe mode for fleets that need a config for a new shape
    before any sweep window opens. ``cost_model`` injects a pre-fitted
    model (default: fit from ``cache``'s own banked rows).

    Per-candidate summary rows carry predicted AND measured seconds:
    ``{tag: {"predicted_s", "source", "measured_s", "status"}}`` with
    status "ok" | "failed" | "pruned"."""
    import jax
    if interpret is None:
        interpret = pd.default_interpret()
    if backend is None:
        backend = jax.default_backend()
    if cache is None:
        cache = AutotuneCache()
    if candidates is None:
        candidates = (DRY_CANDIDATES if interpret else CANDIDATES)[op]
    model = cost_model
    if model is None and (top_k or cost_model_only):
        model = fit_cost_model(cache, interpret=interpret)
    results = {}
    predicted = {}
    if model is not None:
        for cfg, sec, src in model.rank(op, tuple(shape), candidates,
                                        backend=backend,
                                        interpret=interpret):
            predicted[cm.config_tag(cfg)] = (sec, src)
    for config in candidates:
        tag = cm.config_tag(config)
        sec_src = predicted.get(tag)
        results[tag] = {
            "predicted_s": round(sec_src[0], 9) if sec_src else None,
            "source": sec_src[1] if sec_src else None,
            "measured_s": None,
            "status": "pruned" if (top_k or cost_model_only) else
                      "pending"}

    key = pd.cache_key(op, shape, dtype, mesh_axes, backend)
    if cost_model_only:
        ranked = model.top_k(op, tuple(shape), candidates, k=1,
                             backend=backend, interpret=interpret)
        pred = {"config": ranked[0][0],
                "predicted_s": ranked[0][1]} if ranked else None
        entry = {
            "impl": "pallas",
            "config": pred["config"] if pred else None,
            "pallas_s": None, "xla_s": None, "probes": 0,
            "interpret": bool(interpret), "backend": backend,
            "predicted_s": round(pred["predicted_s"], 9) if pred
            else None,
            "source": "costmodel",
        }
        cache.put(key, entry)
        cache.save()
        return {"op": op, "key": key, "entry": entry,
                "results": results, "cache": cache.path,
                "candidates_total": len(candidates),
                "candidates_measured": 0, "top_k": top_k}

    if top_k:
        measure = [c for c, _s, _src in model.top_k(
            op, tuple(shape), candidates, k=top_k, backend=backend,
            interpret=interpret)]
        if not measure:
            # nothing in the space tiles this shape: fall back to the
            # exhaustive list so the size guards get to say "failed"
            measure = list(candidates)
    else:
        measure = list(candidates)

    make, xla_fn = _workloads(op, tuple(shape), dtype, interpret)
    best_cfg, best_s = None, None
    measured_rows = {}
    for config in measure:
        tag = cm.config_tag(config)
        dt = _time_fn(make(config), probes, candidate_deadline_s)
        row = results.setdefault(tag, {"predicted_s": None,
                                       "source": None})
        if dt is None:
            row["measured_s"], row["status"] = None, "failed"
        else:
            row["measured_s"], row["status"] = round(dt, 6), "ok"
            measured_rows[tag] = round(dt, 6)
        if dt is not None and (best_s is None or dt < best_s):
            best_cfg, best_s = dict(config), dt
    for row in results.values():
        if row.get("status") == "pending":
            row["status"] = "failed"
    xla_s = _time_fn(xla_fn, probes, candidate_deadline_s)
    # Fall back to XLA when the best Pallas candidate loses (or none
    # ran). Interpret-mode sweeps NEVER conclude "xla" — not even when
    # every candidate failed: the interpreter's wall time says nothing
    # about Mosaic, so off-chip runs only pick among Pallas configs (a
    # config-less "pallas" entry means kernel defaults, whose own size
    # guards still fall back dynamically at trace time).
    pallas_wins = interpret or (best_s is not None and
                                (xla_s is None or best_s <= xla_s))
    best_pred = predicted.get(cm.config_tag(best_cfg)) if best_cfg \
        else None
    entry = {
        "impl": "pallas" if pallas_wins else "xla",
        "config": best_cfg if pallas_wins else None,
        "pallas_s": round(best_s, 6) if best_s is not None else None,
        "xla_s": round(xla_s, 6) if xla_s is not None else None,
        "probes": probes,
        "interpret": bool(interpret),
        "backend": backend,
        "results": measured_rows,
        "source": "sweep",
    }
    if best_pred is not None:
        entry["predicted_s"] = round(best_pred[0], 9)
    cache.put(key, entry)
    cache.save()
    return {"op": op, "key": key, "entry": entry, "results": results,
            "cache": cache.path, "candidates_total": len(candidates),
            "candidates_measured": len(measure), "top_k": top_k}
