"""Fused LayerNorm forward + backward (Pallas TPU).

The pre/post-attention normalization of every BASELINE transformer
block. Forward computes mean/variance and the normalized output in one
pass over each (block_rows, cols) tile resident in VMEM — XLA's
lowering reads x once for the moments and again for the normalize —
saving the (mean, rstd) residuals per row. Backward is one pass too:
dx via the fused layernorm-backward formula, with dscale/dbias
accumulated across row blocks in VMEM scratch (the grid's sequential
dimension), so no (rows, cols)-sized intermediate beyond the
unavoidable dx.

Layout: 2-D ``x (rows, cols)`` normalized over the last axis; callers
collapse leading dims per begin_norm_axis. Per-row residuals ride the
(8, rows) sublane-padded layout (row 0 real — same convention as
flash_attention's lse). Rows are zero-padded to the block multiple
(padded rows normalize garbage that is sliced off; their zero
cotangents contribute nothing to dscale/dbias). On CPU the kernels run
in interpret mode; `fused_layer_norm` returns None when the shape
cannot tile (compiled Mosaic wants cols 128-aligned) and callers keep
the XLA lowering.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .blockwise_ce import _rows8
from .. import pallas_dispatch as pd


def _ln_fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, rstd_ref,
                   *, eps):
    x = x_ref[...].astype(jnp.float32)              # (BR, C)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (xc * rstd) * scale_ref[0][None, :].astype(jnp.float32) \
        + bias_ref[0][None, :].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = jnp.broadcast_to(mean[:, 0][None, :], mean_ref.shape)
    rstd_ref[...] = jnp.broadcast_to(rstd[:, 0][None, :], rstd_ref.shape)


def _ln_bwd_kernel(x_ref, g_ref, scale_ref, mean_ref, rstd_ref,
                   dx_ref, dscale_ref, dbias_ref, ds_acc, db_acc):
    i = pl.program_id(0)
    n = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        ds_acc[:] = jnp.zeros_like(ds_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    x = x_ref[...].astype(jnp.float32)              # (BR, C)
    g = g_ref[...].astype(jnp.float32)
    mean = mean_ref[0][:, None]                     # (BR, 1)
    rstd = rstd_ref[0][:, None]
    xhat = (x - mean) * rstd
    gs = g * scale_ref[0][None, :].astype(jnp.float32)
    mg = jnp.mean(gs, axis=-1, keepdims=True)
    mgx = jnp.mean(gs * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (gs - mg - xhat * mgx)).astype(dx_ref.dtype)
    ds_acc[:] = ds_acc[:] + jnp.broadcast_to(
        jnp.sum(g * xhat, axis=0, keepdims=True), ds_acc.shape)
    db_acc[:] = db_acc[:] + jnp.broadcast_to(
        jnp.sum(g, axis=0, keepdims=True), db_acc.shape)

    @pl.when(i == n - 1)
    def _fin():
        dscale_ref[...] = ds_acc[:].astype(dscale_ref.dtype)
        dbias_ref[...] = db_acc[:].astype(dbias_ref.dtype)


def _pad_rows(x, rows_p, dtype):
    pad = rows_p - x.shape[0]
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    return x.astype(dtype)


def _ln_call_fwd(x, scale, bias, eps, block_rows, interpret):
    rows, cols = x.shape
    rows_p = -(-rows // block_rows) * block_rows
    x2 = _pad_rows(x, rows_p, x.dtype)
    blk = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    row8 = pl.BlockSpec((8, block_rows), lambda i: (0, i))
    vec = pl.BlockSpec((8, cols), lambda i: (0, 0))
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=float(eps)),
        grid=(rows_p // block_rows,),
        in_specs=[blk, vec, vec],
        out_specs=[blk, row8, row8],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, cols), x.dtype),
            jax.ShapeDtypeStruct((8, rows_p), jnp.float32),
            jax.ShapeDtypeStruct((8, rows_p), jnp.float32),
        ],
        interpret=interpret,
    )(x2, _rows8(scale, jnp.float32), _rows8(bias, jnp.float32))
    return y[:rows], mean[0, :rows], rstd[0, :rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x, scale, bias, eps, block_rows, interpret):
    y, _, _ = _ln_call_fwd(x, scale, bias, eps, block_rows, interpret)
    return y


def _ln_fwd(x, scale, bias, eps, block_rows, interpret):
    y, mean, rstd = _ln_call_fwd(x, scale, bias, eps, block_rows,
                                 interpret)
    return y, (x, scale, bias, mean, rstd)


def _ln_bwd(eps, block_rows, interpret, res, g):
    x, scale, bias, mean, rstd = res
    rows, cols = x.shape
    rows_p = -(-rows // block_rows) * block_rows
    x2 = _pad_rows(x, rows_p, x.dtype)
    g2 = _pad_rows(g, rows_p, g.dtype)
    mean_p = jnp.pad(mean, (0, rows_p - rows))
    rstd_p = jnp.pad(rstd, (0, rows_p - rows))
    blk = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    row8 = pl.BlockSpec((8, block_rows), lambda i: (0, i))
    vec = pl.BlockSpec((8, cols), lambda i: (0, 0))
    dx, ds8, db8 = pl.pallas_call(
        _ln_bwd_kernel,
        grid=(rows_p // block_rows,),
        in_specs=[blk, blk, vec, row8, row8],
        out_specs=[blk, vec, vec],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, cols), x.dtype),
            jax.ShapeDtypeStruct((8, cols), jnp.float32),
            jax.ShapeDtypeStruct((8, cols), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, cols), jnp.float32),
            pltpu.VMEM((8, cols), jnp.float32),
        ],
        interpret=interpret,
    )(x2, g2, _rows8(scale, jnp.float32), _rows8(mean_p, jnp.float32),
      _rows8(rstd_p, jnp.float32))
    return (dx[:rows], ds8[0].astype(scale.dtype),
            db8[0].astype(bias.dtype))


_ln.defvjp(_ln_fwd, _ln_bwd)


def fused_layer_norm(x, scale, bias, eps=1e-5, block_rows=128,
                     interpret=None):
    """Fused LayerNorm over the last axis of 2-D ``x (rows, cols)`` with
    ``scale (cols,)`` / ``bias (cols,)``. Returns y in x.dtype, or None
    when the shape cannot tile (caller keeps the XLA lowering).
    Differentiable wrt x/scale/bias with one-pass Pallas fwd and bwd."""
    if interpret is None:
        interpret = pd.default_interpret()
    rows, cols = x.shape
    br = min(block_rows, max(rows, 1))
    if rows < 1 or cols < 8:
        return None
    # compiled Mosaic wants cols 128-lane aligned AND br a 128-multiple
    # (the (8, block_rows) mean/rstd residuals put br on the lane dim —
    # same constraint as flash_attention's lse): round br down to the
    # alignment, bail to XLA when nothing fits; interpret mode takes
    # any tile
    if not interpret:
        br = (br // 128) * 128
        if cols % 128 or br < 128:
            return None
    br = max(br, 1)
    return _ln(jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias),
               float(eps), int(br), bool(interpret))
