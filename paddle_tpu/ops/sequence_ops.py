"""Sequence op kernels — dense (batch, time, ...) + length-vector design.

Reference parity: paddle/fluid/operators/sequence_ops/* which operate on
ragged LoD tensors. Ragged rows are hostile to XLA's static shapes, so every
op here takes dense (N, T, ...) tensors plus an explicit (N,) length vector
and reproduces the per-sequence semantics with masks/gathers — identical
results on the valid prefix, zeros (or pad_value) beyond it.
"""
import jax
import jax.numpy as jnp

from .registry import register_op


def _lengths(ins, n, t):
    if ins.get("Length"):
        return ins["Length"][0].reshape(-1).astype(jnp.int32)
    return jnp.full((n,), t, jnp.int32)


@register_op("sequence_reverse", nondiff=("Length",))
def _sequence_reverse(ctx, ins, attrs):
    """Reverse each sequence's valid prefix, keep padding in place
    (reference sequence_ops/sequence_reverse_op.h)."""
    x = ins["X"][0]                       # (N, T, ...)
    n, t = x.shape[0], x.shape[1]
    lens = _lengths(ins, n, t)
    pos = jnp.arange(t)[None, :]
    idx = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
    return {"Y": jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)}


@register_op("sequence_erase", nondiff=("X", "Length"), differentiable=False)
def _sequence_erase(ctx, ins, attrs):
    """Remove listed tokens and left-compact each row (reference
    sequence_ops/sequence_erase_op.h). Output keeps the (N, T) shape with
    pad_value in vacated slots; OutLength gives new lengths."""
    x = ins["X"][0]                       # (N, T) int tokens
    n, t = x.shape
    lens = _lengths(ins, n, t)
    tokens = jnp.asarray(list(attrs.get("tokens", [])), x.dtype)
    pad_value = attrs.get("pad_value", 0)
    valid = jnp.arange(t)[None, :] < lens[:, None]
    keep = valid
    if tokens.size:
        keep = valid & ~jnp.isin(x, tokens)
    # stable partition: kept tokens first, original order preserved
    order = jnp.argsort(~keep, axis=1, stable=True)
    gathered = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(jnp.arange(t)[None, :] < new_len[:, None], gathered,
                    jnp.asarray(pad_value, x.dtype))
    return {"Out": out, "OutLength": new_len}


@register_op("sequence_enumerate", nondiff=("X", "Length"),
             differentiable=False)
def _sequence_enumerate(ctx, ins, attrs):
    """Sliding windows of win_size per position (reference
    sequence_ops/sequence_enumerate_op.h): out[i,t,k] = x[i,t+k] while
    t+k is inside the sequence, else pad_value."""
    x = ins["X"][0]                       # (N, T) int
    n, t = x.shape
    lens = _lengths(ins, n, t)
    win = int(attrs["win_size"])
    pad_value = attrs.get("pad_value", 0)
    pos = jnp.arange(t)[None, :, None] + jnp.arange(win)[None, None, :]
    src = jnp.take_along_axis(x[:, :, None],
                              jnp.minimum(pos, t - 1), axis=1)
    ok = pos < lens[:, None, None]
    return {"Out": jnp.where(ok, src, jnp.asarray(pad_value, x.dtype))}


@register_op("sequence_slice", nondiff=("Offset", "SliceLength", "Length"))
def _sequence_slice(ctx, ins, attrs):
    """Per-row (offset, length) subsequence (reference
    sequence_ops/sequence_slice_op.h), left-aligned with zero padding."""
    x = ins["X"][0]                       # (N, T, ...)
    n, t = x.shape[0], x.shape[1]
    lens = _lengths(ins, n, t)
    offset = jnp.maximum(ins["Offset"][0].reshape(-1).astype(jnp.int32), 0)
    slice_len = ins["SliceLength"][0].reshape(-1).astype(jnp.int32)
    # the reference enforces offset + length <= seq_len; traced values
    # can't error, so clamp the reported/valid window instead of
    # fabricating duplicated timesteps
    eff_len = jnp.clip(slice_len, 0, jnp.maximum(lens - offset, 0))
    pos = jnp.arange(t)[None, :]
    idx = jnp.clip(pos + offset[:, None], 0, t - 1)
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    mask = pos < eff_len[:, None]
    return {"Out": jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - 2)),
                             out, 0),
            "OutLength": eff_len}


@register_op("sequence_expand_as", nondiff=("Y", "Length"))
def _sequence_expand_as(ctx, ins, attrs):
    """Broadcast each row of x over y's time steps (reference
    sequence_ops/sequence_expand_as_op.h): row i repeats len_i times."""
    x = ins["X"][0]                       # (N, D) or (N, 1, D)
    y = ins["Y"][0]                       # (N, T, ...) provides T
    t = y.shape[1]
    if x.ndim == 2:
        x = x[:, None, :]
    n = x.shape[0]
    lens = _lengths(ins, n, t)
    out = jnp.broadcast_to(x, (n, t) + x.shape[2:])
    mask = jnp.arange(t)[None, :] < lens[:, None]
    return {"Out": jnp.where(mask.reshape(mask.shape + (1,) *
                                          (out.ndim - 2)), out, 0)}


@register_op("sequence_pad_dense", nondiff=("Length",))
def _sequence_pad_dense(ctx, ins, attrs):
    """Dense form of sequence_pad (reference sequence_ops/sequence_pad_op.h):
    fill beyond each row's length with pad_value; optionally re-cap T at
    padded_length."""
    x = ins["X"][0]
    n, t = x.shape[0], x.shape[1]
    lens = _lengths(ins, n, t)
    pad_value = attrs.get("pad_value", 0.0)
    maxlen = int(attrs.get("padded_length", -1))
    if maxlen > 0 and maxlen != t:
        if maxlen < t:
            x = x[:, :maxlen]
        else:
            cfg = [(0, 0, 0), (0, maxlen - t, 0)] + \
                [(0, 0, 0)] * (x.ndim - 2)
            x = jax.lax.pad(x, jnp.asarray(pad_value, x.dtype), cfg)
        t = maxlen
    mask = jnp.arange(t)[None, :] < lens[:, None]
    out = jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - 2)), x,
                    jnp.asarray(pad_value, x.dtype))
    return {"Out": out, "Length": jnp.minimum(lens, t)}


@register_op("sequence_expand", nondiff=("RepeatCounts",))
def _sequence_expand(ctx, ins, attrs):
    """Repeat row i of X RepeatCounts[i] times, packed from the top of a
    static out_len-row buffer (reference sequence_ops/sequence_expand_op.h,
    python/paddle/fluid/layers/sequence_lod.py:596 — LoD repeat counts
    become a dense int vector; static capacity keeps XLA shapes fixed).
    Rows past the dynamic total are zeroed. searchsorted over the count
    cumsum maps output row -> source row without any host loop."""
    x = ins["X"][0]
    counts = ins["RepeatCounts"][0].reshape(-1).astype(jnp.int32)
    out_len = int(attrs["out_len"])
    cum = jnp.cumsum(counts)
    total = jnp.minimum(cum[-1], out_len)  # never report past capacity
    pos = jnp.arange(out_len, dtype=jnp.int32)
    row = jnp.searchsorted(cum, pos, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, x.shape[0] - 1)
    out = jnp.take(x, row, axis=0)
    mask = (pos < total).reshape((-1,) + (1,) * (out.ndim - 1))
    out = out * mask.astype(out.dtype)
    return {"Out": out, "OutLength": total.reshape(1)}


@register_op("sequence_scatter", nondiff=("Ids", "Length"))
def _sequence_scatter(ctx, ins, attrs):
    """x[n, ids[n, k]] += updates[n, k] for k < length[n] (ref
    sequence_scatter_op.h on the dense per-row encoding; padded (id,
    update) pairs past a row's length are masked out)."""
    x = ins["X"][0]
    ids = ins["Ids"][0].astype(jnp.int32)
    upd = ins["Updates"][0]
    n, k = ids.shape
    if ins.get("Length"):
        lens = ins["Length"][0].reshape(-1)
        upd = upd * (jnp.arange(k)[None, :] < lens[:, None]).astype(
            upd.dtype)
    rows = jnp.arange(n)[:, None].repeat(k, axis=1)
    return {"Out": x.at[rows.reshape(-1),
                        ids.reshape(-1)].add(upd.reshape(-1))}


@register_op("reorder_by_rank", nondiff=("RankTable",))
def _reorder_by_rank(ctx, ins, attrs):
    """Stable sort rows by descending length (ref
    reorder_lod_tensor_by_rank_op.cc)."""
    x = ins["X"][0]
    lens = ins["RankTable"][0].reshape(-1)
    order = jnp.argsort(-lens, stable=True)
    return {"Out": jnp.take(x, order, axis=0)}
