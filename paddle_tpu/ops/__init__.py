"""Op kernel registry — importing this package registers every kernel."""
from .registry import register_op, get_op, has_op, registered_ops  # noqa
from . import math_ops      # noqa: F401
from . import nn_ops        # noqa: F401
from . import tensor_ops    # noqa: F401
from . import random_ops    # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import metric_ops    # noqa: F401
from . import crf_ops       # noqa: F401
from . import detection_ops  # noqa: F401
from . import rnn_ops       # noqa: F401
from . import attention_ops  # noqa: F401
from . import collective_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import quant_ops     # noqa: F401
from . import vision_ops    # noqa: F401
from . import misc_ops      # noqa: F401
from . import extras_ops    # noqa: F401
from . import loss_extra_ops  # noqa: F401
from . import contrib_ops   # noqa: F401
from . import detection_train_ops  # noqa: F401
