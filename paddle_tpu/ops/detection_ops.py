"""Detection op kernels (subset).

Reference parity: paddle/fluid/operators/detection/{prior_box_op,
box_coder_op,iou_similarity_op,yolo_box_op}.cc — the building blocks of the
SSD/YOLO heads. NMS variants are host-side post-processing in the TPU
design (dynamic output shapes don't belong in XLA graphs); a top-k-capped
static NMS is provided for on-device use.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("prior_box", nondiff=("Input", "Image"), differentiable=False)
def _prior_box(ctx, ins, attrs):
    feat = ins["Input"][0]     # (N, C, H, W)
    img = ins["Image"][0]      # (N, C, IH, IW)
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - x) < 1e-6 for x in ars):
            ars.append(ar)
            if attrs.get("flip", True):
                ars.append(1.0 / ar)
    step_w = attrs.get("step_w", 0.0) or iw / w
    step_h = attrs.get("step_h", 0.0) or ih / h
    offset = attrs.get("offset", 0.5)

    boxes = []
    for s in min_sizes:
        for ar in ars:
            boxes.append((s * math.sqrt(ar), s / math.sqrt(ar)))
        if max_sizes:
            ms = max_sizes[min_sizes.index(s)]
            boxes.append((math.sqrt(s * ms), math.sqrt(s * ms)))
    num_priors = len(boxes)
    bw = np.array([b[0] for b in boxes]) / 2.0
    bh = np.array([b[1] for b in boxes]) / 2.0

    cx = (np.arange(w) + offset) * step_w
    cy = (np.arange(h) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)
    out = np.zeros((h, w, num_priors, 4), np.float32)
    out[..., 0] = (cxg[..., None] - bw) / iw
    out[..., 1] = (cyg[..., None] - bh) / ih
    out[..., 2] = (cxg[..., None] + bw) / iw
    out[..., 3] = (cyg[..., None] + bh) / ih
    if attrs.get("clip", True):
        out = np.clip(out, 0.0, 1.0)
    var = np.tile(np.array(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                           np.float32), (h, w, num_priors, 1))
    return {"Boxes": jnp.asarray(out), "Variances": jnp.asarray(var)}


@register_op("iou_similarity", nondiff=("X", "Y"), differentiable=False)
def _iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]    # (N,4), (M,4) xyxy
    area_x = jnp.maximum(x[:, 2] - x[:, 0], 0) * \
        jnp.maximum(x[:, 3] - x[:, 1], 0)
    area_y = jnp.maximum(y[:, 2] - y[:, 0], 0) * \
        jnp.maximum(y[:, 3] - y[:, 1], 0)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return {"Out": inter / jnp.maximum(union, 1e-10)}


@register_op("box_coder", nondiff=("PriorBox", "PriorBoxVar", "TargetBox"),
             differentiable=False)
def _box_coder(ctx, ins, attrs):
    prior = ins["PriorBox"][0]          # (M,4) xyxy
    target = ins["TargetBox"][0]
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    code_type = attrs.get("code_type", "encode_center_size")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if var is None:
        var = jnp.ones_like(prior)
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0]
        th = target[:, 3] - target[:, 1]
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / var[None, :, 1],
            jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10)) /
            var[None, :, 2],
            jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10)) /
            var[None, :, 3]], axis=-1)
        return {"OutputBox": out}
    # decode_center_size: target (N,M,4) deltas
    d = target
    cx = d[..., 0] * var[None, :, 0] * pw[None, :] + pcx[None, :]
    cy = d[..., 1] * var[None, :, 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(d[..., 2] * var[None, :, 2]) * pw[None, :]
    h = jnp.exp(d[..., 3] * var[None, :, 3]) * ph[None, :]
    out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5, cy + h * 0.5], axis=-1)
    return {"OutputBox": out}


@register_op("yolo_box", nondiff=("X", "ImgSize"), differentiable=False)
def _yolo_box(ctx, ins, attrs):
    x = ins["X"][0]                     # (N, A*(5+C), H, W)
    img_size = ins["ImgSize"][0]        # (N,2) h,w
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    downsample = attrs.get("downsample_ratio", 32)
    conf_thresh = attrs.get("conf_thresh", 0.01)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    in_h = h * downsample
    in_w = w * downsample
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    bw = jnp.exp(x[:, :, 2]) * aw / in_w
    bh = jnp.exp(x[:, :, 3]) * ah / in_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    mask = (conf > conf_thresh).astype(x.dtype)
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack([(bx - bw / 2) * img_w, (by - bh / 2) * img_h,
                       (bx + bw / 2) * img_w, (by + bh / 2) * img_h],
                      axis=-1)
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2)
    scores = scores.reshape(n, na * h * w, class_num)
    return {"Boxes": boxes, "Scores": scores}


@register_op("static_nms", nondiff=("Boxes", "Scores"),
             differentiable=False)
def _static_nms(ctx, ins, attrs):
    """Top-k-capped NMS with static output shape (keep_top_k boxes,
    score 0 for suppressed slots) — the XLA-compatible form of
    multiclass_nms; exact filtering happens host-side."""
    boxes = ins["Boxes"][0]      # (M,4)
    scores = ins["Scores"][0]    # (M,)
    iou_th = attrs.get("nms_threshold", 0.45)
    keep = attrs.get("keep_top_k", 100)
    keep = min(keep, boxes.shape[0])
    order = jnp.argsort(-scores)
    boxes_s = boxes[order][:keep * 4 if keep * 4 < boxes.shape[0]
                           else boxes.shape[0]]
    scores_s = scores[order][:boxes_s.shape[0]]
    m = boxes_s.shape[0]
    area = jnp.maximum(boxes_s[:, 2] - boxes_s[:, 0], 0) * \
        jnp.maximum(boxes_s[:, 3] - boxes_s[:, 1], 0)
    lt = jnp.maximum(boxes_s[:, None, :2], boxes_s[None, :, :2])
    rb = jnp.minimum(boxes_s[:, None, 2:], boxes_s[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)

    def body(i, alive):
        sup = (iou[i] > iou_th) & (jnp.arange(m) > i) & alive[i]
        return alive & ~sup

    alive = jax.lax.fori_loop(0, m, body, jnp.ones((m,), bool))
    final_scores = jnp.where(alive, scores_s, 0.0)
    order2 = jnp.argsort(-final_scores)[:keep]
    return {"Out": boxes_s[order2], "Scores": final_scores[order2],
            "Index": order[order2].astype(jnp.int64)}
