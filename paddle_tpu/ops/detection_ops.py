"""Detection op kernels (subset).

Reference parity: paddle/fluid/operators/detection/{prior_box_op,
box_coder_op,iou_similarity_op,yolo_box_op}.cc — the building blocks of the
SSD/YOLO heads. NMS variants are host-side post-processing in the TPU
design (dynamic output shapes don't belong in XLA graphs); a top-k-capped
static NMS is provided for on-device use.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("prior_box", nondiff=("Input", "Image"), differentiable=False)
def _prior_box(ctx, ins, attrs):
    feat = ins["Input"][0]     # (N, C, H, W)
    img = ins["Image"][0]      # (N, C, IH, IW)
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - x) < 1e-6 for x in ars):
            ars.append(ar)
            if attrs.get("flip", True):
                ars.append(1.0 / ar)
    step_w = attrs.get("step_w", 0.0) or iw / w
    step_h = attrs.get("step_h", 0.0) or ih / h
    offset = attrs.get("offset", 0.5)

    boxes = []
    for s in min_sizes:
        for ar in ars:
            boxes.append((s * math.sqrt(ar), s / math.sqrt(ar)))
        if max_sizes:
            ms = max_sizes[min_sizes.index(s)]
            boxes.append((math.sqrt(s * ms), math.sqrt(s * ms)))
    num_priors = len(boxes)
    bw = np.array([b[0] for b in boxes]) / 2.0
    bh = np.array([b[1] for b in boxes]) / 2.0

    cx = (np.arange(w) + offset) * step_w
    cy = (np.arange(h) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)
    out = np.zeros((h, w, num_priors, 4), np.float32)
    out[..., 0] = (cxg[..., None] - bw) / iw
    out[..., 1] = (cyg[..., None] - bh) / ih
    out[..., 2] = (cxg[..., None] + bw) / iw
    out[..., 3] = (cyg[..., None] + bh) / ih
    if attrs.get("clip", True):
        out = np.clip(out, 0.0, 1.0)
    var = np.tile(np.array(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                           np.float32), (h, w, num_priors, 1))
    return {"Boxes": jnp.asarray(out), "Variances": jnp.asarray(var)}


@register_op("iou_similarity", nondiff=("X", "Y"), differentiable=False)
def _iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]    # (N,4), (M,4) xyxy
    area_x = jnp.maximum(x[:, 2] - x[:, 0], 0) * \
        jnp.maximum(x[:, 3] - x[:, 1], 0)
    area_y = jnp.maximum(y[:, 2] - y[:, 0], 0) * \
        jnp.maximum(y[:, 3] - y[:, 1], 0)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return {"Out": inter / jnp.maximum(union, 1e-10)}


@register_op("box_coder", nondiff=("PriorBox", "PriorBoxVar", "TargetBox"),
             differentiable=False)
def _box_coder(ctx, ins, attrs):
    prior = ins["PriorBox"][0]          # (M,4) xyxy
    target = ins["TargetBox"][0]
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    code_type = attrs.get("code_type", "encode_center_size")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if var is None:
        var = jnp.ones_like(prior)
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0]
        th = target[:, 3] - target[:, 1]
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / var[None, :, 1],
            jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10)) /
            var[None, :, 2],
            jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10)) /
            var[None, :, 3]], axis=-1)
        return {"OutputBox": out}
    # decode_center_size: target (N,M,4) deltas
    d = target
    cx = d[..., 0] * var[None, :, 0] * pw[None, :] + pcx[None, :]
    cy = d[..., 1] * var[None, :, 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(d[..., 2] * var[None, :, 2]) * pw[None, :]
    h = jnp.exp(d[..., 3] * var[None, :, 3]) * ph[None, :]
    out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5, cy + h * 0.5], axis=-1)
    return {"OutputBox": out}


@register_op("yolo_box", nondiff=("X", "ImgSize"), differentiable=False)
def _yolo_box(ctx, ins, attrs):
    x = ins["X"][0]                     # (N, A*(5+C), H, W)
    img_size = ins["ImgSize"][0]        # (N,2) h,w
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    downsample = attrs.get("downsample_ratio", 32)
    conf_thresh = attrs.get("conf_thresh", 0.01)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    in_h = h * downsample
    in_w = w * downsample
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    bw = jnp.exp(x[:, :, 2]) * aw / in_w
    bh = jnp.exp(x[:, :, 3]) * ah / in_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    mask = (conf > conf_thresh).astype(x.dtype)
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack([(bx - bw / 2) * img_w, (by - bh / 2) * img_h,
                       (bx + bw / 2) * img_w, (by + bh / 2) * img_h],
                      axis=-1)
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2)
    scores = scores.reshape(n, na * h * w, class_num)
    return {"Boxes": boxes, "Scores": scores}


def _nms_alive(boxes, scores, iou_th, score_th=0.0, normalized=True,
               nms_eta=1.0):
    """Greedy NMS survivor mask with static shapes (boxes (M,4), scores (M,)).

    Shared core of static_nms / multiclass_nms / generate_proposals. Boxes are
    visited in score order; a box dies if it overlaps a higher-scoring live
    box by > iou_th. normalized=False adds the reference's +1 pixel offset to
    widths/heights; nms_eta < 1 decays the threshold adaptively, as in
    multiclass_nms_op.cc. Returns a bool mask aligned with the input order.
    """
    m = boxes.shape[0]
    off = 0.0 if normalized else 1.0
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    area = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * \
        jnp.maximum(b[:, 3] - b[:, 1] + off, 0)
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)

    def body(i, state):
        alive, th = state
        sup = (iou[i] > th) & (jnp.arange(m) > i) & alive[i]
        th = jnp.where((nms_eta < 1.0) & (th > 0.5) & alive[i],
                       th * nms_eta, th)
        return alive & ~sup, th

    alive, _ = jax.lax.fori_loop(
        0, m, body, (jnp.ones((m,), bool), jnp.asarray(iou_th, jnp.float32)))
    alive = alive & (s > score_th)
    # scatter back to input order
    inv = jnp.zeros((m,), jnp.int32).at[order].set(jnp.arange(m, dtype=jnp.int32))
    return alive[inv]


@register_op("static_nms", nondiff=("Boxes", "Scores"),
             differentiable=False)
def _static_nms(ctx, ins, attrs):
    """Top-k-capped NMS with static output shape (keep_top_k boxes,
    score 0 for suppressed slots) — the XLA-compatible form of
    multiclass_nms; exact filtering happens host-side."""
    boxes = ins["Boxes"][0]      # (M,4)
    scores = ins["Scores"][0]    # (M,)
    iou_th = attrs.get("nms_threshold", 0.45)
    keep = attrs.get("keep_top_k", 100)
    keep = min(keep, boxes.shape[0])
    # cap the O(M^2) IoU matrix at 4*keep candidates before suppression
    cap = min(keep * 4, boxes.shape[0])
    order = jnp.argsort(-scores)[:cap]
    boxes_s = boxes[order]
    scores_s = scores[order]
    alive = _nms_alive(boxes_s, scores_s, iou_th)
    final_scores = jnp.where(alive, scores_s, 0.0)
    order2 = jnp.argsort(-final_scores)[:keep]
    return {"Out": boxes_s[order2], "Scores": final_scores[order2],
            "Index": order[order2].astype(jnp.int64)}


@register_op("anchor_generator", nondiff=("Input",), differentiable=False)
def _anchor_generator(ctx, ins, attrs):
    """FasterRCNN-style anchors (reference detection/anchor_generator_op.h:28).

    Anchors are in input-image coordinates (NOT normalized like prior_box);
    centers at (idx*stride + offset*(stride-1)); base w/h from the stride
    cell area re-shaped by the aspect ratio, scaled by size/stride.
    """
    feat = ins["Input"][0]            # (N, C, H, W)
    h, w = feat.shape[2], feat.shape[3]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs.get("aspect_ratios", [1.0])]
    sw, sh = [float(s) for s in attrs.get("stride", [16.0, 16.0])]
    offset = float(attrs.get("offset", 0.5))
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])

    aw, ah = [], []
    for ar in ratios:
        base_w = round(math.sqrt(sw * sh / ar))
        base_h = round(base_w * ar)
        for s in sizes:
            aw.append(s / sw * base_w)
            ah.append(s / sh * base_h)
    aw = np.asarray(aw, np.float32)
    ah = np.asarray(ah, np.float32)
    num_anchors = aw.shape[0]

    cx = np.arange(w, dtype=np.float32) * sw + offset * (sw - 1)
    cy = np.arange(h, dtype=np.float32) * sh + offset * (sh - 1)
    cxg, cyg = np.meshgrid(cx, cy)
    out = np.empty((h, w, num_anchors, 4), np.float32)
    out[..., 0] = cxg[..., None] - 0.5 * (aw - 1)
    out[..., 1] = cyg[..., None] - 0.5 * (ah - 1)
    out[..., 2] = cxg[..., None] + 0.5 * (aw - 1)
    out[..., 3] = cyg[..., None] + 0.5 * (ah - 1)
    var = np.tile(np.asarray(variances, np.float32), (h, w, num_anchors, 1))
    return {"Anchors": jnp.asarray(out), "Variances": jnp.asarray(var)}


@register_op("density_prior_box", nondiff=("Input", "Image"),
             differentiable=False)
def _density_prior_box(ctx, ins, attrs):
    """Density prior boxes (reference detection/density_prior_box_op.h:25):
    per fixed_size a density x density grid of shifted centers, one box per
    fixed_ratio, normalized to [0,1] by the image size."""
    feat, img = ins["Input"][0], ins["Image"][0]
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs["fixed_ratios"]]
    densities = [int(d) for d in attrs["densities"]]
    step_w = float(attrs.get("step_w", 0.0)) or iw / w
    step_h = float(attrs.get("step_h", 0.0)) or ih / h
    offset = float(attrs.get("offset", 0.5))
    step_avg = int((step_w + step_h) * 0.5)

    # per-prior (dx, dy, bw/2, bh/2) offsets relative to the cell center
    offs = []
    for fs, density in zip(fixed_sizes, densities):
        shift = step_avg // density
        for r in fixed_ratios:
            bw = fs * math.sqrt(r)
            bh = fs / math.sqrt(r)
            base = -step_avg / 2.0 + shift / 2.0
            for di in range(density):
                for dj in range(density):
                    offs.append((base + dj * shift, base + di * shift,
                                 bw / 2.0, bh / 2.0))
    offs = np.asarray(offs, np.float32)
    num_priors = offs.shape[0]

    cx = (np.arange(w, dtype=np.float32) + offset) * step_w
    cy = (np.arange(h, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)
    px = cxg[..., None] + offs[:, 0]
    py = cyg[..., None] + offs[:, 1]
    out = np.stack([np.maximum((px - offs[:, 2]) / iw, 0.0),
                    np.maximum((py - offs[:, 3]) / ih, 0.0),
                    np.minimum((px + offs[:, 2]) / iw, 1.0),
                    np.minimum((py + offs[:, 3]) / ih, 1.0)], axis=-1)
    if attrs.get("clip", False):
        out = np.clip(out, 0.0, 1.0)
    var = np.tile(np.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                             np.float32), (h, w, num_priors, 1))
    out = out.astype(np.float32)
    if attrs.get("flatten_to_2d", False):
        out = out.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return {"Boxes": jnp.asarray(out), "Variances": jnp.asarray(var)}


@register_op("box_clip", nondiff=("ImInfo",))
def _box_clip(ctx, ins, attrs):
    """Clip boxes to image bounds (reference detection/box_clip_op.h:25).
    ImInfo rows are (h, w, scale); boxes clip to [0, dim/scale - 1]."""
    boxes = ins["Input"][0]       # (N, M, 4) or (M, 4)
    im_info = ins["ImInfo"][0]    # (N, 3)
    squeeze = boxes.ndim == 2
    if squeeze:
        boxes = boxes[None]
    hmax = im_info[:, 0] / im_info[:, 2] - 1.0   # (N,)
    wmax = im_info[:, 1] / im_info[:, 2] - 1.0
    x1 = jnp.clip(boxes[..., 0], 0.0, wmax[:, None])
    y1 = jnp.clip(boxes[..., 1], 0.0, hmax[:, None])
    x2 = jnp.clip(boxes[..., 2], 0.0, wmax[:, None])
    y2 = jnp.clip(boxes[..., 3], 0.0, hmax[:, None])
    out = jnp.stack([x1, y1, x2, y2], axis=-1)
    return {"Output": out[0] if squeeze else out}


def _bipartite_match_single(dist, match_type, overlap_threshold):
    """Greedy max bipartite matching on one (R, C) distance matrix —
    reference detection/bipartite_match_op.cc:61 (BipartiteMatch then
    optional ArgMaxMatch for still-unmatched columns)."""
    r, c = dist.shape
    eps = 1e-6

    def body(_, state):
        col_match, col_dist, row_used = state
        masked = jnp.where(row_used[:, None] | (col_match[None, :] >= 0),
                           -jnp.inf, dist)
        flat = jnp.argmax(masked)
        i, j = flat // c, flat % c
        best = masked[i, j]
        take = best > eps
        col_match = jnp.where(take, col_match.at[j].set(i.astype(jnp.int32)),
                              col_match)
        col_dist = jnp.where(take, col_dist.at[j].set(best), col_dist)
        row_used = jnp.where(take, row_used.at[i].set(True), row_used)
        return col_match, col_dist, row_used

    init = (jnp.full((c,), -1, jnp.int32), jnp.zeros((c,), dist.dtype),
            jnp.zeros((r,), bool))
    col_match, col_dist, _ = jax.lax.fori_loop(0, min(r, c), body, init)

    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        extra = (col_match < 0) & (best_val > overlap_threshold)
        col_match = jnp.where(extra, best_row, col_match)
        col_dist = jnp.where(extra, best_val, col_dist)
    return col_match, col_dist


@register_op("bipartite_match", nondiff=("DistMat",), differentiable=False)
def _bipartite_match(ctx, ins, attrs):
    dist = ins["DistMat"][0]
    match_type = attrs.get("match_type", "bipartite")
    th = float(attrs.get("dist_threshold", 0.5))
    if dist.ndim == 2:
        dist = dist[None]
    m, d = jax.vmap(lambda dm: _bipartite_match_single(dm, match_type, th))(dist)
    return {"ColToRowMatchIndices": m, "ColToRowMatchDist": d}


@register_op("target_assign", nondiff=("X", "MatchIndices", "NegIndices"),
             differentiable=False)
def _target_assign(ctx, ins, attrs):
    """Assign row entities to matched columns (reference
    detection/target_assign_op.h): out[i,j] = x[i, match[i,j]] when
    match >= 0 else mismatch_value; weight 1 where matched (or negative)."""
    x = ins["X"][0]                      # (N, R, K)
    match = ins["MatchIndices"][0]       # (N, C) int32, -1 = unmatched
    mismatch = attrs.get("mismatch_value", 0)
    safe = jnp.maximum(match, 0)
    out = jnp.take_along_axis(x, safe[..., None], axis=1)
    out = jnp.where((match >= 0)[..., None], out,
                    jnp.asarray(mismatch, x.dtype))
    wt = (match >= 0).astype(jnp.float32)[..., None]
    if ins.get("NegIndices"):
        neg = ins["NegIndices"][0]       # (N, C) bool/int mask of negatives
        wt = jnp.maximum(wt, neg.astype(jnp.float32).reshape(wt.shape))
    return {"Out": out, "OutWeight": wt}


@register_op("sigmoid_focal_loss", nondiff=("Label", "FgNum"))
def _sigmoid_focal_loss(ctx, ins, attrs):
    """Focal loss (reference detection/sigmoid_focal_loss_op.h:26). Labels in
    0..C with 0 = background, -1 = ignored; normalized by FgNum."""
    x = ins["X"][0]                      # (N, C) logits
    label = ins["Label"][0].reshape(-1)  # (N,)
    fg = ins["FgNum"][0].reshape(-1)[0]
    gamma = float(attrs.get("gamma", 2.0))
    alpha = float(attrs.get("alpha", 0.25))
    c = x.shape[1]
    d = jnp.arange(1, c + 1)
    c_pos = (label[:, None] == d).astype(x.dtype)
    c_neg = ((label[:, None] != -1) & (label[:, None] != d)).astype(x.dtype)
    fg_num = jnp.maximum(fg, 1).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    term_pos = jnp.power(1.0 - p, gamma) * jnp.log(jnp.maximum(p, 1e-37))
    # log(1-p) computed stably as -x*(x>=0) - log1p(exp(x - 2x*(x>=0)))
    pos_x = (x >= 0).astype(x.dtype)
    term_neg = jnp.power(p, gamma) * (
        -x * pos_x - jnp.log1p(jnp.exp(x - 2.0 * x * pos_x)))
    out = -c_pos * term_pos * (alpha / fg_num) \
        - c_neg * term_neg * ((1.0 - alpha) / fg_num)
    return {"Out": out}


@register_op("polygon_box_transform", differentiable=False)
def _polygon_box_transform(ctx, ins, attrs):
    """EAST geo-map offsets -> absolute quad coords (reference
    detection/polygon_box_transform_op.cc:23): even channels use 4*w - in,
    odd channels 4*h - in."""
    x = ins["Input"][0]                  # (N, G, H, W)
    n, g, h, w = x.shape
    wi = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    hi = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    even = (jnp.arange(g) % 2 == 0)[None, :, None, None]
    return {"Output": jnp.where(even, 4.0 * wi - x, 4.0 * hi - x)}


def _roi_batch_index(rois_num, num_rois, n):
    """RoisNum (N,) per-image counts -> (num_rois,) image index."""
    ends = jnp.cumsum(rois_num)
    return jnp.sum(jnp.arange(num_rois)[:, None] >= ends[None, :],
                   axis=1).astype(jnp.int32)


@register_op("roi_align", nondiff=("ROIs", "RoisNum"))
def _roi_align(ctx, ins, attrs):
    """RoIAlign (reference detection-era roi_align_op.h): average of bilinear
    samples per bin; XLA gathers give exact scatter-add gradients. With
    sampling_ratio <= 0 the reference adapts samples to the roi size
    (dynamic); we use a fixed 2x2 grid per bin — the detectron default."""
    x = ins["X"][0]                      # (N, C, H, W)
    rois = ins["ROIs"][0]                # (R, 4) xyxy in input-image coords
    n, c, h, w = x.shape
    r = rois.shape[0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    sr = int(attrs.get("sampling_ratio", -1))
    if sr <= 0:
        sr = 2
    if ins.get("RoisNum"):
        bidx = _roi_batch_index(ins["RoisNum"][0], r, n)
    else:
        bidx = jnp.zeros((r,), jnp.int32)

    x1 = rois[:, 0] * scale
    y1 = rois[:, 1] * scale
    rw = jnp.maximum(rois[:, 2] * scale - x1, 1.0)
    rh = jnp.maximum(rois[:, 3] * scale - y1, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw
    iy = (jnp.arange(sr) + 0.5) / sr                       # (sr,)
    gy = y1[:, None, None] + (jnp.arange(ph)[None, :, None] +
                              iy[None, None, :]) * bin_h[:, None, None]
    gx = x1[:, None, None] + (jnp.arange(pw)[None, :, None] +
                              iy[None, None, :]) * bin_w[:, None, None]
    gy = gy.reshape(r, ph * sr)                            # (R, PH*S)
    gx = gx.reshape(r, pw * sr)

    def bilinear_1d(coord, size):
        coord = jnp.clip(coord, 0.0, size - 1.0)
        lo = jnp.floor(coord).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, size - 1)
        frac = coord - lo
        return lo, hi, frac

    y0, y1i, fy = bilinear_1d(gy, h)
    x0, x1i, fx = bilinear_1d(gx, w)
    xb = x[bidx]                                           # (R, C, H, W)
    ridx = jnp.arange(r)[:, None, None]
    ya, yb_, xa, xb_ = (y0[:, :, None], y1i[:, :, None],
                       x0[:, None, :], x1i[:, None, :])
    v00 = xb[ridx, :, ya, xa]                              # (R, PH*S, PW*S, C)
    v01 = xb[ridx, :, ya, xb_]
    v10 = xb[ridx, :, yb_, xa]
    v11 = xb[ridx, :, yb_, xb_]
    fyb = fy[:, :, None, None]
    fxb = fx[:, None, :, None]
    vals = (v00 * (1 - fyb) * (1 - fxb) + v01 * (1 - fyb) * fxb +
            v10 * fyb * (1 - fxb) + v11 * fyb * fxb)       # (R,PH*S,PW*S,C)
    vals = vals.reshape(r, ph, sr, pw, sr, c)
    out = vals.mean(axis=(2, 4)).transpose(0, 3, 1, 2)     # (R, C, PH, PW)
    return {"Out": out}


@register_op("roi_pool", nondiff=("ROIs", "RoisNum"))
def _roi_pool(ctx, ins, attrs):
    """RoIPool (reference roi_pool_op.h): quantized bins, max per bin.
    Computed as a masked max over the full map — static shapes, exact
    reference bin arithmetic, differentiable through max."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    n, c, h, w = x.shape
    r = rois.shape[0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    if ins.get("RoisNum"):
        bidx = _roi_batch_index(ins["RoisNum"][0], r, n)
    else:
        bidx = jnp.zeros((r,), jnp.int32)

    x1 = jnp.round(rois[:, 0] * scale)
    y1 = jnp.round(rois[:, 1] * scale)
    x2 = jnp.round(rois[:, 2] * scale)
    y2 = jnp.round(rois[:, 3] * scale)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)

    def bin_mask(start, extent, p, size):
        # (R, P, size) True where pixel in [start + floor(i*e/p),
        #                                   start + ceil((i+1)*e/p))
        i = jnp.arange(p, dtype=jnp.float32)
        lo = start[:, None] + jnp.floor(i * extent[:, None] / p)
        hi = start[:, None] + jnp.ceil((i + 1) * extent[:, None] / p)
        lo = jnp.clip(lo, 0, size)
        hi = jnp.clip(hi, 0, size)
        pix = jnp.arange(size, dtype=jnp.float32)
        return (pix[None, None, :] >= lo[..., None]) & \
               (pix[None, None, :] < hi[..., None])

    mh = bin_mask(y1, rh, ph, h)                           # (R, PH, H)
    mw = bin_mask(x1, rw, pw, w)                           # (R, PW, W)
    xb = x[bidx]                                           # (R, C, H, W)
    neg = jnp.asarray(-jnp.inf, x.dtype)
    # reduce one output bin per (unrolled, static-count) iteration so the
    # largest intermediate stays O(R*C*H*W) — a broadcast over all PH*PW
    # bins at once would be PW (then PH) times larger
    cols = [jnp.where(mw[:, None, None, j, :], xb, neg).max(axis=-1)
            for j in range(pw)]
    t = jnp.stack(cols, axis=-1)                           # (R, C, H, PW)
    rows = [jnp.where(mh[:, None, i, :, None], t, neg).max(axis=2)
            for i in range(ph)]
    out = jnp.stack(rows, axis=2)                          # (R, C, PH, PW)
    empty = ~(mh.any(-1)[:, None, :, None] & mw.any(-1)[:, None, None, :])
    out = jnp.where(empty, 0.0, out)
    return {"Out": out}


@register_op("multiclass_nms", nondiff=("BBoxes", "Scores"),
             differentiable=False)
def _multiclass_nms(ctx, ins, attrs):
    """Static-shape multiclass NMS (reference detection/multiclass_nms_op.cc).
    Output is (N, keep_top_k, 6) [label, score, x1, y1, x2, y2] with -1
    labels / 0 scores in suppressed slots (the reference emits a variable-
    length LoD tensor; a fixed-capacity tensor is the XLA-native form)."""
    bboxes = ins["BBoxes"][0]            # (N, M, 4)
    scores = ins["Scores"][0]            # (N, C, M)
    score_th = float(attrs.get("score_threshold", 0.0))
    iou_th = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", -1))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    bg = int(attrs.get("background_label", 0))
    normalized = bool(attrs.get("normalized", True))
    nms_eta = float(attrs.get("nms_eta", 1.0))
    n, cc, m = scores.shape
    m_eff = min(m, nms_top_k) if nms_top_k > 0 else m
    if keep_top_k <= 0:
        # reference sentinel: no cap — keep every surviving candidate
        keep_top_k = cc * m_eff
    keep_top_k = min(keep_top_k, cc * m_eff)

    def per_class(boxes, sc):
        cand = jnp.arange(m)
        if m_eff < m:
            _, top = jax.lax.top_k(sc, m_eff)
            boxes, sc, cand = boxes[top], sc[top], top
        alive = _nms_alive(boxes, sc, iou_th, score_th, normalized, nms_eta)
        return boxes, jnp.where(alive, sc, 0.0), cand

    def per_image(boxes, sc):
        cb, cs, cidx = jax.vmap(lambda s: per_class(boxes, s))(sc)
        labels = jnp.broadcast_to(jnp.arange(cc)[:, None], cs.shape)
        flat_s = cs.reshape(-1)
        flat_b = cb.reshape(-1, 4)
        flat_l = labels.reshape(-1)
        flat_i = cidx.reshape(-1)
        if bg >= 0:
            flat_s = jnp.where(flat_l == bg, 0.0, flat_s)
        top_s, idx = jax.lax.top_k(flat_s, keep_top_k)
        sel_b = flat_b[idx]
        sel_l = jnp.where(top_s > 0, flat_l[idx], -1).astype(jnp.float32)
        # Index into the per-image BBoxes rows (-1 for empty slots), the
        # multiclass_nms2 "Index" output
        sel_i = jnp.where(top_s > 0, flat_i[idx], -1).astype(jnp.int32)
        return (jnp.concatenate([sel_l[:, None], top_s[:, None], sel_b], -1),
                sel_i)

    out, index = jax.vmap(per_image)(bboxes, scores)
    nms_rois_num = (out[..., 1] > 0).sum(-1).astype(jnp.int32)
    return {"Out": out, "Index": index, "NmsRoisNum": nms_rois_num}


@register_op("box_decoder_and_assign",
             nondiff=("PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"),
             differentiable=False)
def _box_decoder_and_assign(ctx, ins, attrs):
    """Decode per-class deltas and pick each roi's best-class box
    (reference detection/box_decoder_and_assign_op.h)."""
    prior = ins["PriorBox"][0]           # (M, 4)
    var = ins["PriorBoxVar"][0]          # (M, 4) or (4,)
    deltas = ins["TargetBox"][0]         # (M, 4*C)
    score = ins["BoxScore"][0]           # (M, C)
    clip = float(attrs.get("box_clip", 4.135))
    m, c = score.shape
    d = deltas.reshape(m, c, 4)
    if var.ndim == 1:
        var = jnp.broadcast_to(var, (m, 4))
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    dx = d[..., 0] * var[:, None, 0]
    dy = d[..., 1] * var[:, None, 1]
    dw = jnp.clip(d[..., 2] * var[:, None, 2], -clip, clip)
    dh = jnp.clip(d[..., 3] * var[:, None, 3], -clip, clip)
    cx = dx * pw[:, None] + pcx[:, None]
    cy = dy * ph[:, None] + pcy[:, None]
    bw = jnp.exp(dw) * pw[:, None]
    bh = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack([cx - bw / 2, cy - bh / 2,
                         cx + bw / 2 - 1, cy + bh / 2 - 1], -1)  # (M, C, 4)
    # reference box_decoder_and_assign_op.h scans classes FROM 1 — the
    # background column never wins the assignment
    best = jnp.argmax(score[:, 1:], axis=1) + 1 if c > 1 else \
        jnp.zeros((m,), jnp.int32)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
    return {"DecodeBox": decoded.reshape(m, c * 4), "OutputAssignBox": assigned}


@register_op("generate_proposals",
             nondiff=("Scores", "BboxDeltas", "ImInfo", "Anchors", "Variances"),
             differentiable=False)
def _generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (reference detection/generate_proposals_op.cc)
    with static shapes: decode -> clip -> small-box filter (as score mask)
    -> pre_nms top-k -> NMS -> post_nms top-k padded with zeros."""
    scores = ins["Scores"][0]            # (N, A, H, W)
    deltas = ins["BboxDeltas"][0]        # (N, A*4, H, W)
    im_info = ins["ImInfo"][0]           # (N, 3)
    anchors = ins["Anchors"][0].reshape(-1, 4)
    variances = ins["Variances"][0].reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    iou_th = float(attrs.get("nms_thresh", 0.5))
    min_size = float(attrs.get("min_size", 0.1))
    eta = float(attrs.get("eta", 1.0))
    n, a, h, w = scores.shape
    total = a * h * w
    pre_n = min(pre_n, total)
    post_n = min(post_n, pre_n)

    def per_image(sc, dl, info):
        sc = sc.transpose(1, 2, 0).reshape(-1)               # (H*W*A,)
        dl = dl.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        anc = anchors.reshape(h, w, a, 4).reshape(-1, 4)
        vr = variances.reshape(h, w, a, 4).reshape(-1, 4)
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah_ = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah_ * 0.5
        cx = vr[:, 0] * dl[:, 0] * aw + acx
        cy = vr[:, 1] * dl[:, 1] * ah_ + acy
        clip = math.log(1000.0 / 16.0)   # kBBoxClipDefault
        bw = jnp.exp(jnp.minimum(vr[:, 2] * dl[:, 2], clip)) * aw
        bh = jnp.exp(jnp.minimum(vr[:, 3] * dl[:, 3], clip)) * ah_
        props = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - 1, cy + bh / 2 - 1], -1)
        hmax = info[0] / info[2] - 1.0
        wmax = info[1] / info[2] - 1.0
        props = jnp.stack([jnp.clip(props[:, 0], 0, wmax),
                           jnp.clip(props[:, 1], 0, hmax),
                           jnp.clip(props[:, 2], 0, wmax),
                           jnp.clip(props[:, 3], 0, hmax)], -1)
        ms = min_size * info[2]
        keep = ((props[:, 2] - props[:, 0] + 1 >= ms) &
                (props[:, 3] - props[:, 1] + 1 >= ms))
        sc = jnp.where(keep, sc, -jnp.inf)
        top_s, idx = jax.lax.top_k(sc, pre_n)
        pb = props[idx]
        alive = _nms_alive(pb, top_s, iou_th, nms_eta=eta)
        final = jnp.where(alive, top_s, -jnp.inf)
        out_s, oidx = jax.lax.top_k(final, post_n)
        ob = pb[oidx]
        good = jnp.isfinite(out_s)
        return (jnp.where(good[:, None], ob, 0.0),
                jnp.where(good, out_s, 0.0), good.sum().astype(jnp.int32))

    rois, rscores, num = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": rscores[..., None],
            "RpnRoisNum": num}


@register_op("distribute_fpn_proposals", nondiff=("FpnRois", "RoisNum"),
             differentiable=False)
def _distribute_fpn_proposals(ctx, ins, attrs):
    """Assign each roi to an FPN level (reference
    detection/distribute_fpn_proposals_op.h): level = floor(log2(
    sqrt(area) / refer_scale + 1e-6)) + refer_level, clipped. Static form:
    per-level outputs keep full length with a validity mask encoded by
    zeroed rois + per-level RoisNum counts; RestoreIndex maps the
    level-sorted concat back to input order."""
    rois = ins["FpnRois"][0]             # (R, 4)
    min_level = int(attrs["min_level"])
    max_level = int(attrs["max_level"])
    refer_level = int(attrs["refer_level"])
    refer_scale = int(attrs["refer_scale"])
    r = rois.shape[0]
    num_lvl = max_level - min_level + 1
    if ins.get("RoisNum"):
        valid = jnp.arange(r) < ins["RoisNum"][0].reshape(-1)[0]
    else:
        valid = jnp.ones((r,), bool)
    scale = jnp.sqrt(jnp.maximum(
        (rois[:, 2] - rois[:, 0] + 1) * (rois[:, 3] - rois[:, 1] + 1), 0.0))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    # padding rois get the past-the-end sentinel level so they sort after
    # every real level and never count toward MultiLevelRoIsNum
    lidx = jnp.where(valid, lvl - min_level, num_lvl)
    outs = {}
    multi = []
    nums = []
    for i in range(num_lvl):
        mask = lidx == i
        # stable sort: members first, preserving order
        order = jnp.argsort(~mask, stable=True)
        cnt = mask.sum().astype(jnp.int32)
        sel = jnp.where((jnp.arange(r) < cnt)[:, None], rois[order], 0.0)
        multi.append(sel)
        nums.append(cnt)
    # RestoreIndex (reference distribute_fpn_proposals_op.h:136):
    # restore[orig] = position in the level-sorted concat, so
    # gather(concat, restore) recovers the input order (padding rois land
    # after all valid ones).
    counts = jnp.stack(nums)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(counts)]).astype(jnp.int32)
    # rank within level = number of same-level rois before this one
    same = (lidx[None, :] == lidx[:, None]) & \
        (jnp.arange(r)[None, :] < jnp.arange(r)[:, None])
    rank_in_level = same.sum(1).astype(jnp.int32)
    pos = offsets[lidx] + rank_in_level
    outs["MultiFpnRois"] = multi
    outs["RestoreIndex"] = pos[:, None]
    outs["MultiLevelRoIsNum"] = [c[None] for c in nums]
    return outs


@register_op("collect_fpn_proposals",
             nondiff=("MultiLevelRois", "MultiLevelScores", "MultiLevelRoisNum"),
             differentiable=False)
def _collect_fpn_proposals(ctx, ins, attrs):
    """Concat per-level proposals and keep global top-N by score (reference
    detection/collect_fpn_proposals_op.h). Static shapes: output is exactly
    post_nms_topN rois, zero-padded when fewer are valid."""
    rois = jnp.concatenate([x.reshape(-1, 4) for x in ins["MultiLevelRois"]], 0)
    scores = jnp.concatenate([x.reshape(-1) for x in ins["MultiLevelScores"]], 0)
    if ins.get("MultiLevelRoisNum"):
        valid = []
        for roi_t, cnt in zip(ins["MultiLevelRois"],
                              ins["MultiLevelRoisNum"]):
            m = roi_t.reshape(-1, 4).shape[0]
            valid.append(jnp.arange(m) < cnt.reshape(()))
        vmask = jnp.concatenate(valid)
        scores = jnp.where(vmask, scores, -jnp.inf)
    post_n = min(int(attrs.get("post_nms_topN", 100)), scores.shape[0])
    top_s, idx = jax.lax.top_k(scores, post_n)
    good = jnp.isfinite(top_s)
    return {"FpnRois": jnp.where(good[:, None], rois[idx], 0.0),
            "RoisNum": good.sum().astype(jnp.int32)[None]}


@register_op("mine_hard_examples",
             nondiff=("ClsLoss", "LocLoss", "MatchIndices", "MatchDist"),
             differentiable=False)
def _mine_hard_examples(ctx, ins, attrs):
    """OHEM negative mining (reference detection/mine_hard_examples_op.cc).
    Static form: returns a (N, P) 0/1 mask of selected negatives (the
    reference emits LoD index lists) plus UpdatedMatchIndices."""
    cls_loss = ins["ClsLoss"][0]         # (N, P)
    match = ins["MatchIndices"][0]       # (N, P)
    loc_loss = ins["LocLoss"][0] if ins.get("LocLoss") else None
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    neg_dist_threshold = float(attrs.get("neg_dist_threshold", 0.5))
    mining_type = attrs.get("mining_type", "max_negative")
    sample_size = int(attrs.get("sample_size", 0))
    dist = ins["MatchDist"][0] if ins.get("MatchDist") else None
    loss = cls_loss if loc_loss is None else cls_loss + loc_loss
    is_neg = match < 0
    if dist is not None and mining_type == "max_negative":
        is_neg = is_neg & (dist < neg_dist_threshold)
    num_pos = (match >= 0).sum(axis=1)
    if mining_type == "hard_example" and sample_size > 0:
        limit = jnp.full_like(num_pos, sample_size)
    else:
        limit = jnp.ceil(num_pos * neg_pos_ratio).astype(jnp.int32)
    neg_loss = jnp.where(is_neg, loss, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.zeros_like(order).at[
        jnp.arange(order.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(order.shape[1]), order.shape))
    sel = is_neg & (rank < limit[:, None])
    upd = jnp.where(sel, -1, match)
    return {"NegIndices": sel.astype(jnp.int32), "UpdatedMatchIndices": upd}


def _bce_logits(x, label):
    # SigmoidCrossEntropy of reference yolov3_loss_op.h:34 — numerically
    # stable BCE-with-logits
    return jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))


@register_op("yolov3_loss", nondiff=("GTBox", "GTLabel", "GTScore"))
def _yolov3_loss(ctx, ins, attrs):
    """YOLOv3 training loss (reference detection/yolov3_loss_op.h:258).

    Vectorized: per-prediction best-IoU-vs-gt computes the ignore mask; each
    gt picks its best anchor by shifted wh-IoU and, when that anchor is in
    anchor_mask, contributes location (BCE xy + L1 wh, scaled by
    (2 - w*h) * score), class (sigmoid CE vs smoothed one-hot) and
    objectness targets. Differentiable w.r.t. X only.
    """
    x = ins["X"][0]                       # (N, M*(5+C), H, W)
    gt_box = ins["GTBox"][0]              # (N, B, 4) xywh, normalized
    gt_label = ins["GTLabel"][0]          # (N, B) int
    anchors = [int(a) for a in attrs["anchors"]]
    anchor_mask = [int(a) for a in attrs["anchor_mask"]]
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    downsample = int(attrs.get("downsample_ratio", 32))
    use_label_smooth = bool(attrs.get("use_label_smooth", True))
    n, _, h, w = x.shape
    an_num = len(anchors) // 2
    mask_num = len(anchor_mask)
    b = gt_box.shape[1]
    input_size = downsample * h
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    gt_score = (ins["GTScore"][0] if ins.get("GTScore")
                else jnp.ones((n, b), x.dtype))

    label_pos, label_neg = 1.0, 0.0
    if use_label_smooth:
        delta = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - delta, delta

    aw_all = jnp.asarray(anchors[0::2], x.dtype)           # (A,)
    ah_all = jnp.asarray(anchors[1::2], x.dtype)
    # static map: anchor index -> position in anchor_mask (or -1)
    an2mask = np.full((an_num,), -1, np.int32)
    for pos, a in enumerate(anchor_mask):
        an2mask[a] = pos
    an2mask = jnp.asarray(an2mask)
    aw_m = jnp.asarray([anchors[2 * a] for a in anchor_mask], x.dtype)
    ah_m = jnp.asarray([anchors[2 * a + 1] for a in anchor_mask], x.dtype)

    def per_image(xi, gb, gl, gs):
        xi = xi.reshape(mask_num, 5 + class_num, h, w)
        valid = (gb[:, 2] > 1e-6) & (gb[:, 3] > 1e-6)      # (B,)

        # --- predicted boxes and best-IoU ignore mask -------------------
        gx = jnp.arange(w, dtype=x.dtype)[None, None, :]
        gy = jnp.arange(h, dtype=x.dtype)[None, :, None]
        px = (gx + jax.nn.sigmoid(xi[:, 0])) / w           # (M, H, W)
        py = (gy + jax.nn.sigmoid(xi[:, 1])) / h
        pw_ = jnp.exp(xi[:, 2]) * aw_m[:, None, None] / input_size
        ph_ = jnp.exp(xi[:, 3]) * ah_m[:, None, None] / input_size

        def iou_xywh(x1, y1, w1, h1, x2, y2, w2, h2):
            ow = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2) - \
                jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
            oh = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2) - \
                jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
            inter = jnp.where((ow < 0) | (oh < 0), 0.0, ow * oh)
            return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

        ious = iou_xywh(px[..., None], py[..., None], pw_[..., None],
                        ph_[..., None], gb[:, 0], gb[:, 1], gb[:, 2],
                        gb[:, 3])                          # (M, H, W, B)
        ious = jnp.where(valid, ious, 0.0)
        best_iou = ious.max(-1)                            # (M, H, W)
        objness = jnp.where(best_iou > ignore_thresh, -1.0,
                            0.0).astype(x.dtype)

        # --- per-gt best anchor -----------------------------------------
        a_iou = iou_xywh(0.0, 0.0, aw_all[None, :] / input_size,
                         ah_all[None, :] / input_size,
                         0.0, 0.0, gb[:, 2:3], gb[:, 3:4])  # (B, A)
        best_n = jnp.argmax(a_iou, axis=1)                 # (B,)
        midx = an2mask[best_n]                             # (B,)
        pos = valid & (midx >= 0)
        gi = jnp.clip((gb[:, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gb[:, 1] * h).astype(jnp.int32), 0, h - 1)
        msafe = jnp.maximum(midx, 0)

        tx = gb[:, 0] * w - gi
        ty = gb[:, 1] * h - gj
        tw = jnp.log(jnp.maximum(gb[:, 2] * input_size /
                                 aw_all[best_n], 1e-10))
        th = jnp.log(jnp.maximum(gb[:, 3] * input_size /
                                 ah_all[best_n], 1e-10))
        scale = (2.0 - gb[:, 2] * gb[:, 3]) * gs
        cell = xi[msafe, :, gj, gi]                        # (B, 5+C)
        loc = (_bce_logits(cell[:, 0], tx) + _bce_logits(cell[:, 1], ty) +
               jnp.abs(cell[:, 2] - tw) + jnp.abs(cell[:, 3] - th)) * scale
        onehot = (jnp.arange(class_num)[None, :] == gl[:, None])
        tgt = jnp.where(onehot, label_pos, label_neg).astype(x.dtype)
        lbl = (_bce_logits(cell[:, 5:], tgt).sum(-1)) * gs
        pos_loss = jnp.where(pos, loc + lbl, 0.0).sum()

        # --- objectness: positives overwrite in gt order (last wins, as
        # the reference's sequential loop does) ---------------------------
        def set_obj(t, obj):
            return jnp.where(pos[t],
                             obj.at[msafe[t], gj[t], gi[t]].set(gs[t]), obj)

        objness = jax.lax.fori_loop(0, 
                                    b, set_obj, objness)
        obj_logit = xi[:, 4]
        obj_loss = jnp.where(
            objness > 1e-5, _bce_logits(obj_logit, 1.0) * objness,
            jnp.where(objness > -0.5, _bce_logits(obj_logit, 0.0), 0.0)).sum()
        match = jnp.where(valid, midx, -1)
        return pos_loss + obj_loss, objness, match

    loss, objness, match = jax.vmap(per_image)(x, gt_box, gt_label, gt_score)
    return {"Loss": loss, "ObjectnessMask": objness, "GTMatchMask": match}


@register_op("ssd_loss", nondiff=("GtBox", "GtLabel", "PriorBox",
                                  "PriorBoxVar"))
def _ssd_loss(ctx, ins, attrs):
    """SSD multibox loss (reference python/paddle/fluid/layers/detection.py
    ssd_loss): bipartite match on IoU, encode matched gts against priors,
    smooth-L1 location loss on positives, softmax CE on positives plus
    hard-mined negatives, normalized by the match count. Dense design: gt
    padded to (N, G, 4) with zero boxes marking padding."""
    loc = ins["Location"][0]             # (N, P, 4)
    conf = ins["Confidence"][0]          # (N, P, C)
    gt_box = ins["GtBox"][0]             # (N, G, 4) xyxy normalized
    gt_label = ins["GtLabel"][0]         # (N, G) int
    prior = ins["PriorBox"][0].reshape(-1, 4)     # (P, 4)
    pvar = (ins["PriorBoxVar"][0].reshape(-1, 4) if ins.get("PriorBoxVar")
            else jnp.full((prior.shape[0], 4), 1.0, loc.dtype))
    background = int(attrs.get("background_label", 0))
    overlap_threshold = float(attrs.get("overlap_threshold", 0.5))
    neg_overlap = float(attrs.get("neg_overlap", 0.5))
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    loc_weight = float(attrs.get("loc_loss_weight", 1.0))
    conf_weight = float(attrs.get("conf_loss_weight", 1.0))
    match_type = attrs.get("match_type", "per_prediction")
    mining_type = attrs.get("mining_type", "max_negative")
    normalize = bool(attrs.get("normalize", True))
    sample_size = int(attrs.get("sample_size", 0) or 0)
    if mining_type not in ("max_negative", "hard_example"):
        raise ValueError("ssd_loss: unsupported mining_type %r" % mining_type)
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    n, p, c = conf.shape

    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5

    def per_image(li, ci, gb, gl):
        valid = ((gb[:, 2] - gb[:, 0]) > 1e-6) & ((gb[:, 3] - gb[:, 1]) > 1e-6)
        area_g = jnp.maximum(gb[:, 2] - gb[:, 0], 0) * \
            jnp.maximum(gb[:, 3] - gb[:, 1], 0)
        area_p = jnp.maximum(pw, 0) * jnp.maximum(ph, 0)
        lt = jnp.maximum(gb[:, None, :2], prior[None, :, :2])
        rb = jnp.minimum(gb[:, None, 2:], prior[None, :, 2:])
        wh = jnp.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / jnp.maximum(area_g[:, None] + area_p[None, :] - inter,
                                  1e-10)
        iou = jnp.where(valid[:, None], iou, 0.0)
        match, mdist = _bipartite_match_single(iou, match_type,
                                               overlap_threshold)
        matched = match >= 0
        msafe = jnp.maximum(match, 0)

        # encode matched gt against priors (box_coder encode_center_size)
        g = gb[msafe]
        gw = g[:, 2] - g[:, 0]
        gh = g[:, 3] - g[:, 1]
        gcx = g[:, 0] + gw * 0.5
        gcy = g[:, 1] + gh * 0.5
        enc = jnp.stack([
            (gcx - pcx) / pw / pvar[:, 0],
            (gcy - pcy) / ph / pvar[:, 1],
            jnp.log(jnp.maximum(gw / pw, 1e-10)) / pvar[:, 2],
            jnp.log(jnp.maximum(gh / ph, 1e-10)) / pvar[:, 3]], -1)
        diff = li - enc
        ad = jnp.abs(diff)
        sl1 = jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5).sum(-1)
        loc_loss = jnp.where(matched, sl1, 0.0)

        tlabel = jnp.where(matched, gl[msafe], background)
        logz = jax.nn.logsumexp(ci, axis=-1)
        ce = logz - jnp.take_along_axis(ci, tlabel[:, None], -1)[:, 0]

        # hard negative mining on conf loss
        num_pos = matched.sum()
        if mining_type == "hard_example" and sample_size > 0:
            limit = jnp.asarray(sample_size, jnp.int32)
        else:
            limit = jnp.ceil(num_pos * neg_pos_ratio).astype(jnp.int32)
        is_neg = (~matched) & (mdist < neg_overlap)
        neg_score = jnp.where(is_neg, ce, -jnp.inf)
        order = jnp.argsort(-neg_score)
        rank = jnp.zeros((p,), jnp.int32).at[order].set(
            jnp.arange(p, dtype=jnp.int32))
        sel_neg = is_neg & (rank < limit)
        conf_loss = jnp.where(matched | sel_neg, ce, 0.0)
        return (conf_weight * conf_loss + loc_weight * loc_loss), num_pos

    loss, num_pos = jax.vmap(per_image)(loc, conf, gt_box, gt_label)
    if normalize:
        # reference normalizes by the batch-global matched count (ssd_loss
        # divides by reduce_sum of the loc target weights)
        loss = loss / jnp.maximum(num_pos.sum(), 1).astype(loss.dtype)
    return {"Loss": loss}
