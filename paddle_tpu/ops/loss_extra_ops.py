"""Loss long-tail kernels: center/edit-distance/NCE/hsigmoid/sampled-CE.

Reference parity: paddle/fluid/operators/{center_loss_op.h,
edit_distance_op.h, nce_op.h, hierarchical_sigmoid_op.h,
sample_logits_op (sampled_softmax_with_cross_entropy),
teacher_student_sigmoid_loss_op.h}. Sampling ops draw from the op's
deterministic PRNG (ctx.rng); the DP/tree recursions are lax.scan loops.
"""
import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _softplus(x):
    # max(x,0) + log1p(exp(-|x|)) — the reference's stable spelling
    return jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))


@register_op("teacher_student_sigmoid_loss", nondiff=("Label",))
def _ts_sigmoid_loss(ctx, ins, attrs):
    x = ins["X"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1).astype(jnp.float32)
    # label < -1: teacher-absent, no click | [-1,0): teacher-absent, click
    # [0,1): teacher z', no click          | >=1: teacher z'+1, click
    base = _softplus(x)
    case0 = base
    case1 = base - x
    case2 = base + base - x * label
    case3 = base - x + base - x * (label - 1.0)
    y = jnp.where(label < -1.0, case0,
                  jnp.where(label < 0.0, case1,
                            jnp.where(label < 1.0, case2, case3)))
    return {"Y": y.reshape(-1, 1)}


@register_op("center_loss", nondiff=("Label", "Centers", "CenterUpdateRate"))
def _center_loss(ctx, ins, attrs):
    """0.5*||x - center_{label}||^2; optionally update centers toward the
    batch means (ref center_loss_op.h: delta averaged by class count)."""
    x = ins["X"][0]
    label = ins["Label"][0].reshape(-1)
    centers = ins["Centers"][0]
    alpha = ins["CenterUpdateRate"][0].reshape(())
    picked = jnp.take(centers, label, axis=0)
    diff = x.astype(jnp.float32) - picked.astype(jnp.float32)
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if attrs.get("update_center", True):
        counts = jnp.zeros((centers.shape[0],), jnp.float32) \
            .at[label].add(1.0)
        accum = jnp.zeros_like(centers, shape=centers.shape,
                               dtype=jnp.float32).at[label].add(diff)
        update = accum / (1.0 + counts)[:, None]
        new_centers = centers + alpha.astype(centers.dtype) * \
            update.astype(centers.dtype)
    else:
        new_centers = centers
    return {"Loss": loss.astype(x.dtype),
            "SampleCenterDiff": diff.astype(x.dtype),
            "CentersOut": lax.stop_gradient(new_centers)}


@register_op("edit_distance", nondiff=("Hyps", "Refs", "HypsLength",
                                       "RefsLength"), differentiable=False)
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance per row (ref edit_distance_op.h), dense form:
    Hyps (N, Th), Refs (N, Tr) int ids + optional lengths."""
    hyps = ins["Hyps"][0]
    refs = ins["Refs"][0]
    n, th = hyps.shape
    tr = refs.shape[1]
    hl = ins["HypsLength"][0].reshape(-1) if ins.get("HypsLength") \
        else jnp.full((n,), th, jnp.int32)
    rl = ins["RefsLength"][0].reshape(-1) if ins.get("RefsLength") \
        else jnp.full((n,), tr, jnp.int32)

    def one(hyp, ref, m, r):
        # DP rows over the reference; positions past lengths are inert
        row0 = jnp.arange(tr + 1, dtype=jnp.float32)
        row0 = jnp.minimum(row0, r.astype(jnp.float32))

        def body(row, i):
            # i indexes hyp (1-based row of the DP table)
            valid_i = i < m

            def cell(carry, j):
                left = carry          # D[i][j-1]
                up = row[j]           # D[i-1][j]
                diag = row[j - 1]     # D[i-1][j-1]
                sub = diag + jnp.where(hyp[i] == ref[j - 1], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(up + 1.0, left + 1.0), sub)
                val = jnp.where(j <= r, val, left)   # clamp past ref len
                return val, val

            first = jnp.where(valid_i, (i + 1).astype(jnp.float32), row[0])
            _, rest = lax.scan(cell, first, jnp.arange(1, tr + 1))
            new_row = jnp.concatenate([first[None], rest])
            return jnp.where(valid_i, new_row, row), None

        row, _ = lax.scan(body, row0, jnp.arange(th))
        return row[jnp.minimum(r, tr)]

    dist = jax.vmap(one)(hyps, refs, hl, rl)
    if attrs.get("normalized", True):
        dist = dist / jnp.maximum(rl.astype(jnp.float32), 1.0)
    return {"Out": dist.reshape(n, 1),
            "SequenceNum": jnp.asarray([n], jnp.int32)}


def _sample_classes(key, num_total, num_samples, sampler):
    if sampler == "log_uniform":
        u = jax.random.uniform(key, (num_samples,))
        s = (jnp.exp(u * math.log(num_total + 1.0)) - 1.0).astype(jnp.int32)
        return jnp.clip(s, 0, num_total - 1)
    return jax.random.randint(key, (num_samples,), 0, num_total)


def _sampler_prob(classes, num_total, sampler):
    if sampler == "log_uniform":
        c = classes.astype(jnp.float32)
        return jnp.log((c + 2.0) / (c + 1.0)) / math.log(num_total + 1.0)
    return jnp.full(classes.shape, 1.0 / num_total)


@register_op("nce", nondiff=("Label",), uses_rng=True)
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation (ref nce_op.h): binary logistic on the
    true class vs num_neg sampled noise classes, scores corrected by
    log(k*q(class))."""
    x = ins["Input"][0]                       # (N, D)
    label = ins["Label"][0].reshape(-1)       # (N,)
    w = ins["Weight"][0]                      # (C, D)
    b = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    num_total = int(attrs["num_total_classes"])
    num_neg = int(attrs.get("num_neg_samples", 10))
    sampler = attrs.get("sampler", "uniform")
    neg = _sample_classes(ctx.rng(), num_total, num_neg, sampler)

    def score(cls_rows):
        s = jnp.einsum("nd,kd->nk", x.astype(jnp.float32),
                       jnp.take(w, cls_rows, axis=0).astype(jnp.float32))
        if b is not None:
            s = s + jnp.take(b, cls_rows)[None, :]
        return s

    s_true = jnp.sum(x.astype(jnp.float32) *
                     jnp.take(w, label, axis=0).astype(jnp.float32),
                     axis=1)
    if b is not None:
        s_true = s_true + jnp.take(b, label)
    logq_true = jnp.log(num_neg *
                        _sampler_prob(label, num_total, sampler) + 1e-20)
    logq_neg = jnp.log(num_neg *
                       _sampler_prob(neg, num_total, sampler) + 1e-20)
    s_neg = score(neg) - logq_neg[None, :]
    s_pos = s_true - logq_true
    loss = _softplus(-s_pos) + jnp.sum(_softplus(s_neg), axis=1)
    return {"Cost": loss.reshape(-1, 1).astype(x.dtype)}


@register_op("hierarchical_sigmoid", nondiff=("Label",))
def _hsigmoid(ctx, ins, attrs):
    """Default complete-binary-tree hierarchical sigmoid (ref
    hierarchical_sigmoid_op.h SimpleCode): leaf code = label+C; path nodes
    are the heap ancestors code>>k, their row index node-1; the bit stepped
    through selects the sigmoid target."""
    x = ins["X"][0]                           # (N, D)
    label = ins["Label"][0].reshape(-1)       # (N,)
    w = ins["W"][0]                           # (C-1, D) non-leaf weights
    b = ins["Bias"][0] if ins.get("Bias") else None
    num_classes = int(attrs["num_classes"])
    depth = max(1, int(math.ceil(math.log2(num_classes))))
    code = label + num_classes                # heap leaf id

    # O(depth) per example: gather only the path nodes' weight rows and
    # take batched dots — never the dense (N, C-1) logits matrix
    xf = x.astype(jnp.float32)
    loss = jnp.zeros(label.shape, jnp.float32)
    path_scores = []
    for k in range(1, depth + 1):
        node = code >> k                      # ancestor at height k
        valid = node >= 1
        bit = ((code >> (k - 1)) & 1).astype(jnp.float32)
        idx = jnp.clip(node - 1, 0, num_classes - 2)
        wr = jnp.take(w, idx, axis=0).astype(jnp.float32)   # (N, D)
        s = jnp.sum(xf * wr, axis=1)
        if b is not None:
            s = s + jnp.take(b.reshape(-1), idx)
        path_scores.append(jnp.where(valid, s, 0.0))
        # sigmoid CE with target = bit
        term = _softplus(s) - s * bit
        loss = loss + jnp.where(valid, term, 0.0)
    return {"Out": loss.reshape(-1, 1).astype(x.dtype),
            "PreOut": jnp.stack(path_scores, axis=1).astype(x.dtype)}


@register_op("sampled_softmax_with_cross_entropy", nondiff=("Label",),
             uses_rng=True)
def _sampled_softmax_ce(ctx, ins, attrs):
    """Softmax CE over the true class + num_samples sampled classes (ref
    sample_logits_op): sampled logits corrected by log q, true class at
    column 0."""
    logits = ins["Logits"][0]                 # (N, C)
    label = ins["Label"][0].reshape(-1)
    num_total = logits.shape[-1]
    num_samples = int(attrs.get("num_samples", 64))
    use_q = bool(attrs.get("use_customized_samples", False))
    del use_q  # custom sample feed not supported (documented)
    sampler = "log_uniform"
    neg = _sample_classes(ctx.rng(), num_total, num_samples, sampler)
    lt = jnp.take_along_axis(logits, label[:, None], axis=1)  # (N,1)
    ln = jnp.take(logits, neg, axis=1)                        # (N,S)
    qn = jnp.log(_sampler_prob(neg, num_total, sampler) + 1e-20)
    qt = jnp.log(_sampler_prob(label, num_total, sampler) + 1e-20)
    # mask accidental hits of the true class among samples
    hit = neg[None, :] == label[:, None]
    ln = jnp.where(hit, -1e30, ln - qn[None, :])
    z = jnp.concatenate([lt - qt[:, None], ln], axis=1)
    logp = jax.nn.log_softmax(z, axis=1)
    return {"Loss": (-logp[:, :1]).astype(logits.dtype)}
