"""Kernels for contrib layers (ref python/paddle/fluid/contrib/layers/nn.py
+ paddle/fluid/operators/{shuffle_batch,tree_conv,match_matrix_tensor,
sequence_topk_avg_pooling,var_conv_2d}* ops).

Dense TPU designs: ragged/LoD inputs become padded tensors + explicit
length vectors (the package-wide convention from layers/sequence_lod.py),
and tree structure becomes a dense adjacency matrix so patch extraction
is matmuls on the MXU instead of per-node gathers.
"""
import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("shuffle_batch", uses_rng=True, nondiff=("Seed",))
def _shuffle_batch(ctx, ins, attrs):
    """Random row permutation (ref operators/shuffle_batch_op.h): returns
    the shuffled tensor and the permutation used (for unshuffling)."""
    x = ins["X"][0]
    seed = attrs.get("startup_seed", -1)
    key = jax.random.PRNGKey(seed) if seed >= 0 else ctx.rng()
    perm = jax.random.permutation(key, x.shape[0])
    return {"Out": jnp.take(x, perm, axis=0),
            "ShuffleIdx": perm.astype(jnp.int64)}


@register_op("match_matrix_tensor", nondiff=())
def _match_matrix_tensor(ctx, ins, attrs):
    """Bilinear match matrix (ref contrib nn.py:221): x (N, Tx, D1),
    y (N, Ty, D2), W (D1, C, D2) -> out (N, C, Tx, Ty) where
    out[n,c] = x[n] @ W[:,c,:] @ y[n]^T.  One einsum => two MXU matmuls."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["W"][0]
    out = jnp.einsum("btd,dce,bse->bcts", x, w, y,
                     preferred_element_type=jnp.float32)
    return {"Out": out.astype(x.dtype)}


@register_op("sequence_topk_avg_pooling", nondiff=("RowLen", "ColLen"))
def _sequence_topk_avg_pooling(ctx, ins, attrs):
    """Top-k average pooling over the column axis of a match matrix
    (ref contrib nn.py:304).  x: (N, C, Tx, Ty); row_len/col_len: (N,)
    valid extents.  For each k in topks, average of the k largest valid
    column scores -> out (N, Tx, C * len(topks)), rows past row_len
    zeroed."""
    x = ins["X"][0]
    row_len = ins["RowLen"][0].astype(jnp.int32)
    col_len = ins["ColLen"][0].astype(jnp.int32)
    topks = tuple(int(k) for k in attrs["topks"])
    n, c, tx, ty = x.shape
    col_mask = jnp.arange(ty)[None, None, None, :] < \
        col_len[:, None, None, None]
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, x.dtype)
    masked = jnp.where(col_mask, x, neg)
    # descending sort once; every k reuses the prefix sums
    srt = -jnp.sort(-masked, axis=-1)
    valid = col_mask.astype(x.dtype)  # count of valid cols per row
    n_valid = jnp.sum(valid, axis=-1, keepdims=True)  # (N,C,Tx,1)
    csum = jnp.cumsum(jnp.where(srt <= neg / 2, 0.0, srt), axis=-1)
    outs = []
    for k in topks:
        kk = jnp.minimum(jnp.asarray(float(k), x.dtype),
                         jnp.maximum(n_valid[..., 0], 1.0))
        idx = jnp.clip(kk.astype(jnp.int32) - 1, 0, ty - 1)
        topsum = jnp.take_along_axis(csum, idx[..., None], axis=-1)[..., 0]
        outs.append(topsum / jnp.asarray(float(k), x.dtype))
    out = jnp.stack(outs, axis=-1)            # (N, C, Tx, K)
    out = out.transpose(0, 2, 1, 3).reshape(n, tx, c * len(topks))
    row_mask = (jnp.arange(tx)[None, :] < row_len[:, None])[..., None]
    return {"Out": jnp.where(row_mask, out, 0.0).astype(x.dtype)}


@register_op("var_conv_2d", nondiff=("RowLen", "ColLen"))
def _var_conv_2d(ctx, ins, attrs):
    """Variable-size conv2d (ref contrib nn.py:105): a dense conv over
    the padded batch, with outputs beyond each sample's valid (row, col)
    extent zeroed — numerically identical to per-sample convs for
    'same'-style interiors and fully XLA-fusible."""
    x, w = ins["X"][0], ins["W"][0]
    row_len = ins["RowLen"][0].astype(jnp.int32)
    col_len = ins["ColLen"][0].astype(jnp.int32)
    stride = attrs.get("stride", [1, 1])
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding="SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    h_out, w_out = out.shape[2], out.shape[3]
    r = (row_len + stride[0] - 1) // stride[0]
    c = (col_len + stride[1] - 1) // stride[1]
    rmask = jnp.arange(h_out)[None, None, :, None] < r[:, None, None, None]
    cmask = jnp.arange(w_out)[None, None, None, :] < c[:, None, None, None]
    return {"Out": jnp.where(rmask & cmask, out, 0.0).astype(x.dtype)}


def _tree_eta(depth, max_depth, pos, n_sib):
    """Continuous-binary-tree interpolation weights (TBCNN, Mou et al.):
    eta_t favors patch roots, eta_l/eta_r split by sibling position."""
    d = depth.astype(jnp.float32)
    eta_t = jnp.where(max_depth > 1, (max_depth - d) / max_depth, 1.0)
    frac = jnp.where(n_sib > 1, (pos - 1.0) / jnp.maximum(n_sib - 1.0, 1.0),
                     0.5)
    eta_r = (1.0 - eta_t) * frac
    eta_l = (1.0 - eta_t) * (1.0 - frac)
    return eta_t, eta_l, eta_r


@register_op("tree_conv", nondiff=("EdgeSet",))
def _tree_conv(ctx, ins, attrs):
    """Tree-based convolution (ref contrib nn.py:372,
    operators/tree_conv_op.*): nodes (N, M, F), edge_set (N, E, 2) int
    rows [parent, child] (negative = padding), filter (F, 3, H, K).

    Dense design: one (M, M) descendant matrix per depth level, built by
    repeated multiplication of the child adjacency — patch gathering
    becomes batched matmuls.  Out: (N, M, H, K) with max-pooling over
    patch members folded into the weighted sum per the TBCNN paper.
    """
    nodes, edges, filt = ins["NodesVector"][0], ins["EdgeSet"][0], \
        ins["Filter"][0]
    max_depth = int(attrs.get("max_depth", 2))
    n, m, f = nodes.shape
    _, _, h, k = filt.shape
    e = edges.shape[1]

    parent = edges[:, :, 0].astype(jnp.int32)
    child = edges[:, :, 1].astype(jnp.int32)
    valid = (parent >= 0) & (child >= 0)
    p_safe = jnp.where(valid, parent, 0)
    c_safe = jnp.where(valid, child, 0)
    # child adjacency A[b, p, c] = 1, plus sibling position of c under p
    oh_p = jax.nn.one_hot(p_safe, m, dtype=jnp.float32) * \
        valid[..., None]
    oh_c = jax.nn.one_hot(c_safe, m, dtype=jnp.float32) * \
        valid[..., None]
    adj = jnp.einsum("bep,bec->bpc", oh_p, oh_c)
    # sibling order = edge order: position of each child among its
    # parent's earlier edges
    order = jnp.cumsum(oh_p, axis=1)  # (N, E, M) running count per parent
    pos_e = jnp.einsum("bem,bem->be", order, oh_p)  # 1-based position
    pos = jnp.einsum("be,bep,bec->bpc", pos_e, oh_p, oh_c)
    n_sib = jnp.sum(adj, axis=2, keepdims=True)  # (N, M, 1)

    wt, wl, wr = filt[:, 0], filt[:, 1], filt[:, 2]  # (F, H, K)
    md = jnp.asarray(float(max_depth), jnp.float32)

    def level_feature(level_adj, level_pos, depth):
        eta_t, eta_l, eta_r = _tree_eta(
            jnp.asarray(float(depth), jnp.float32), md, level_pos,
            jnp.broadcast_to(n_sib, level_pos.shape))
        mask = (level_adj > 0).astype(jnp.float32)
        feats = []
        for eta, w in ((eta_t, wt), (eta_l, wl), (eta_r, wr)):
            gathered = jnp.einsum("bpc,bcf->bpf", eta * mask,
                                  nodes.astype(jnp.float32))
            feats.append(jnp.einsum("bpf,fhk->bphk", gathered, w))
        return feats[0] + feats[1] + feats[2]

    # depth 0: the node itself is the patch root (eta_t = 1)
    out = jnp.einsum("bmf,fhk->bmhk", nodes.astype(jnp.float32), wt)
    level_adj, level_pos = adj, pos
    for depth in range(1, max_depth):
        out = out + level_feature(level_adj, level_pos, depth)
        if depth + 1 < max_depth:
            # descendants one level deeper; positions propagate from the
            # first hop (the sibling split happens at the top branching)
            level_adj = jnp.einsum("bpc,bcd->bpd", level_adj, adj)
            level_pos = jnp.einsum("bpc,bcd->bpd", pos, (adj > 0) *
                                   jnp.float32(1.0)) + level_pos * 0.0
            level_pos = jnp.where(level_adj > 0,
                                  jnp.maximum(level_pos, 1.0), 0.0)
    return {"Out": out.astype(nodes.dtype)}
