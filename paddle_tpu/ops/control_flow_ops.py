"""Control-flow op kernels: cond / while_loop via lax.

Reference parity: paddle/fluid/operators/controlflow/{conditional_block_op,
while_op}.cc + python layers/control_flow.py. The reference executes
sub-blocks with a nested executor on the host; here sub-blocks are traced
into lax.cond / lax.while_loop so control flow stays ON DEVICE inside the
single compiled step — no host round-trips (the TPU-idiomatic form).

Gradients flow through ``cond`` and bounded ``while_loop``: the layer
builder lifts every outer var a sub-block reads into an explicit `Captures`
input (layers/control_flow.py), so the generic trace-time vjp pairing sees
them as arguments (reference: conditional_block_grad_op / while_grad_op).
Unbounded ``while_loop`` stays forward-only — XLA cannot reverse-diff a
dynamic trip count; pass maximum_trip_count for the differentiable form.
"""
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _subblock_env(ctx, ins, attrs):
    """Environment for tracing a sub-block: outer-env snapshot overlaid with
    the op's explicit captures. The explicit values take precedence — under
    jax.vjp they are the traced arguments gradients flow back to, while the
    outer-env copies of the same names would be opaque closure constants."""
    env = dict(ctx.outer_env or {})
    env.update(zip(attrs.get("capture_names", []), ins.get("Captures", [])))
    return env


def _branch_fn(ctx, block, out_names, env_snapshot):
    def fn(_):
        local = dict(env_snapshot)
        ctx.trace_block(block, local)
        return tuple(local[n] for n in out_names)
    return fn


@register_op("cond", uses_subblock=True, nondiff=("Cond",))
def _cond(ctx, ins, attrs):
    pred = ins["Cond"][0].reshape(())
    program = ctx.program
    tb = program.block(attrs["true_block"])
    fb = program.block(attrs["false_block"])
    env = _subblock_env(ctx, ins, attrs)
    outs = lax.cond(pred,
                    _branch_fn(ctx, tb, attrs["true_out_names"], env),
                    _branch_fn(ctx, fb, attrs["false_out_names"], env),
                    operand=0)
    return {"Out": list(outs)}


@register_op("while_loop", uses_subblock=True, nondiff=("LoopVars",),
             differentiable=False)
def _while_loop(ctx, ins, attrs):
    program = ctx.program
    cond_block = program.block(attrs["cond_block"])
    body_block = program.block(attrs["body_block"])
    var_names = attrs["loop_var_names"]
    cond_out = attrs["cond_out_name"]
    env = _subblock_env(ctx, ins, attrs)

    def cond_fn(vals):
        local = dict(env)
        local.update(zip(var_names, vals))
        ctx.trace_block(cond_block, local)
        return local[cond_out].reshape(())

    def body_fn(vals):
        local = dict(env)
        local.update(zip(var_names, vals))
        ctx.trace_block(body_block, local)
        return tuple(local[n] for n in var_names)

    outs = lax.while_loop(cond_fn, body_fn, tuple(ins["LoopVars"]))
    return {"Out": list(outs)}


@register_op("bounded_while", uses_subblock=True)
def _bounded_while(ctx, ins, attrs):
    """Differentiable while: lax.scan of max_trip_count steps; once the cond
    turns false the carry passes through unchanged (jnp.where), which is a
    fixpoint since blocks are pure — so the result equals the dynamic loop
    whenever the true trip count fits the bound."""
    program = ctx.program
    cond_block = program.block(attrs["cond_block"])
    body_block = program.block(attrs["body_block"])
    var_names = attrs["loop_var_names"]
    cond_out = attrs["cond_out_name"]
    env = _subblock_env(ctx, ins, attrs)

    def run_body(vals):
        local = dict(env)
        local.update(zip(var_names, vals))
        ctx.trace_block(body_block, local)
        return tuple(local[n] for n in var_names)

    def step(vals, _):
        local = dict(env)
        local.update(zip(var_names, vals))
        ctx.trace_block(cond_block, local)
        pred = local[cond_out].reshape(())
        # lax.cond (not jnp.where): its vjp differentiates only the taken
        # branch, so finished iterations contribute an exact identity —
        # a body with a non-finite Jacobian at the fixpoint (e.g. sqrt at
        # 0) cannot poison gradients with 0*inf=NaN.
        return lax.cond(pred, run_body, lambda vs: vs, vals), None

    vals, _ = lax.scan(step, tuple(ins["LoopVars"]), None,
                       length=int(attrs["max_trip_count"]))
    return {"Out": list(vals)}


@register_op("recurrent_scan", uses_subblock=True)
def _recurrent_scan(ctx, ins, attrs):
    """Differentiable recurrence: lax.scan over a sub-block step function.

    inputs:  Seq    — per-step sequences, scanned over axis `time_axis` (=0)
             Init   — initial carry values
             Extra  — loop-invariant captures (weights etc.)
    The sub-block reads vars named attrs[seq_var_names][i] (current step
    slice), attrs[carry_var_names][i], attrs[extra_var_names][i] and must
    define attrs[carry_out_names] and attrs[step_out_names].
    Grad support comes for free: the whole kernel is differentiable, so the
    generic vjp grad op handles BPTT (reference: recurrent_op.cc backward).
    """
    program = ctx.program
    block = program.block(attrs["sub_block"])
    seqs = ins.get("Seq", [])
    init = ins.get("Init", [])
    extra = ins.get("Extra", [])
    seq_names = attrs.get("seq_var_names", [])
    carry_names = attrs.get("carry_var_names", [])
    extra_names = attrs.get("extra_var_names", [])
    carry_out = attrs.get("carry_out_names", [])
    step_out = attrs.get("step_out_names", [])
    reverse = attrs.get("is_reverse", False)

    def step(carry, xs):
        local = dict(zip(extra_names, extra))
        local.update(zip(carry_names, carry))
        local.update(zip(seq_names, xs))
        ctx.trace_block(block, local)
        new_carry = tuple(local[n] for n in carry_out)
        outs = tuple(local[n] for n in step_out)
        return new_carry, outs

    carry, ys = lax.scan(step, tuple(init), tuple(seqs), reverse=reverse)
    return {"FinalCarry": list(carry), "SeqOut": list(ys)}


@register_op("select_input", nondiff=("Mask",))
def _select_input(ctx, ins, attrs):
    mask = ins["Mask"][0].reshape(()).astype(jnp.int32)
    xs = ins["X"]
    out = xs[0]
    for i, x in enumerate(xs[1:], 1):
        out = lax.select(mask == i, x, out)
    return {"Out": out}


@register_op("remat_block", uses_subblock=True)
def _remat_block(ctx, ins, attrs):
    """Rematerialized segment: the sub-block is traced under
    jax.checkpoint, so XLA drops its intermediates after forward and
    recomputes them in backward — HBM for FLOPs, the TPU-native form of
    the reference's RecomputeOptimizer (reference: recompute pass in
    optimizer.py). Differentiable: the generic vjp grad op sees one
    checkpointed function."""
    import jax
    program = ctx.program
    block = program.block(attrs["sub_block"])
    in_names = attrs["in_names"]
    out_names = attrs["out_names"]
    vals = ins["In"]

    def fn(*vals):
        local = dict(zip(in_names, vals))
        ctx.trace_block(block, local)
        return tuple(local[n] for n in out_names)

    outs = jax.checkpoint(fn)(*vals)
    return {"Out": list(outs)}


@register_op("print")
def _print(ctx, ins, attrs):
    """Identity with an in-step debug print (ref print_op.cc); gradients
    pass straight through."""
    import jax
    x = ins["In"][0]
    n = int(attrs.get("summarize", 20))
    # message goes in as an argument, not part of the format string —
    # user text may contain braces
    jax.debug.print("{m} {v}", m=str(attrs.get("message", "")),
                    v=x.reshape(-1)[:n] if n > 0 else x)
    return {"Out": x}
