"""Recurrent op kernels: LSTM / GRU over whole sequences via lax.scan.

Reference parity: paddle/fluid/operators/{lstm_op,gru_op}.cc. The reference
consumes LoD (ragged) sequences; the TPU-native design is batch-major dense
(N, T, ...) with optional masks — static shapes so XLA can pipeline the scan
across the MXU. Differentiable end-to-end (BPTT = vjp of lax.scan).

Activations follow the reference attr names: gate/cell/candidate activation.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


@register_op("lstm_seq")
def _lstm_seq(ctx, ins, attrs):
    """ins: Input (N,T,4H) — already projected by an fc (reference
    dynamic_lstm takes the same pre-projected layout); Weight (H,4H)
    recurrent weights; Bias (4H); optional H0/C0 (N,H).
    outs: Hidden (N,T,H), Cell (N,T,H), LastH, LastC.
    Gate order matches reference lstm_op: i, f, c(candidate), o."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    n, t, h4 = x.shape
    h = h4 // 4
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((n, h), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((n, h), x.dtype)
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]

    def step(carry, xt):
        hp, cp = carry
        gates = xt + hp @ w
        if bias is not None:
            gates = gates + bias
        i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
        i, f, o = gate_act(i), gate_act(f), gate_act(o)
        c = f * cp + i * cand_act(c_hat)
        hh = o * cell_act(c)
        return (hh, c), (hh, c)

    xs = jnp.swapaxes(x, 0, 1)  # (T, N, 4H)
    if attrs.get("is_reverse", False):
        xs = jnp.flip(xs, 0)
    (last_h, last_c), (hs, cs) = lax.scan(step, (h0, c0), xs)
    if attrs.get("is_reverse", False):
        hs, cs = jnp.flip(hs, 0), jnp.flip(cs, 0)
    return {"Hidden": jnp.swapaxes(hs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1),
            "LastH": last_h, "LastC": last_c}


@register_op("gru_seq")
def _gru_seq(ctx, ins, attrs):
    """ins: Input (N,T,3H) pre-projected; Weight (H,3H) recurrent
    [update,reset | candidate]; optional Bias (3H), H0.
    Gate math matches reference gru_op (gate_weight (H,2H) + state_weight
    (H,H) concatenated)."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    n, t, h3 = x.shape
    h = h3 // 3
    w_gate = w[:, :2 * h]
    w_cand = w[:, 2 * h:]
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((n, h), x.dtype)
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]

    def step(hp, xt):
        if bias is not None:
            xt = xt + bias
        ur = gate_act(xt[:, :2 * h] + hp @ w_gate)
        u, r = ur[:, :h], ur[:, h:]
        c = cand_act(xt[:, 2 * h:] + (r * hp) @ w_cand)
        hh = u * hp + (1 - u) * c
        return hh, hh

    xs = jnp.swapaxes(x, 0, 1)
    if attrs.get("is_reverse", False):
        xs = jnp.flip(xs, 0)
    last_h, hs = lax.scan(step, h0, xs)
    if attrs.get("is_reverse", False):
        hs = jnp.flip(hs, 0)
    return {"Hidden": jnp.swapaxes(hs, 0, 1), "LastH": last_h}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """Single GRU step (reference gru_unit_op.cc)."""
    x = ins["Input"][0]          # (N, 3H)
    hp = ins["HiddenPrev"][0]    # (N, H)
    w = ins["Weight"][0]         # (H, 3H)
    h = hp.shape[-1]
    if ins.get("Bias"):
        x = x + ins["Bias"][0].reshape(-1)
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    ur = gate_act(x[:, :2 * h] + hp @ w[:, :2 * h])
    u, r = ur[:, :h], ur[:, h:]
    c = cand_act(x[:, 2 * h:] + (r * hp) @ w[:, 2 * h:])
    hh = u * hp + (1 - u) * c
    return {"Hidden": hh, "Gate": jnp.concatenate([ur, c], -1),
            "ResetHiddenPrev": r * hp}
