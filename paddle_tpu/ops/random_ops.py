"""Random op kernels — stateless TPU-friendly PRNG.

Reference parity: paddle/fluid/operators/{gaussian_random_op,
uniform_random_op,truncated_gaussian_random_op,randint_op}.cc.
The reference uses stateful per-device generators; here keys derive
deterministically from (program.random_seed, step, op.desc_id) via
threefry fold-ins (framework/trace.py), so results are reproducible and
identical under any sharding.
"""
import jax
import jax.numpy as jnp

from .registry import register_op
from ..framework.dtypes import to_jax_dtype


def _key(ctx, attrs):
    seed = attrs.get("seed", 0)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.rng()


@register_op("gaussian_random", uses_rng=True)
def _gaussian_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jax_dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * \
        jax.random.normal(_key(ctx, attrs), shape, dtype=jnp.float32)
    return {"Out": out.astype(dtype)}


@register_op("uniform_random", uses_rng=True)
def _uniform_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jax_dtype(attrs.get("dtype", "float32"))
    out = jax.random.uniform(_key(ctx, attrs), shape, dtype=jnp.float32,
                             minval=attrs.get("min", -1.0),
                             maxval=attrs.get("max", 1.0))
    return {"Out": out.astype(dtype)}


@register_op("truncated_gaussian_random", uses_rng=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jax_dtype(attrs.get("dtype", "float32"))
    out = jax.random.truncated_normal(_key(ctx, attrs), -2.0, 2.0, shape,
                                      dtype=jnp.float32)
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * out
    return {"Out": out.astype(dtype)}


@register_op("randint", uses_rng=True)
def _randint(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jax_dtype(attrs.get("dtype", "int64"))
    return {"Out": jax.random.randint(_key(ctx, attrs), shape,
                                      attrs.get("low", 0),
                                      attrs.get("high", 100)).astype(dtype)}


@register_op("randperm", uses_rng=True)
def _randperm(ctx, ins, attrs):
    n = attrs["n"]
    dtype = to_jax_dtype(attrs.get("dtype", "int64"))
    return {"Out": jax.random.permutation(_key(ctx, attrs), n).astype(dtype)}


@register_op("bernoulli", uses_rng=True)
def _bernoulli(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": jax.random.bernoulli(_key(ctx, attrs), x).astype(x.dtype)}


@register_op("sampling_id", uses_rng=True, nondiff=("X",))
def _sampling_id(ctx, ins, attrs):
    x = ins["X"][0]  # (batch, num_classes) probabilities
    return {"Out": jax.random.categorical(
        _key(ctx, attrs), jnp.log(jnp.maximum(x, 1e-20)), axis=-1)}
