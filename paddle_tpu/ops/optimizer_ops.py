"""Optimizer update op kernels.

Reference parity: paddle/fluid/operators/optimizers/{sgd_op,momentum_op,
adam_op,adagrad_op,rmsprop_op,adamax_op,lamb_op,lars_momentum_op,ftrl_op,
decayed_adagrad_op,...}.cc.

These ops are appended by paddle_tpu.optimizer.*.minimize() and run INSIDE
the same jitted step as forward/backward — XLA fuses the whole update, and
because the Executor donates parameter buffers the update is in-place in HBM.
All slot names match the reference so programs read identically.
"""
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from . import pallas_dispatch as _pd


def _p(ins, slot):
    return ins[slot][0]


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    return {"ParamOut": p - lr.reshape(()).astype(p.dtype) * g}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Velocity")
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    mu = attrs["mu"]
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": p_new, "VelocityOut": v_new}


@register_op("lars_momentum")
def _lars_momentum(ctx, ins, attrs):
    p, g, v = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Velocity")
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    mu = attrs["mu"]
    coeff = attrs.get("lars_coeff", 0.001)
    decay = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 1e-9)
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(pn > 0,
                         lr * coeff * pn / (gn + decay * pn + eps), lr)
    v_new = mu * v + local_lr * (g + decay * p)
    return {"ParamOut": p - v_new, "VelocityOut": v_new}


def _pallas_adam(p, gf, m1, m2, lr, b1p, b2p, b1, b2, eps, cfg):
    """BuildStrategy.use_pallas={"adam"}: the whole m/v/param
    read-modify-write in ONE Pallas pass per parameter instead of the
    elementwise XLA chain below. Returns (p_new, m1_new, m2_new) or None
    when the autotune cache routed this shape to XLA / the parameter is
    too small to tile — caller keeps the XLA chain."""
    from .pallas.fused_adam import fused_adam
    # keyed on the FLATTENED size — the kernel tiles the flat lane
    # layout, and tools/autotune.py sweeps flat shapes, so a (64,128)
    # param and an (8192,) sweep meet on the same cache key
    impl, tuned = _pd.choose(cfg, "adam", (int(p.size),), p.dtype)
    if impl == "xla":
        return None
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    return fused_adam(p, gf, m1, m2, lr_t, beta1=b1, beta2=b2,
                      eps=eps, interpret=cfg.interpret, **(tuned or {}))


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    m1, m2 = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p = _p(ins, "Beta1Pow").reshape(()).astype(jnp.float32)
    b2p = _p(ins, "Beta2Pow").reshape(()).astype(jnp.float32)
    lr = _p(ins, "LearningRate").reshape(()).astype(jnp.float32)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    gf = g.astype(jnp.float32)
    cfg = _pd.enabled("adam")
    if cfg is not None and not attrs.get("lazy_mode"):
        fused = _pallas_adam(p, gf, m1, m2, lr, b1p, b2p, b1, b2, eps,
                             cfg)
        if fused is not None:
            return {"ParamOut": fused[0], "Moment1Out": fused[1],
                    "Moment2Out": fused[2],
                    "Beta1PowOut":
                        (b1p * b1).reshape(ins["Beta1Pow"][0].shape),
                    "Beta2PowOut":
                        (b2p * b2).reshape(ins["Beta2Pow"][0].shape)}
    m1n = b1 * m1 + (1 - b1) * gf
    m2n = b2 * m2 + (1 - b2) * gf * gf
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p.astype(jnp.float32) - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    if attrs.get("lazy_mode") and g.ndim >= 2:
        # reference lazy-mode adam (adam_op.h sparse path): rows absent
        # from the batch — all-zero grad rows for an embedding's dense
        # scatter-add gradient — keep their param AND moments untouched
        touched = jnp.any(gf != 0, axis=tuple(range(1, g.ndim)),
                          keepdims=True)
        m1n = jnp.where(touched, m1n, m1)
        m2n = jnp.where(touched, m2n, m2)
        p_new = jnp.where(touched, p_new, p.astype(jnp.float32))
    return {"ParamOut": p_new.astype(p.dtype), "Moment1Out": m1n,
            "Moment2Out": m2n,
            "Beta1PowOut": (b1p * b1).reshape(ins["Beta1Pow"][0].shape),
            "Beta2PowOut": (b2p * b2).reshape(ins["Beta2Pow"][0].shape)}


@register_op("adamw")
def _adamw(ctx, ins, attrs):
    outs = _adam(ctx, ins, attrs)
    coeff = attrs.get("coeff", 0.01)
    lr = _p(ins, "LearningRate").reshape(()).astype(jnp.float32)
    p = _p(ins, "Param")
    decayed = (outs["ParamOut"].astype(jnp.float32) -
               lr * coeff * p.astype(jnp.float32))
    g = _p(ins, "Grad")
    if attrs.get("lazy_mode") and g.ndim >= 2:
        # untouched rows must stay frozen — no decoupled decay either
        touched = jnp.any(g.astype(jnp.float32) != 0,
                          axis=tuple(range(1, g.ndim)), keepdims=True)
        decayed = jnp.where(touched, decayed,
                            outs["ParamOut"].astype(jnp.float32))
    outs["ParamOut"] = decayed.astype(p.dtype)
    return outs


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, m = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Moment")
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    eps = attrs.get("epsilon", 1e-6)
    m_new = m + g * g
    return {"ParamOut": p - lr * g / (jnp.sqrt(m_new) + eps),
            "MomentOut": m_new}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g, m = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Moment")
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * g * g
    return {"ParamOut": p - lr * g / (jnp.sqrt(m_new) + eps),
            "MomentOut": m_new}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    ms, mom = _p(ins, "MeanSquare"), _p(ins, "Moment")
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * g * g
    if attrs.get("centered", False):
        mg = _p(ins, "MeanGrad")
        mg_new = rho * mg + (1 - rho) * g
        mom_new = momentum * mom + lr * g / jnp.sqrt(
            ms_new - mg_new * mg_new + eps)
        return {"ParamOut": p - mom_new, "MeanSquareOut": ms_new,
                "MomentOut": mom_new, "MeanGradOut": mg_new}
    mom_new = momentum * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": p - mom_new, "MeanSquareOut": ms_new,
            "MomentOut": mom_new}


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    m, inf = _p(ins, "Moment"), _p(ins, "InfNorm")
    b1p = _p(ins, "Beta1Pow").reshape(()).astype(jnp.float32)
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    lr_t = lr / (1 - b1p)
    return {"ParamOut": p - lr_t * m_new / (inf_new + eps),
            "MomentOut": m_new, "InfNormOut": inf_new}


@register_op("lamb")
def _lamb(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    m1, m2 = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p = _p(ins, "Beta1Pow").reshape(()).astype(jnp.float32)
    b2p = _p(ins, "Beta2Pow").reshape(()).astype(jnp.float32)
    lr = _p(ins, "LearningRate").reshape(()).astype(jnp.float32)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m1n = b1 * m1 + (1 - b1) * gf
    m2n = b2 * m2 + (1 - b2) * gf * gf
    m1h = m1n / (1 - b1p)
    m2h = m2n / (1 - b2p)
    r = m1h / (jnp.sqrt(m2h) + eps) + wd * pf
    pn = jnp.sqrt(jnp.sum(pf * pf))
    rn = jnp.sqrt(jnp.sum(r * r))
    ratio = jnp.where((pn > 0) & (rn > 0), pn / rn, 1.0)
    p_new = pf - lr * ratio * r
    return {"ParamOut": p_new.astype(p.dtype), "Moment1Out": m1n,
            "Moment2Out": m2n,
            "Beta1PowOut": (b1p * b1).reshape(ins["Beta1Pow"][0].shape),
            "Beta2PowOut": (b2p * b2).reshape(ins["Beta2Pow"][0].shape)}


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    sq, lin = _p(ins, "SquaredAccumulator"), _p(ins, "LinearAccumulator")
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    sq_new = sq + g * g
    sigma = (jnp.power(sq_new, -power) - jnp.power(sq, -power)) / lr
    lin_new = lin + g - sigma * p
    quad = jnp.power(sq_new, -power) / lr + 2 * l2
    pre = jnp.clip(lin_new, -l1, l1) - lin_new
    p_new = jnp.where(jnp.abs(lin_new) > l1, pre / quad, 0.0)
    return {"ParamOut": p_new, "SquaredAccumOut": sq_new,
            "LinearAccumOut": lin_new}


@register_op("dpsgd", uses_rng=True)
def _dpsgd(ctx, ins, attrs):
    import jax
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    lr = _p(ins, "LearningRate").reshape(()).astype(p.dtype)
    clip = attrs.get("clip", 10.0)
    sigma = attrs.get("sigma", 1.0)
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g * jnp.minimum(1.0, clip / jnp.maximum(gn, 1e-12))
    noise = sigma * clip * jax.random.normal(ctx.rng(), g.shape, g.dtype)
    return {"ParamOut": p - lr * (g + noise)}


@register_op("average_accumulates", differentiable=False)
def _average_accumulates(ctx, ins, attrs):
    """Sliding-window parameter-sum accumulators for ModelAverage.

    Reference parity: paddle/fluid/operators/average_accumulates_op.h.
    All branching is jnp.where on scalar counters so the whole update stays
    inside the fused jitted step (no host round-trip per step).
    """
    p = _p(ins, "param")
    s1, s2, s3 = _p(ins, "in_sum_1"), _p(ins, "in_sum_2"), _p(ins, "in_sum_3")
    num_acc = _p(ins, "in_num_accumulates")
    old_acc = _p(ins, "in_old_num_accumulates")
    num_upd = _p(ins, "in_num_updates")
    rate = attrs["average_window"]
    min_w = attrs["min_average_window"]
    max_w = attrs["max_average_window"]
    k_max = 16384  # spill sum_1 into sum_2 to bound accumulation error
    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p.astype(s1.dtype)
    spill = (num_upd % k_max == 0).reshape(())
    s2 = jnp.where(spill, s2 + s1, s2)
    s1 = jnp.where(spill, jnp.zeros_like(s1), s1)
    # reference truncates num_updates*average_window to integer before the
    # comparison (average_accumulates_op.h std::min<int64_t>)
    window = jnp.minimum(
        jnp.int32(max_w),
        (num_upd.astype(jnp.float32) * rate).astype(jnp.int32))
    trigger = ((num_acc >= min_w) & (num_acc >= window)).reshape(())
    s3 = jnp.where(trigger, s1 + s2, s3)
    s1 = jnp.where(trigger, jnp.zeros_like(s1), s1)
    s2 = jnp.where(trigger, jnp.zeros_like(s2), s2)
    old_acc = jnp.where(trigger, num_acc, old_acc)
    num_acc = jnp.where(trigger, jnp.zeros_like(num_acc), num_acc)
    return {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": num_acc,
            "out_old_num_accumulates": old_acc,
            "out_num_updates": num_upd}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    """Ref adadelta_op.cc: accumulate squared grads and squared updates
    with decay rho; step = -sqrt(E[dx^2]+eps)/sqrt(E[g^2]+eps) * g."""
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    eg = _p(ins, "AvgSquaredGrad")
    ex = _p(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    eg_new = rho * eg + (1 - rho) * g * g
    update = -jnp.sqrt(ex + eps) / jnp.sqrt(eg_new + eps) * g
    ex_new = rho * ex + (1 - rho) * update * update
    return {"ParamOut": p + update, "AvgSquaredGradOut": eg_new,
            "AvgSquaredUpdateOut": ex_new}
