"""Math / elementwise / reduction / activation op kernels.

Reference parity: paddle/fluid/operators/{activation_op,elementwise/*,
reduce_ops/*,matmul_op,mul_op,sum_op,scale_op,clip_op,cast_op,...}.cc — each
reference op has CPU+CUDA kernels; here each is one pure JAX function that XLA
fuses/tiles for the TPU MXU/VPU.
"""
import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _x(ins, slot="X"):
    return ins[slot][0]


# ---------------------------------------------------------------------------
# elementwise binary ops with fluid axis-broadcast semantics
# (reference: operators/elementwise/elementwise_op_function.h)
# ---------------------------------------------------------------------------

def _bcast(x, y, axis):
    if x.ndim == y.ndim:
        return x, y
    if y.ndim > x.ndim:   # fluid requires rank(X) >= rank(Y); be permissive
        x, y = y, x
        x, y = _bcast(x, y, axis)
        return y, x
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return x, y.reshape(new_shape)


def _elementwise(fn):
    def kernel(ctx, ins, attrs):
        x, y = _bcast(ins["X"][0], ins["Y"][0], attrs.get("axis", -1))
        return {"Out": fn(x, y)}
    return kernel


for _name, _fn in [
        ("elementwise_add", jnp.add),
        ("elementwise_sub", jnp.subtract),
        ("elementwise_mul", jnp.multiply),
        ("elementwise_div", jnp.divide),
        ("elementwise_max", jnp.maximum),
        ("elementwise_min", jnp.minimum),
        ("elementwise_pow", jnp.power),
        ("elementwise_mod", jnp.mod),
        ("elementwise_floordiv", jnp.floor_divide),
]:
    register_op(_name)(_elementwise(_fn))


# ---------------------------------------------------------------------------
# activations (reference: operators/activation_op.cc ~40 kernels)
# ---------------------------------------------------------------------------

def _act(fn):
    def kernel(ctx, ins, attrs):
        return {"Out": fn(_x(ins), attrs)}
    return kernel


_ACTIVATIONS = {
    "relu": lambda x, a: jax.nn.relu(x),
    "relu6": lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)),
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "softplus": lambda x, a: jax.nn.softplus(x),
    "softsign": lambda x, a: jax.nn.soft_sign(x),
    "exp": lambda x, a: jnp.exp(x),
    "log": lambda x, a: jnp.log(x),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "rsqrt": lambda x, a: lax.rsqrt(x),
    "square": lambda x, a: jnp.square(x),
    "abs": lambda x, a: jnp.abs(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "floor": lambda x, a: jnp.floor(x),
    "round": lambda x, a: jnp.round(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "sin": lambda x, a: jnp.sin(x),
    "cos": lambda x, a: jnp.cos(x),
    "acos": lambda x, a: jnp.arccos(x),
    "asin": lambda x, a: jnp.arcsin(x),
    "atan": lambda x, a: jnp.arctan(x),
    "erf": lambda x, a: jax.scipy.special.erf(x),
    "gelu": lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate",
                                                          False)),
    "leaky_relu": lambda x, a: jax.nn.leaky_relu(
        x, negative_slope=a.get("alpha", 0.02)),
    "elu": lambda x, a: jax.nn.elu(x, alpha=a.get("alpha", 1.0)),
    "selu": lambda x, a: a.get("scale", 1.0507009873554805) * jnp.where(
        x > 0, x, a.get("alpha", 1.6732632423543772) * (jnp.exp(x) - 1)),
    "swish": lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x),
    "hard_sigmoid": lambda x, a: jnp.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
    "hard_swish": lambda x, a: x * jnp.clip(
        x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0)) /
        a.get("scale", 6.0),
    "hard_shrink": lambda x, a: jnp.where(
        jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
    "softshrink": lambda x, a: jnp.sign(x) * jax.nn.relu(
        jnp.abs(x) - a.get("lambda", 0.5)),
    "thresholded_relu": lambda x, a: jnp.where(
        x > a.get("threshold", 1.0), x, 0.0),
    "brelu": lambda x, a: jnp.clip(x, a.get("t_min", 0.0),
                                   a.get("t_max", 24.0)),
    "soft_relu": lambda x, a: jnp.log(
        1 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0),
                             a.get("threshold", 40.0)))),
    "stanh": lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
        a.get("scale_a", 0.67) * x),
    "sign": lambda x, a: jnp.sign(x),
    "log1p": lambda x, a: jnp.log1p(x),
    "expm1": lambda x, a: jnp.expm1(x),
    "silu": lambda x, a: jax.nn.silu(x),
    "mish": lambda x, a: x * jnp.tanh(jax.nn.softplus(x)),
}

for _name, _fn in _ACTIVATIONS.items():
    register_op(_name)(_act(_fn))


@register_op("pow")
def _pow(ctx, ins, attrs):
    return {"Out": jnp.power(_x(ins), attrs.get("factor", 1.0))}


@register_op("scale")
def _scale(ctx, ins, attrs):
    x = _x(ins)
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": x * scale + bias}
    return {"Out": (x + bias) * scale}


@register_op("clip")
def _clip(ctx, ins, attrs):
    return {"Out": jnp.clip(_x(ins), attrs["min"], attrs["max"])}


@register_op("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = _x(ins)
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": x * (max_norm / jnp.maximum(norm, max_norm))}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": jnp.sum(jnp.square(_x(ins))).reshape(())}


@register_op("cast")
def _cast(ctx, ins, attrs):
    from ..framework.dtypes import to_jax_dtype
    return {"Out": _x(ins).astype(to_jax_dtype(attrs["out_dtype"]))}


@register_op("sum")
def _sum(ctx, ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("mean")
def _mean(ctx, ins, attrs):
    return {"Out": jnp.mean(_x(ins)).reshape((1,))}


# ---------------------------------------------------------------------------
# matmul / mul (reference: matmul_op.cc, mul_op.cc — MXU territory)
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _matmul_widen(x, y, out_dt):
    return jnp.matmul(x, y, preferred_element_type=out_dt)


def _matmul_widen_fwd(x, y, out_dt):
    return _matmul_widen(x, y, out_dt), (x, y)


def _matmul_widen_bwd(out_dt, res, g):
    x, y = res
    gx = g.astype(x.dtype)
    gy = g.astype(y.dtype)
    dx = jnp.matmul(gx, jnp.swapaxes(y, -1, -2),
                    preferred_element_type=out_dt).astype(x.dtype)
    dy = jnp.matmul(jnp.swapaxes(x, -1, -2), gy,
                    preferred_element_type=out_dt).astype(y.dtype)
    # broadcasting batch dims: sum grads back to the operand shapes
    while dx.ndim > x.ndim:
        dx = dx.sum(axis=0)
    while dy.ndim > y.ndim:
        dy = dy.sum(axis=0)
    return dx, dy


_matmul_widen.defvjp(_matmul_widen_fwd, _matmul_widen_bwd)


@register_op("matmul")
def _matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    tx, ty = attrs.get("transpose_X", False), attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    # out_dtype: accumulate on the MXU in a wider type than the inputs
    # (bf16 x bf16 -> f32 logits in ONE pass — the mixed-precision path
    # for vocab-scale projections; maps to XLA preferred_element_type).
    # The BACKWARD casts the f32 cotangent down to the input dtype before
    # the grad matmuls — without this, jax's default vjp runs both
    # vocab-width grad dots at f32 (half MXU rate); standard
    # mixed-precision practice, grads re-accumulate in f32 inside the
    # optimizer anyway.
    out_dt = attrs.get("out_dtype")
    if out_dt:
        from ..framework.dtypes import to_jax_dtype
        out = _matmul_widen(x, y, to_jax_dtype(out_dt))
    else:
        out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("mul")
def _mul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((-1, math.prod(xs[xn:])))
    y2 = y.reshape((math.prod(ys[:yn]), -1))
    out = jnp.matmul(x2, y2)
    return {"Out": out.reshape(xs[:xn] + ys[yn:])}


# ---------------------------------------------------------------------------
# reductions (reference: operators/reduce_ops/*)
# ---------------------------------------------------------------------------

def _reduce(fn):
    def kernel(ctx, ins, attrs):
        x = _x(ins)
        dims = attrs.get("dim", [0])
        reduce_all = attrs.get("reduce_all", False) or dims is None
        if reduce_all:
            axes = tuple(range(x.ndim))
        else:
            if not isinstance(dims, (list, tuple)):
                dims = [dims]
            axes = tuple(d % x.ndim for d in dims)
        out = fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))
        if reduce_all and not attrs.get("keep_dim", False):
            out = out.reshape((1,))  # fluid returns shape [1], not 0-d
        return {"Out": out}
    return kernel


for _name, _fn in [
        ("reduce_sum", jnp.sum), ("reduce_mean", jnp.mean),
        ("reduce_max", jnp.max), ("reduce_min", jnp.min),
        ("reduce_prod", jnp.prod),
        ("reduce_all", jnp.all), ("reduce_any", jnp.any),
]:
    register_op(_name)(_reduce(_fn))


@register_op("logsumexp")
def _logsumexp(ctx, ins, attrs):
    x = _x(ins)
    dims = attrs.get("dim", None)
    axes = tuple(d % x.ndim for d in dims) if dims else None
    return {"Out": jax.scipy.special.logsumexp(
        x, axis=axes, keepdims=attrs.get("keep_dim", False))}


@register_op("cumsum")
def _cumsum(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get("exclusive", False):
            out = out - x
    return {"Out": out}


# ---------------------------------------------------------------------------
# comparison / logical (reference: operators/controlflow/compare_op.cc)
# ---------------------------------------------------------------------------

def _compare(fn):
    def kernel(ctx, ins, attrs):
        x, y = _bcast(ins["X"][0], ins["Y"][0], attrs.get("axis", -1))
        return {"Out": fn(x, y)}
    return kernel


for _name, _fn in [
        ("less_than", jnp.less), ("less_equal", jnp.less_equal),
        ("greater_than", jnp.greater), ("greater_equal", jnp.greater_equal),
        ("equal", jnp.equal), ("not_equal", jnp.not_equal),
]:
    register_op(_name)(_compare(_fn))

for _name, _fn in [
        ("logical_and", jnp.logical_and), ("logical_or", jnp.logical_or),
        ("logical_xor", jnp.logical_xor),
]:
    register_op(_name)(_compare(_fn))


@register_op("logical_not")
def _logical_not(ctx, ins, attrs):
    return {"Out": jnp.logical_not(_x(ins))}


@register_op("isfinite")
def _isfinite(ctx, ins, attrs):
    return {"Out": jnp.all(jnp.isfinite(_x(ins))).reshape((1,))}


@register_op("isnan")
def _isnan(ctx, ins, attrs):
    return {"Out": jnp.isnan(_x(ins))}


@register_op("isinf")
def _isinf(ctx, ins, attrs):
    return {"Out": jnp.isinf(_x(ins))}


@register_op("maximum")
def _maximum(ctx, ins, attrs):
    return {"Out": jnp.maximum(ins["X"][0], ins["Y"][0])}


@register_op("minimum")
def _minimum(ctx, ins, attrs):
    return {"Out": jnp.minimum(ins["X"][0], ins["Y"][0])}


@register_op("dot")
def _dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=True)}
