"""Pallas kernel dispatch scope — the light half of the Pallas library.

`BuildStrategy.use_pallas={"softmax_with_cross_entropy","adam","layer_norm"}`
makes `CompiledProgram` trace the step inside :func:`scope`; the op kernels
in nn_ops/optimizer_ops consult :func:`enabled` at trace time and route to
the fused Pallas implementation (``ops/pallas/``), falling back to their
XLA lowering otherwise. The same thread-local pattern as
``collective_ops.grad_sync_scope``: the scope is entered around the
function jax.jit traces, so the decision is baked into the compiled
executable — which is why the option must participate in the executor's
compile-cache token.

This module deliberately imports NEITHER jax.experimental.pallas nor the
kernel modules: every softmax_with_cross_entropy/adam/layer_norm trace
pays one thread-local read when Pallas is off. The heavy kernels load
lazily inside the enabled branch.

Autotuning: a :class:`PallasConfig` may carry a tuning cache (any object
with ``lookup(key) -> entry-dict-or-None``, normally
``ops.pallas.autotune.AutotuneCache``) and a fitted
:class:`~.pallas.costmodel.CostModel`. :func:`choose` resolves the
per-(op, shape, dtype, mesh, backend) :class:`KernelChoice` at trace
time — ONE decision per call site instead of three independent knobs:

  * a cached entry is a MEASURED verdict: it overrides the kernel's
    default block sizes, routes the op back to XLA when the sweep found
    Pallas losing, or selects the quantized variant (``impl:
    "pallas_q"`` — bf16-cast inputs with f32 accumulation, banked only
    from a sweep that measured its numerics envelope);
  * a cache MISS with a cost model attached gets a PREDICTED config
    (the model ranks the candidate space for the never-swept shape)
    instead of the hardcoded kernel default;
  * no signal at all keeps the legacy kernel defaults.

Every decision is exported through the PR 12 observability layer: a
``kernel_choice`` span (op, impl, source, predicted vs measured
seconds) when tracing is enabled, plus cumulative
``kernel_choice_total{op=,impl=,source=}`` counters in
``resilience.metrics()``. Decisions happen at TRACE time only, so the
export rides compiles, never the step hot path.
"""
import contextlib
import os
import threading

#: ops with a Pallas lowering behind this dispatch scope (flash attention
#: has its own auto-engaging entry in layers.attention and is not listed)
PALLAS_OPS = ("softmax_with_cross_entropy", "adam", "layer_norm",
              "fused_mlm_head_loss")

_local = threading.local()


#: kernel_policy values BuildStrategy accepts — the one front door
KERNEL_POLICIES = ("auto", "xla", "pallas")


class PallasConfig(object):
    """Per-compile Pallas dispatch state.

    ops:        iterable of op-type names to route through Pallas
    interpret:  None = decide per kernel call from the effective default
                device (CPU -> interpret mode, same contract as
                flash_attention); True/False forces it
    tuning:     autotune cache (``lookup(key)``) or None for defaults
    mesh_axes:  dict axis->size of the compile's mesh (cache-key part)
    backend:    platform string the executable targets (cache-key part)
    cost_model: fitted ``costmodel.CostModel`` (or None) — resolves a
                cache MISS to a predicted config instead of defaults
    policy:     the BuildStrategy.kernel_policy that built this config
                (labeling/diagnostics; "xla" never builds a config)
    """

    def __init__(self, ops, interpret=None, tuning=None, mesh_axes=None,
                 backend=None, cost_model=None, policy=None):
        unknown = sorted(set(ops) - set(PALLAS_OPS))
        if unknown:
            raise ValueError(
                "use_pallas names ops with no Pallas lowering: %r "
                "(available: %r)" % (unknown, list(PALLAS_OPS)))
        self.ops = frozenset(ops)
        self.interpret = interpret
        self.tuning = tuning
        self.mesh_axes = dict(mesh_axes or {})
        self.backend = backend
        self.cost_model = cost_model
        self.policy = policy


@contextlib.contextmanager
def scope(config):
    """Install `config` for the current thread (the jit trace runs under
    it). Nesting restores the outer config on exit."""
    prev = getattr(_local, "config", None)
    _local.config = config
    try:
        yield config
    finally:
        _local.config = prev


def active():
    return getattr(_local, "config", None)


def enabled(op_type):
    """The active PallasConfig if `op_type` is routed to Pallas, else
    None — the one-line check every wired kernel starts with."""
    cfg = getattr(_local, "config", None)
    if cfg is not None and op_type in cfg.ops:
        return cfg
    return None


def cache_key(op, shape, dtype, mesh_axes=None, backend=None):
    """Autotune cache key — same ingredients as the executor's step
    cache: problem shape + mesh axes + backend. One winning config per
    (op, shape, dtype, topology, platform)."""
    axes = ",".join("%s=%d" % (a, int(s))
                    for a, s in sorted((mesh_axes or {}).items()))
    return "%s|%s|%s|%s|%s" % (
        op, "x".join(str(int(d)) for d in shape), str(dtype),
        axes or "-", backend or "-")


class KernelChoice(tuple):
    """One per-call-site kernel decision, unpackable as the legacy
    ``(impl, tuned_kwargs)`` pair (it IS that tuple) plus provenance:

      impl        -- "pallas" | "xla" | "pallas_q" (quantized variant:
                     bf16-cast inputs, f32 accumulation)
      config      -- tuned/predicted block kwargs, or None = defaults
      source      -- "measured" (banked sweep verdict), "predicted"
                     (fitted cost model), "analytic" (no-data proxy),
                     "default" (no signal)
      predicted_s -- model-predicted seconds (predicted/analytic)
      measured_s  -- banked sweep seconds (measured)
    """

    def __new__(cls, impl, config=None, source="default",
                predicted_s=None, measured_s=None):
        self = tuple.__new__(cls, (impl, config))
        self.impl = impl
        self.config = config
        self.source = source
        self.predicted_s = predicted_s
        self.measured_s = measured_s
        return self


def _export_choice(op, shape, dtype, choice):
    """Ship one trace-time decision through the observability layer:
    cumulative counters always, a retroactive span when tracing is on.
    Trace-rate only (compiles), never the step hot path; any obs
    hiccup must not fail a trace."""
    try:
        from ..framework import resilience
        resilience.record_kernel_choice(op, choice.impl, choice.source)
    except Exception:  # pragma: no cover - obs must never break a trace
        pass
    try:
        from ..framework import obs
        if obs.enabled():
            t = obs.now()
            obs.record(
                "kernel_choice", t, t, op=op,
                shape="x".join(str(int(d)) for d in shape),
                dtype=str(dtype), impl=choice.impl, source=choice.source,
                predicted_s=choice.predicted_s,
                measured_s=choice.measured_s)
    except Exception:  # pragma: no cover
        pass


def choose(cfg, op, shape, dtype):
    """Resolve the :class:`KernelChoice` for one kernel call at trace
    time (unpacks as the legacy ``(impl, tuned_kwargs)`` pair).

    Priority: banked MEASURED verdict (exact key, then the mesh-less
    key — a verdict swept without a mesh serves every topology of its
    backend) > cost-model PREDICTION for a never-swept shape > kernel
    defaults. impl "xla" means the sweep measured Pallas losing here —
    the caller must take its XLA branch; "pallas_q" asks the caller
    for its quantized (bf16-cast) variant where it has one."""
    if cfg is None:
        return KernelChoice("pallas", None)
    choice = None
    entry = None
    if cfg.tuning is not None:
        entry = cfg.tuning.lookup(
            cache_key(op, shape, dtype, cfg.mesh_axes, cfg.backend))
        if not entry and cfg.mesh_axes:
            entry = cfg.tuning.lookup(
                cache_key(op, shape, dtype, None, cfg.backend))
    if entry:
        if entry.get("impl") == "xla":
            choice = KernelChoice("xla", None, "measured",
                                  measured_s=entry.get("xla_s"))
        else:
            config = entry.get("config")
            # a --cost-model-only banked entry was never measured: its
            # provenance stays "predicted" so the kernel_choice export
            # cannot pass a zero-probe prediction off as a sweep verdict
            src = "predicted" if entry.get("source") == "costmodel" \
                else "measured"
            choice = KernelChoice(
                entry.get("impl") or "pallas",
                dict(config) if config else None, src,
                predicted_s=entry.get("predicted_s"),
                measured_s=entry.get("pallas_s"))
    elif cfg.cost_model is not None:
        interp = cfg.interpret if cfg.interpret is not None \
            else default_interpret()
        pred = cfg.cost_model.predict_config(
            op, shape, backend=cfg.backend, interpret=interp)
        if pred is not None:
            choice = KernelChoice(
                "pallas", pred["config"],
                "predicted" if pred["source"] == "fitted"
                else "analytic", predicted_s=pred["predicted_s"])
    if choice is None:
        choice = KernelChoice("pallas", None)
    _export_choice(op, shape, dtype, choice)
    return choice


def default_interpret():
    """interpret-mode default shared by every kernel entry: honor
    PADDLE_TPU_PALLAS_INTERPRET, else interpret off-TPU — decided from
    the EFFECTIVE default device, not the process backend list (a
    jax.default_device(cpu) pin routes this computation to CPU even when
    a chip is attached). Mirrors flash_attention's contract."""
    env = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "")
    import jax
    pinned = getattr(jax.config, "jax_default_device", None)
    if pinned is None:
        platform = jax.default_backend()
    elif isinstance(pinned, str):
        platform = pinned
    else:
        platform = getattr(pinned, "platform", None)
    return platform not in ("tpu", "axon")
