"""Pallas kernel dispatch scope — the light half of the Pallas library.

`BuildStrategy.use_pallas={"softmax_with_cross_entropy","adam","layer_norm"}`
makes `CompiledProgram` trace the step inside :func:`scope`; the op kernels
in nn_ops/optimizer_ops consult :func:`enabled` at trace time and route to
the fused Pallas implementation (``ops/pallas/``), falling back to their
XLA lowering otherwise. The same thread-local pattern as
``collective_ops.grad_sync_scope``: the scope is entered around the
function jax.jit traces, so the decision is baked into the compiled
executable — which is why the option must participate in the executor's
compile-cache token.

This module deliberately imports NEITHER jax.experimental.pallas nor the
kernel modules: every softmax_with_cross_entropy/adam/layer_norm trace
pays one thread-local read when Pallas is off. The heavy kernels load
lazily inside the enabled branch.

Autotuning: a :class:`PallasConfig` may carry a tuning cache (any object
with ``lookup(key) -> entry-dict-or-None``, normally
``ops.pallas.autotune.AutotuneCache``). :func:`choose` resolves the
per-(op, shape, dtype, mesh, backend) verdict at trace time: a cached
entry either overrides the kernel's default block sizes or routes the op
back to XLA when the sweep found Pallas losing.
"""
import contextlib
import os
import threading

#: ops with a Pallas lowering behind this dispatch scope (flash attention
#: has its own auto-engaging entry in layers.attention and is not listed)
PALLAS_OPS = ("softmax_with_cross_entropy", "adam", "layer_norm",
              "fused_mlm_head_loss")

_local = threading.local()


class PallasConfig(object):
    """Per-compile Pallas dispatch state.

    ops:       iterable of op-type names to route through Pallas
    interpret: None = decide per kernel call from the effective default
               device (CPU -> interpret mode, same contract as
               flash_attention); True/False forces it
    tuning:    autotune cache (``lookup(key)``) or None for defaults
    mesh_axes: dict axis->size of the compile's mesh (cache-key part)
    backend:   platform string the executable targets (cache-key part)
    """

    def __init__(self, ops, interpret=None, tuning=None, mesh_axes=None,
                 backend=None):
        unknown = sorted(set(ops) - set(PALLAS_OPS))
        if unknown:
            raise ValueError(
                "use_pallas names ops with no Pallas lowering: %r "
                "(available: %r)" % (unknown, list(PALLAS_OPS)))
        self.ops = frozenset(ops)
        self.interpret = interpret
        self.tuning = tuning
        self.mesh_axes = dict(mesh_axes or {})
        self.backend = backend


@contextlib.contextmanager
def scope(config):
    """Install `config` for the current thread (the jit trace runs under
    it). Nesting restores the outer config on exit."""
    prev = getattr(_local, "config", None)
    _local.config = config
    try:
        yield config
    finally:
        _local.config = prev


def active():
    return getattr(_local, "config", None)


def enabled(op_type):
    """The active PallasConfig if `op_type` is routed to Pallas, else
    None — the one-line check every wired kernel starts with."""
    cfg = getattr(_local, "config", None)
    if cfg is not None and op_type in cfg.ops:
        return cfg
    return None


def cache_key(op, shape, dtype, mesh_axes=None, backend=None):
    """Autotune cache key — same ingredients as the executor's step
    cache: problem shape + mesh axes + backend. One winning config per
    (op, shape, dtype, topology, platform)."""
    axes = ",".join("%s=%d" % (a, int(s))
                    for a, s in sorted((mesh_axes or {}).items()))
    return "%s|%s|%s|%s|%s" % (
        op, "x".join(str(int(d)) for d in shape), str(dtype),
        axes or "-", backend or "-")


def choose(cfg, op, shape, dtype):
    """Resolve (impl, tuned_kwargs) for one kernel call at trace time.

    impl "pallas" with tuned_kwargs=None means "Pallas at default block
    sizes"; a dict carries the sweep winner's blocks; impl "xla" means
    the autotuner measured Pallas losing to the XLA lowering for this
    key — the caller must take its XLA branch."""
    if cfg is None or cfg.tuning is None:
        return "pallas", None
    entry = cfg.tuning.lookup(
        cache_key(op, shape, dtype, cfg.mesh_axes, cfg.backend))
    if not entry:
        return "pallas", None
    if entry.get("impl") == "xla":
        return "xla", None
    config = entry.get("config")
    return "pallas", (dict(config) if config else None)


def default_interpret():
    """interpret-mode default shared by every kernel entry: honor
    PADDLE_TPU_PALLAS_INTERPRET, else interpret off-TPU — decided from
    the EFFECTIVE default device, not the process backend list (a
    jax.default_device(cpu) pin routes this computation to CPU even when
    a chip is attached). Mirrors flash_attention's contract."""
    env = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "")
    import jax
    pinned = getattr(jax.config, "jax_default_device", None)
    if pinned is None:
        platform = jax.default_backend()
    elif isinstance(pinned, str):
        platform = pinned
    else:
        platform = getattr(pinned, "platform", None)
    return platform not in ("tpu", "axon")
