"""Two-stage / RetinaNet training-side detection ops
(ref paddle/fluid/operators/detection/{rpn_target_assign_op,
retinanet_detection_output_op,generate_proposal_labels_op,
locality_aware_nms_op}.cc + python/paddle/fluid/layers/detection.py).

Dense TPU redesign: the reference emits LoD-compacted samples (a
variable number of sampled anchors/rois per image); XLA wants static
shapes, so these kernels return FULL per-anchor/per-roi tensors plus
{-1, 0, 1} label masks and 0/1 weight tensors — the downstream losses
multiply by the weights, which is numerically identical to gathering
the sampled subset.  Sampling uses the deterministic per-op PRNG
(ctx.rng) with score-jitter top-k instead of host-side shuffles.
"""
import jax
import jax.numpy as jnp

from .registry import register_op


def _pairwise_iou(a, b):
    """a (A, 4), b (G, 4) xyxy -> (A, G)."""
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix = jnp.maximum(
        0.0, jnp.minimum(ax2[:, None], bx2[None]) -
        jnp.maximum(ax1[:, None], bx1[None]))
    iy = jnp.maximum(
        0.0, jnp.minimum(ay2[:, None], by2[None]) -
        jnp.maximum(ay1[:, None], by1[None]))
    inter = ix * iy
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    union = area_a[:, None] + area_b[None] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _encode_boxes(anchors, gts):
    """Faster-RCNN box regression targets: anchors/gts (N, 4) xyxy ->
    (N, 4) [dx, dy, dw, dh]."""
    aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-6)
    ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-6)
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah
    gw = jnp.maximum(gts[:, 2] - gts[:, 0], 1e-6)
    gh = jnp.maximum(gts[:, 3] - gts[:, 1], 1e-6)
    gx = gts[:, 0] + 0.5 * gw
    gy = gts[:, 1] + 0.5 * gh
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(gw / aw), jnp.log(gh / ah)], axis=1)


def _sample_mask(key, eligible, count):
    """Pick <=count True positions of ``eligible`` uniformly: random
    scores, keep the count highest among eligible."""
    r = jax.random.uniform(key, eligible.shape)
    scored = jnp.where(eligible, r, -1.0)
    n_keep = jnp.minimum(count, jnp.sum(eligible))
    thresh = -jnp.sort(-scored)[jnp.maximum(n_keep - 1, 0)]
    # n_keep == 0 would otherwise degrade thresh to the max score and
    # still pick one element
    return eligible & (scored >= thresh) & (n_keep > 0)


def _crowd_ignore(anchors, gt, crowd_mask, thresh):
    """Anchors overlapping a crowd gt above ``thresh`` are ignored."""
    iou = _pairwise_iou(anchors, gt)
    crowd_iou = jnp.max(jnp.where(crowd_mask[None, :], iou, 0.0), axis=1)
    return crowd_iou >= thresh


def _inside_image(anchors, im_hw, straddle):
    """Reference straddle rule: with straddle >= 0, anchors poking more
    than ``straddle`` pixels outside the image are disabled."""
    h, w = im_hw[0], im_hw[1]
    return ((anchors[:, 0] >= -straddle) & (anchors[:, 1] >= -straddle) &
            (anchors[:, 2] < w + straddle) &
            (anchors[:, 3] < h + straddle))


def _assign_one(key, anchors, gt, gt_valid, pos_iou, neg_iou,
                batch_per_im, fg_frac, use_random, ignore_mask):
    """Per-image RPN assignment: labels (A,) in {-1,0,1}, matched gt
    index (A,), bbox targets (A, 4)."""
    iou = _pairwise_iou(anchors, gt)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    labels = jnp.full(anchors.shape[0], -1, jnp.int32)
    labels = jnp.where(best_iou < neg_iou, 0, labels)
    labels = jnp.where(best_iou >= pos_iou, 1, labels)
    # every valid gt gets its best anchor as positive (the reference's
    # "force at least one anchor per gt" rule)
    best_anchor = jnp.argmax(jnp.where(gt_valid[None, :], iou, -1.0),
                             axis=0)
    # duplicate indices (every padded gt argmaxes to anchor 0) must not
    # clobber a valid gt's write — route invalid gts out of bounds
    safe_anchor = jnp.where(gt_valid, best_anchor, anchors.shape[0])
    force = jnp.zeros(anchors.shape[0], bool).at[safe_anchor].set(
        True, mode="drop")
    labels = jnp.where(force, 1, labels)
    labels = jnp.where(ignore_mask, -1, labels)

    n_fg = jnp.int32(batch_per_im * fg_frac)
    k1, k2 = jax.random.split(key)
    if use_random:
        fg_pick = _sample_mask(k1, labels == 1, n_fg)
    else:
        idx = jnp.cumsum((labels == 1).astype(jnp.int32))
        fg_pick = (labels == 1) & (idx <= n_fg)
    n_bg = jnp.int32(batch_per_im) - jnp.sum(fg_pick)
    if use_random:
        bg_pick = _sample_mask(k2, labels == 0, n_bg)
    else:
        idxb = jnp.cumsum((labels == 0).astype(jnp.int32))
        bg_pick = (labels == 0) & (idxb <= n_bg)
    labels = jnp.where(fg_pick, 1, jnp.where(bg_pick, 0, -1))
    tgt = _encode_boxes(anchors, gt[best_gt])
    return labels, best_gt, tgt


@register_op("rpn_target_assign",
             nondiff=("Anchor", "AnchorVar", "GtBoxes", "IsCrowd",
                      "ImInfo"), differentiable=False)
def _rpn_target_assign(ctx, ins, attrs):
    """Dense RPN targets (ref rpn_target_assign_op.cc): anchors (A, 4),
    gt_boxes (B, G, 4) zero-padded.  Returns per-anchor tensors:
    Labels (B, A) {-1 ignore, 0 bg, 1 fg}, BBoxTargets (B, A, 4),
    InsideWeights/OutsideWeights (B, A, 4) 1 on sampled foreground."""
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0]
    b = gt.shape[0]
    crowd = ins["IsCrowd"][0].reshape(b, -1).astype(bool) \
        if ins.get("IsCrowd") else None
    im_info = ins["ImInfo"][0] if ins.get("ImInfo") else None
    straddle = attrs.get("rpn_straddle_thresh", 0.0)
    gt_valid = jnp.any(gt != 0.0, axis=2)
    if crowd is not None:
        gt_valid = gt_valid & ~crowd
    keys = jax.random.split(ctx.rng(), b)

    def per_image(k, g, v, cm, hw):
        ignore = _crowd_ignore(
            anchors, g, cm, attrs.get("rpn_negative_overlap", 0.3))
        if straddle >= 0:
            ignore = ignore | ~_inside_image(anchors, hw, straddle)
        return _assign_one(
            k, anchors, g, v,
            attrs.get("rpn_positive_overlap", 0.7),
            attrs.get("rpn_negative_overlap", 0.3),
            attrs.get("rpn_batch_size_per_im", 256),
            attrs.get("rpn_fg_fraction", 0.5),
            attrs.get("use_random", True), ignore)

    labels, best_gt, tgt = jax.vmap(per_image)(
        keys, gt, gt_valid,
        crowd if crowd is not None else jnp.zeros(
            (b, gt.shape[1]), bool),
        im_info[:, :2] if im_info is not None else jnp.full(
            (b, 2), jnp.inf))
    fg = (labels == 1).astype(jnp.float32)[..., None]
    return {"Labels": labels, "BBoxTargets": tgt * fg,
            "BBoxInsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "BBoxOutsideWeights": jnp.broadcast_to(fg, tgt.shape)}


@register_op("retinanet_target_assign",
             nondiff=("Anchor", "AnchorVar", "GtBoxes", "GtLabels",
                      "IsCrowd", "ImInfo"), differentiable=False)
def _retinanet_target_assign(ctx, ins, attrs):
    """RetinaNet targets (ref retinanet_target_assign): like RPN but
    no sampling (focal loss handles imbalance); positives iou >= 0.5,
    negatives < 0.4, rest ignored.  Labels carry the gt CLASS (1-based;
    0 = background, -1 = ignore); also returns ForegroundNumber."""
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0]
    gt_labels = ins["GtLabels"][0]
    if gt_labels.ndim == 3:
        gt_labels = gt_labels[..., 0]
    gt_valid = jnp.any(gt != 0.0, axis=2)
    if ins.get("IsCrowd"):
        gt_valid = gt_valid & ~ins["IsCrowd"][0].reshape(
            gt_valid.shape).astype(bool)
    pos = attrs.get("positive_overlap", 0.5)
    neg = attrs.get("negative_overlap", 0.4)

    def one(g, gl, v):
        iou = jnp.where(v[None, :], _pairwise_iou(anchors, g), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        cls = gl[best_gt].astype(jnp.int32)
        labels = jnp.full(anchors.shape[0], -1, jnp.int32)
        labels = jnp.where(best_iou < neg, 0, labels)
        labels = jnp.where(best_iou >= pos, cls, labels)
        best_anchor = jnp.where(v, jnp.argmax(iou, axis=0),
                                anchors.shape[0])
        labels = labels.at[best_anchor].set(gl.astype(jnp.int32),
                                            mode="drop")
        tgt = _encode_boxes(anchors, g[best_gt])
        return labels, tgt

    labels, tgt = jax.vmap(one)(gt, gt_labels, gt_valid)
    fg = (labels >= 1).astype(jnp.float32)[..., None]
    fg_num = jnp.maximum(jnp.sum(fg.reshape(labels.shape[0], -1),
                                 axis=1), 1.0).astype(jnp.int32)
    return {"Labels": labels, "BBoxTargets": tgt * fg,
            "BBoxInsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "BBoxOutsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "ForegroundNumber": fg_num.reshape(-1, 1)}


@register_op("generate_proposal_labels",
             nondiff=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes",
                      "ImInfo"), differentiable=False)
def _generate_proposal_labels(ctx, ins, attrs):
    """Second-stage RoI sampling (ref generate_proposal_labels_op.cc),
    dense form: rois (B, R, 4), gts (B, G, 4)+classes.  Returns all R
    rois per image with Labels (B, R) {-1 ignore, 0 bg, class fg},
    BBoxTargets (B, R, 4) and inside/outside weights."""
    rois = ins["RpnRois"][0]
    gt = ins["GtBoxes"][0]
    classes = ins["GtClasses"][0]
    if classes.ndim == 3:
        classes = classes[..., 0]
    b = rois.shape[0]
    gt_valid = jnp.any(gt != 0.0, axis=2)
    if ins.get("IsCrowd"):
        gt_valid = gt_valid & ~ins["IsCrowd"][0].reshape(
            gt_valid.shape).astype(bool)
    fg_th = attrs.get("fg_thresh", 0.5)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)
    batch = attrs.get("batch_size_per_im", 512)
    fg_frac = attrs.get("fg_fraction", 0.25)
    use_random = attrs.get("use_random", True)
    reg_w = jnp.asarray(attrs.get("bbox_reg_weights",
                                  [0.1, 0.1, 0.2, 0.2]), jnp.float32)
    keys = jax.random.split(ctx.rng(), b)

    def one(key, r, g, gl, v):
        iou = jnp.where(v[None, :], _pairwise_iou(r, g), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        is_fg = best_iou >= fg_th
        is_bg = (best_iou < bg_hi) & (best_iou >= bg_lo)
        k1, k2 = jax.random.split(key)
        n_fg = jnp.int32(batch * fg_frac)
        if use_random:
            fg_pick = _sample_mask(k1, is_fg, n_fg)
            bg_pick = _sample_mask(
                k2, is_bg, jnp.int32(batch) - jnp.sum(fg_pick))
        else:
            idx_fg = jnp.cumsum(is_fg.astype(jnp.int32))
            fg_pick = is_fg & (idx_fg <= n_fg)
            idx_bg = jnp.cumsum(is_bg.astype(jnp.int32))
            bg_pick = is_bg & (idx_bg <= jnp.int32(batch) -
                               jnp.sum(fg_pick))
        cls = gl[best_gt].astype(jnp.int32)
        labels = jnp.where(fg_pick, cls,
                           jnp.where(bg_pick, 0, -1))
        # fluid convention: targets divided by bbox_reg_weights
        tgt = _encode_boxes(r, g[best_gt]) / reg_w[None, :]
        return labels, tgt

    labels, tgt = jax.vmap(one)(keys, rois, gt, classes, gt_valid)
    fg = (labels >= 1).astype(jnp.float32)[..., None]
    return {"Rois": rois, "Labels": labels, "BBoxTargets": tgt * fg,
            "BBoxInsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "BBoxOutsideWeights": jnp.broadcast_to(fg, tgt.shape)}


@register_op("locality_aware_nms", nondiff=("BBoxes", "Scores"),
             differentiable=False)
def _locality_aware_nms(ctx, ins, attrs):
    """EAST-style locality-aware NMS (ref locality_aware_nms_op.cc):
    consecutive boxes with IoU above the threshold are merged by
    score-weighted averaging before standard class NMS.  Dense form:
    boxes (N, M, 4), scores (N, C, M); output (N, keep_top_k, 6)
    rows [label, score, x1, y1, x2, y2], -1-padded."""
    from .detection_ops import _nms_alive
    boxes = ins["BBoxes"][0]
    scores = ins["Scores"][0]
    iou_th = attrs.get("nms_threshold", 0.3)
    score_th = attrs.get("score_threshold", 0.0)
    keep_top_k = int(attrs.get("keep_top_k", 100))
    nms_top_k = int(attrs.get("nms_top_k", -1))
    normalized = attrs.get("normalized", True)
    nms_eta = attrs.get("nms_eta", 1.0)
    background = int(attrs.get("background_label", -1))
    n, c, m = scores.shape

    def merge_row(bx, sc):
        # weighted-merge sweep: each box merges into its predecessor
        # when IoU > threshold (locality assumption: boxes arrive in
        # reading order)
        iou_prev = jax.vmap(
            lambda i: _pairwise_iou(bx[i][None], bx[i - 1][None])[0, 0]
        )(jnp.arange(1, m))
        merge = jnp.concatenate([jnp.zeros(1), iou_prev]) > iou_th
        # segment ids: increase where not merging
        seg = jnp.cumsum(~merge)
        w = jnp.maximum(sc, 0.0)
        seg_w = jax.ops.segment_sum(w, seg, num_segments=m + 1)
        seg_box = jax.ops.segment_sum(bx * w[:, None], seg,
                                      num_segments=m + 1)
        seg_s = jax.ops.segment_sum(sc, seg, num_segments=m + 1) / \
            jnp.maximum(jax.ops.segment_sum(jnp.ones_like(sc), seg,
                                            num_segments=m + 1), 1.0)
        merged_box = seg_box / jnp.maximum(seg_w[:, None], 1e-8)
        # scatter back to first index of each segment
        first = jnp.concatenate([jnp.ones(1, bool), ~merge[1:]]) \
            if m > 1 else jnp.ones(1, bool)
        out_b = jnp.where(first[:, None], merged_box[seg], 0.0)
        out_s = jnp.where(first, seg_s[seg], -1.0)
        return out_b, out_s

    def per_image(bx, sc_all):
        rows = []
        for cls in range(c):
            if cls == background:
                continue
            mb, ms = merge_row(bx, sc_all[cls])
            if 0 < nms_top_k < m:
                # pre-truncate to the nms_top_k best candidates
                kth = -jnp.sort(-ms)[nms_top_k - 1]
                ms = jnp.where(ms >= kth, ms, -1.0)
            alive = _nms_alive(mb, ms, iou_th, score_th,
                               normalized=normalized,
                               nms_eta=nms_eta)
            s = jnp.where(alive, ms, -1.0)
            rows.append((s, mb, jnp.full(m, cls, jnp.float32)))
        s = jnp.concatenate([r[0] for r in rows])
        bb = jnp.concatenate([r[1] for r in rows])
        lab = jnp.concatenate([r[2] for r in rows])
        k = min(keep_top_k, int(s.shape[0]))
        top_s, idx = jax.lax.top_k(s, k)
        keep = top_s > score_th
        out = jnp.concatenate(
            [jnp.where(keep, lab[idx], -1.0)[:, None],
             jnp.where(keep, top_s, -1.0)[:, None],
             jnp.where(keep[:, None], bb[idx], 0.0)], axis=1)
        if k < keep_top_k:
            pad = jnp.full((keep_top_k - k, 6), -1.0, out.dtype)
            pad = pad.at[:, 2:].set(0.0)
            out = jnp.concatenate([out, pad], axis=0)
        return out

    return {"Out": jax.vmap(per_image)(boxes, scores)}


def _decode_boxes(anchors, deltas, variance=None):
    """Inverse of _encode_boxes: anchors (A, 4) + deltas (A, 4) -> boxes
    (A, 4) xyxy."""
    if variance is not None:
        deltas = deltas * jnp.asarray(variance, deltas.dtype)[None, :]
    aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-6)
    ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-6)
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah
    cx = deltas[:, 0] * aw + ax
    cy = deltas[:, 1] * ah + ay
    w = jnp.exp(jnp.minimum(deltas[:, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(deltas[:, 3], 10.0)) * ah
    return jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                      cx + 0.5 * w, cy + 0.5 * h], axis=1)


@register_op("retinanet_detection_output",
             nondiff=("BBoxes", "Scores", "Anchors", "ImInfo"),
             differentiable=False)
def _retinanet_detection_output(ctx, ins, attrs):
    """RetinaNet inference head (ref retinanet_detection_output_op.cc):
    per-level box deltas (B, A_l, 4) + sigmoid scores (B, A_l, C) +
    anchors (A_l, 4), decoded, clipped to im_info, then per-class NMS.
    Out (B, keep_top_k, 6) rows [label, score, x1, y1, x2, y2]."""
    from .detection_ops import _nms_alive
    deltas_list = ins["BBoxes"]
    scores_list = ins["Scores"]
    anchors_list = ins["Anchors"]
    im_info = ins["ImInfo"][0]
    score_th = attrs.get("score_threshold", 0.05)
    nms_th = attrs.get("nms_threshold", 0.3)
    nms_eta = attrs.get("nms_eta", 1.0)
    nms_top_k = int(attrs.get("nms_top_k", 1000))
    keep_top_k = int(attrs.get("keep_top_k", 100))

    def per_image(deltas_i, scores_i, hw):
        boxes, scores = [], []
        for d, s, a in zip(deltas_i, scores_i, anchors_list):
            dec = _decode_boxes(a.reshape(-1, 4), d.reshape(-1, 4))
            dec = jnp.stack([
                jnp.clip(dec[:, 0], 0, hw[1] - 1),
                jnp.clip(dec[:, 1], 0, hw[0] - 1),
                jnp.clip(dec[:, 2], 0, hw[1] - 1),
                jnp.clip(dec[:, 3], 0, hw[0] - 1)], axis=1)
            boxes.append(dec)
            scores.append(s.reshape(dec.shape[0], -1))
        boxes = jnp.concatenate(boxes)          # (A, 4)
        scores = jnp.concatenate(scores)        # (A, C)
        a_tot, c = scores.shape
        outs = []
        for cls in range(c):
            sc = scores[:, cls]
            if 0 < nms_top_k < a_tot:
                kth = -jnp.sort(-sc)[nms_top_k - 1]
                sc = jnp.where(sc >= kth, sc, -1.0)
            alive = _nms_alive(boxes, sc, nms_th, score_th,
                               nms_eta=nms_eta)
            outs.append((jnp.where(alive, sc, -1.0), boxes,
                         jnp.full(a_tot, cls + 1, jnp.float32)))
        s = jnp.concatenate([o[0] for o in outs])
        bb = jnp.concatenate([o[1] for o in outs])
        lab = jnp.concatenate([o[2] for o in outs])
        k = min(keep_top_k, int(s.shape[0]))
        top_s, idx = jax.lax.top_k(s, k)
        keep = top_s > score_th
        out = jnp.concatenate(
            [jnp.where(keep, lab[idx], -1.0)[:, None],
             jnp.where(keep, top_s, -1.0)[:, None],
             jnp.where(keep[:, None], bb[idx], 0.0)], axis=1)
        if k < keep_top_k:
            pad = jnp.full((keep_top_k - k, 6), -1.0, out.dtype)
            out = jnp.concatenate([out, pad.at[:, 2:].set(0.0)], axis=0)
        return out

    out = jax.vmap(lambda ds, ss, hw: per_image(list(ds), list(ss),
                                                hw))(
        tuple(deltas_list), tuple(scores_list), im_info[:, :2])
    return {"Out": out}


@register_op("roi_perspective_transform", nondiff=("ROIs",))
def _roi_perspective_transform(ctx, ins, attrs):
    """Perspective-warp roi crops (ref roi_perspective_transform_op.cc):
    input (N, C, H, W); rois (N, R, 8) quads [x1 y1 ... x4 y4] in
    clockwise order (image coordinates x spatial_scale); output
    (N, R, C, out_h, out_w) bilinear-sampled through the homography
    mapping the output grid onto each quad."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    out_h = int(attrs.get("transformed_height", 8))
    out_w = int(attrs.get("transformed_width", 8))
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def solve_h(quad):
        """Homography sending (0,0),(w-1,0),(w-1,h-1),(0,h-1) of the
        OUTPUT grid to the quad's 4 corners (8-dof DLT solve)."""
        src = jnp.asarray(
            [[0, 0], [out_w - 1, 0], [out_w - 1, out_h - 1],
             [0, out_h - 1]], jnp.float32)
        dst = quad.reshape(4, 2) * scale
        rows = []
        for i in range(4):
            sx, sy = src[i, 0], src[i, 1]
            dx, dy = dst[i, 0], dst[i, 1]
            rows.append(jnp.asarray(
                [sx, sy, 1, 0, 0, 0, 0, 0], jnp.float32
            ).at[6].set(-dx * sx).at[7].set(-dx * sy))
            rows.append(jnp.asarray(
                [0, 0, 0, sx, sy, 1, 0, 0], jnp.float32
            ).at[6].set(-dy * sx).at[7].set(-dy * sy))
        A = jnp.stack(rows)
        bvec = dst.reshape(-1)
        sol = jnp.linalg.solve(
            A + 1e-6 * jnp.eye(8, dtype=jnp.float32), bvec)
        return jnp.concatenate([sol, jnp.ones(1, jnp.float32)]
                               ).reshape(3, 3)

    yy, xx = jnp.meshgrid(jnp.arange(out_h, dtype=jnp.float32),
                          jnp.arange(out_w, dtype=jnp.float32),
                          indexing="ij")
    grid = jnp.stack([xx.reshape(-1), yy.reshape(-1),
                      jnp.ones(out_h * out_w, jnp.float32)])  # (3, P)

    def sample_one(img, quad):
        H = solve_h(quad)
        pts = H @ grid
        px = pts[0] / jnp.maximum(pts[2], 1e-6)
        py = pts[1] / jnp.maximum(pts[2], 1e-6)
        x0 = jnp.floor(px); y0 = jnp.floor(py)
        fx = px - x0; fy = py - y0
        def at(ix, iy):
            ix = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            iy = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            return img[:, iy, ix]                 # (C, P)
        val = (at(x0, y0) * (1 - fx) * (1 - fy) +
               at(x0 + 1, y0) * fx * (1 - fy) +
               at(x0, y0 + 1) * (1 - fx) * fy +
               at(x0 + 1, y0 + 1) * fx * fy)
        # points mapping outside the image are zeroed (reference rule)
        inside = ((px >= 0) & (px <= w - 1) & (py >= 0) & (py <= h - 1))
        return (val * inside[None, :]).reshape(c, out_h, out_w)

    out = jax.vmap(lambda img, qs: jax.vmap(
        lambda q: sample_one(img, q))(qs))(x, rois)
    return {"Out": out}


@register_op("generate_mask_labels",
             nondiff=("ImInfo", "GtClasses", "IsCrowd", "GtSegms",
                      "Rois", "LabelsInt32"), differentiable=False)
def _generate_mask_labels(ctx, ins, attrs):
    """Mask-RCNN mask targets (ref generate_mask_labels_op.cc), dense
    redesign: the reference takes polygon LoD; here GtSegms is a dense
    bitmap (B, G, S, S) registered to each gt box.  For every fg roi
    (label > 0) the matched gt's bitmap is warped into the roi window
    and resized to resolution^2.  MaskInt32 (B, R, num_classes * res *
    res) carries {0,1} targets in the roi's class slot and -1
    elsewhere/for non-fg rois (the reference's ignore convention)."""
    gt = ins["GtSegms"][0]
    rois = ins["Rois"][0]
    labels = ins["LabelsInt32"][0]
    gt_boxes = ins["GtBoxes"][0]   # bitmaps are registered to these
    gt_valid = jnp.any(gt_boxes != 0.0, axis=2)
    if ins.get("IsCrowd"):
        gt_valid = gt_valid & ~ins["IsCrowd"][0].reshape(
            gt_valid.shape).astype(bool)
    res = int(attrs.get("resolution", 14))
    num_classes = int(attrs.get("num_classes", 81))
    b, r = labels.shape
    g = gt.shape[1]
    s = gt.shape[-1]

    def one(roi_b, lab_b, gt_b, seg_b, v_b):
        iou = jnp.where(v_b[None, :], _pairwise_iou(roi_b, gt_b), -1.0)
        best = jnp.argmax(iou, axis=1)                    # (R,)

        def roi_mask(roi, gidx):
            box = gt_b[gidx]
            seg = seg_b[gidx]                             # (S, S)
            # sample the roi window out of the gt-registered bitmap
            ys = jnp.linspace(0.0, 1.0, res)
            xs = jnp.linspace(0.0, 1.0, res)
            ry = roi[1] + (roi[3] - roi[1]) * ys          # abs coords
            rx = roi[0] + (roi[2] - roi[0]) * xs
            gy = (ry - box[1]) / jnp.maximum(box[3] - box[1], 1e-6)
            gx = (rx - box[0]) / jnp.maximum(box[2] - box[0], 1e-6)
            iy = jnp.clip(jnp.round(gy * (s - 1)), 0, s - 1).astype(
                jnp.int32)
            ix = jnp.clip(jnp.round(gx * (s - 1)), 0, s - 1).astype(
                jnp.int32)
            inside = ((gy >= 0) & (gy <= 1))[:, None] & \
                ((gx >= 0) & (gx <= 1))[None, :]
            return jnp.where(inside, seg[iy[:, None], ix[None, :]],
                             0.0)

        masks = jax.vmap(roi_mask)(roi_b, best)           # (R, res, res)
        out = jnp.full((r, num_classes, res * res), -1.0)
        flat = masks.reshape(r, res * res)
        cls = jnp.clip(lab_b, 0, num_classes - 1)
        out = out.at[jnp.arange(r), cls].set(flat)
        fg = (lab_b > 0)[:, None, None]
        out = jnp.where(fg, out, -1.0)
        return out.reshape(r, num_classes * res * res)

    mask = jax.vmap(one)(rois, labels, gt_boxes, gt, gt_valid)
    has_mask = (labels > 0).astype(jnp.int32)
    return {"MaskRois": rois, "RoiHasMaskInt32": has_mask,
            "MaskInt32": mask.astype(jnp.int32)}
