"""Two-stage / RetinaNet training-side detection ops
(ref paddle/fluid/operators/detection/{rpn_target_assign_op,
retinanet_detection_output_op,generate_proposal_labels_op,
locality_aware_nms_op}.cc + python/paddle/fluid/layers/detection.py).

Dense TPU redesign: the reference emits LoD-compacted samples (a
variable number of sampled anchors/rois per image); XLA wants static
shapes, so these kernels return FULL per-anchor/per-roi tensors plus
{-1, 0, 1} label masks and 0/1 weight tensors — the downstream losses
multiply by the weights, which is numerically identical to gathering
the sampled subset.  Sampling uses the deterministic per-op PRNG
(ctx.rng) with score-jitter top-k instead of host-side shuffles.
"""
import jax
import jax.numpy as jnp

from .registry import register_op


def _pairwise_iou(a, b):
    """a (A, 4), b (G, 4) xyxy -> (A, G)."""
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix = jnp.maximum(
        0.0, jnp.minimum(ax2[:, None], bx2[None]) -
        jnp.maximum(ax1[:, None], bx1[None]))
    iy = jnp.maximum(
        0.0, jnp.minimum(ay2[:, None], by2[None]) -
        jnp.maximum(ay1[:, None], by1[None]))
    inter = ix * iy
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    union = area_a[:, None] + area_b[None] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _encode_boxes(anchors, gts):
    """Faster-RCNN box regression targets: anchors/gts (N, 4) xyxy ->
    (N, 4) [dx, dy, dw, dh]."""
    aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-6)
    ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-6)
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah
    gw = jnp.maximum(gts[:, 2] - gts[:, 0], 1e-6)
    gh = jnp.maximum(gts[:, 3] - gts[:, 1], 1e-6)
    gx = gts[:, 0] + 0.5 * gw
    gy = gts[:, 1] + 0.5 * gh
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(gw / aw), jnp.log(gh / ah)], axis=1)


def _sample_mask(key, eligible, count):
    """Pick <=count True positions of ``eligible`` uniformly: random
    scores, keep the count highest among eligible."""
    r = jax.random.uniform(key, eligible.shape)
    scored = jnp.where(eligible, r, -1.0)
    n_keep = jnp.minimum(count, jnp.sum(eligible))
    thresh = -jnp.sort(-scored)[jnp.maximum(n_keep - 1, 0)]
    picked = eligible & (scored >= thresh)
    return picked


def _crowd_ignore(anchors, gt, crowd_mask, thresh):
    """Anchors overlapping a crowd gt above ``thresh`` are ignored."""
    iou = _pairwise_iou(anchors, gt)
    crowd_iou = jnp.max(jnp.where(crowd_mask[None, :], iou, 0.0), axis=1)
    return crowd_iou >= thresh


def _inside_image(anchors, im_hw, straddle):
    """Reference straddle rule: with straddle >= 0, anchors poking more
    than ``straddle`` pixels outside the image are disabled."""
    h, w = im_hw[0], im_hw[1]
    return ((anchors[:, 0] >= -straddle) & (anchors[:, 1] >= -straddle) &
            (anchors[:, 2] < w + straddle) &
            (anchors[:, 3] < h + straddle))


def _assign_one(key, anchors, gt, gt_valid, pos_iou, neg_iou,
                batch_per_im, fg_frac, use_random, ignore_mask):
    """Per-image RPN assignment: labels (A,) in {-1,0,1}, matched gt
    index (A,), bbox targets (A, 4)."""
    iou = _pairwise_iou(anchors, gt)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    labels = jnp.full(anchors.shape[0], -1, jnp.int32)
    labels = jnp.where(best_iou < neg_iou, 0, labels)
    labels = jnp.where(best_iou >= pos_iou, 1, labels)
    # every valid gt gets its best anchor as positive (the reference's
    # "force at least one anchor per gt" rule)
    best_anchor = jnp.argmax(jnp.where(gt_valid[None, :], iou, -1.0),
                             axis=0)
    force = jnp.zeros(anchors.shape[0], bool).at[best_anchor].set(
        gt_valid)
    labels = jnp.where(force, 1, labels)
    labels = jnp.where(ignore_mask, -1, labels)

    n_fg = jnp.int32(batch_per_im * fg_frac)
    k1, k2 = jax.random.split(key)
    if use_random:
        fg_pick = _sample_mask(k1, labels == 1, n_fg)
    else:
        idx = jnp.cumsum((labels == 1).astype(jnp.int32))
        fg_pick = (labels == 1) & (idx <= n_fg)
    n_bg = jnp.int32(batch_per_im) - jnp.sum(fg_pick)
    if use_random:
        bg_pick = _sample_mask(k2, labels == 0, n_bg)
    else:
        idxb = jnp.cumsum((labels == 0).astype(jnp.int32))
        bg_pick = (labels == 0) & (idxb <= n_bg)
    labels = jnp.where(fg_pick, 1, jnp.where(bg_pick, 0, -1))
    tgt = _encode_boxes(anchors, gt[best_gt])
    return labels, best_gt, tgt


@register_op("rpn_target_assign",
             nondiff=("Anchor", "AnchorVar", "GtBoxes", "IsCrowd",
                      "ImInfo"), differentiable=False)
def _rpn_target_assign(ctx, ins, attrs):
    """Dense RPN targets (ref rpn_target_assign_op.cc): anchors (A, 4),
    gt_boxes (B, G, 4) zero-padded.  Returns per-anchor tensors:
    Labels (B, A) {-1 ignore, 0 bg, 1 fg}, BBoxTargets (B, A, 4),
    InsideWeights/OutsideWeights (B, A, 4) 1 on sampled foreground."""
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0]
    b = gt.shape[0]
    crowd = ins["IsCrowd"][0].reshape(b, -1).astype(bool) \
        if ins.get("IsCrowd") else None
    im_info = ins["ImInfo"][0] if ins.get("ImInfo") else None
    straddle = attrs.get("rpn_straddle_thresh", 0.0)
    gt_valid = jnp.any(gt != 0.0, axis=2)
    if crowd is not None:
        gt_valid = gt_valid & ~crowd
    keys = jax.random.split(ctx.rng(), b)

    def per_image(k, g, v, cm, hw):
        ignore = _crowd_ignore(
            anchors, g, cm, attrs.get("rpn_negative_overlap", 0.3))
        if straddle >= 0:
            ignore = ignore | ~_inside_image(anchors, hw, straddle)
        return _assign_one(
            k, anchors, g, v,
            attrs.get("rpn_positive_overlap", 0.7),
            attrs.get("rpn_negative_overlap", 0.3),
            attrs.get("rpn_batch_size_per_im", 256),
            attrs.get("rpn_fg_fraction", 0.5),
            attrs.get("use_random", True), ignore)

    labels, best_gt, tgt = jax.vmap(per_image)(
        keys, gt, gt_valid,
        crowd if crowd is not None else jnp.zeros(
            (b, gt.shape[1]), bool),
        im_info[:, :2] if im_info is not None else jnp.full(
            (b, 2), jnp.inf))
    fg = (labels == 1).astype(jnp.float32)[..., None]
    return {"Labels": labels, "BBoxTargets": tgt * fg,
            "BBoxInsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "BBoxOutsideWeights": jnp.broadcast_to(fg, tgt.shape)}


@register_op("retinanet_target_assign",
             nondiff=("Anchor", "AnchorVar", "GtBoxes", "GtLabels",
                      "IsCrowd", "ImInfo"), differentiable=False)
def _retinanet_target_assign(ctx, ins, attrs):
    """RetinaNet targets (ref retinanet_target_assign): like RPN but
    no sampling (focal loss handles imbalance); positives iou >= 0.5,
    negatives < 0.4, rest ignored.  Labels carry the gt CLASS (1-based;
    0 = background, -1 = ignore); also returns ForegroundNumber."""
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0]
    gt_labels = ins["GtLabels"][0]
    if gt_labels.ndim == 3:
        gt_labels = gt_labels[..., 0]
    gt_valid = jnp.any(gt != 0.0, axis=2)
    if ins.get("IsCrowd"):
        gt_valid = gt_valid & ~ins["IsCrowd"][0].reshape(
            gt_valid.shape).astype(bool)
    pos = attrs.get("positive_overlap", 0.5)
    neg = attrs.get("negative_overlap", 0.4)

    def one(g, gl, v):
        iou = jnp.where(v[None, :], _pairwise_iou(anchors, g), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        cls = gl[best_gt].astype(jnp.int32)
        labels = jnp.full(anchors.shape[0], -1, jnp.int32)
        labels = jnp.where(best_iou < neg, 0, labels)
        labels = jnp.where(best_iou >= pos, cls, labels)
        best_anchor = jnp.argmax(iou, axis=0)
        labels = labels.at[best_anchor].set(
            jnp.where(v, gl.astype(jnp.int32), labels[best_anchor]))
        tgt = _encode_boxes(anchors, g[best_gt])
        return labels, tgt

    labels, tgt = jax.vmap(one)(gt, gt_labels, gt_valid)
    fg = (labels >= 1).astype(jnp.float32)[..., None]
    fg_num = jnp.maximum(jnp.sum(fg.reshape(labels.shape[0], -1),
                                 axis=1), 1.0).astype(jnp.int32)
    return {"Labels": labels, "BBoxTargets": tgt * fg,
            "BBoxInsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "BBoxOutsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "ForegroundNumber": fg_num.reshape(-1, 1)}


@register_op("generate_proposal_labels",
             nondiff=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes",
                      "ImInfo"), differentiable=False)
def _generate_proposal_labels(ctx, ins, attrs):
    """Second-stage RoI sampling (ref generate_proposal_labels_op.cc),
    dense form: rois (B, R, 4), gts (B, G, 4)+classes.  Returns all R
    rois per image with Labels (B, R) {-1 ignore, 0 bg, class fg},
    BBoxTargets (B, R, 4) and inside/outside weights."""
    rois = ins["RpnRois"][0]
    gt = ins["GtBoxes"][0]
    classes = ins["GtClasses"][0]
    if classes.ndim == 3:
        classes = classes[..., 0]
    b = rois.shape[0]
    gt_valid = jnp.any(gt != 0.0, axis=2)
    if ins.get("IsCrowd"):
        gt_valid = gt_valid & ~ins["IsCrowd"][0].reshape(
            gt_valid.shape).astype(bool)
    fg_th = attrs.get("fg_thresh", 0.5)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)
    batch = attrs.get("batch_size_per_im", 512)
    fg_frac = attrs.get("fg_fraction", 0.25)
    use_random = attrs.get("use_random", True)
    reg_w = jnp.asarray(attrs.get("bbox_reg_weights",
                                  [0.1, 0.1, 0.2, 0.2]), jnp.float32)
    keys = jax.random.split(ctx.rng(), b)

    def one(key, r, g, gl, v):
        iou = jnp.where(v[None, :], _pairwise_iou(r, g), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        is_fg = best_iou >= fg_th
        is_bg = (best_iou < bg_hi) & (best_iou >= bg_lo)
        k1, k2 = jax.random.split(key)
        n_fg = jnp.int32(batch * fg_frac)
        if use_random:
            fg_pick = _sample_mask(k1, is_fg, n_fg)
            bg_pick = _sample_mask(
                k2, is_bg, jnp.int32(batch) - jnp.sum(fg_pick))
        else:
            idx_fg = jnp.cumsum(is_fg.astype(jnp.int32))
            fg_pick = is_fg & (idx_fg <= n_fg)
            idx_bg = jnp.cumsum(is_bg.astype(jnp.int32))
            bg_pick = is_bg & (idx_bg <= jnp.int32(batch) -
                               jnp.sum(fg_pick))
        cls = gl[best_gt].astype(jnp.int32)
        labels = jnp.where(fg_pick, cls,
                           jnp.where(bg_pick, 0, -1))
        # fluid convention: targets divided by bbox_reg_weights
        tgt = _encode_boxes(r, g[best_gt]) / reg_w[None, :]
        return labels, tgt

    labels, tgt = jax.vmap(one)(keys, rois, gt, classes, gt_valid)
    fg = (labels >= 1).astype(jnp.float32)[..., None]
    return {"Rois": rois, "Labels": labels, "BBoxTargets": tgt * fg,
            "BBoxInsideWeights": jnp.broadcast_to(fg, tgt.shape),
            "BBoxOutsideWeights": jnp.broadcast_to(fg, tgt.shape)}


@register_op("locality_aware_nms", nondiff=("BBoxes", "Scores"),
             differentiable=False)
def _locality_aware_nms(ctx, ins, attrs):
    """EAST-style locality-aware NMS (ref locality_aware_nms_op.cc):
    consecutive boxes with IoU above the threshold are merged by
    score-weighted averaging before standard class NMS.  Dense form:
    boxes (N, M, 4), scores (N, C, M); output (N, keep_top_k, 6)
    rows [label, score, x1, y1, x2, y2], -1-padded."""
    from .detection_ops import _nms_alive
    boxes = ins["BBoxes"][0]
    scores = ins["Scores"][0]
    iou_th = attrs.get("nms_threshold", 0.3)
    score_th = attrs.get("score_threshold", 0.0)
    keep_top_k = int(attrs.get("keep_top_k", 100))
    nms_top_k = int(attrs.get("nms_top_k", -1))
    normalized = attrs.get("normalized", True)
    nms_eta = attrs.get("nms_eta", 1.0)
    background = int(attrs.get("background_label", -1))
    n, c, m = scores.shape

    def merge_row(bx, sc):
        # weighted-merge sweep: each box merges into its predecessor
        # when IoU > threshold (locality assumption: boxes arrive in
        # reading order)
        iou_prev = jax.vmap(
            lambda i: _pairwise_iou(bx[i][None], bx[i - 1][None])[0, 0]
        )(jnp.arange(1, m))
        merge = jnp.concatenate([jnp.zeros(1), iou_prev]) > iou_th
        # segment ids: increase where not merging
        seg = jnp.cumsum(~merge)
        w = jnp.maximum(sc, 0.0)
        seg_w = jax.ops.segment_sum(w, seg, num_segments=m + 1)
        seg_box = jax.ops.segment_sum(bx * w[:, None], seg,
                                      num_segments=m + 1)
        seg_s = jax.ops.segment_sum(sc, seg, num_segments=m + 1) / \
            jnp.maximum(jax.ops.segment_sum(jnp.ones_like(sc), seg,
                                            num_segments=m + 1), 1.0)
        merged_box = seg_box / jnp.maximum(seg_w[:, None], 1e-8)
        # scatter back to first index of each segment
        first = jnp.concatenate([jnp.ones(1, bool), ~merge[1:]]) \
            if m > 1 else jnp.ones(1, bool)
        out_b = jnp.where(first[:, None], merged_box[seg], 0.0)
        out_s = jnp.where(first, seg_s[seg], -1.0)
        return out_b, out_s

    def per_image(bx, sc_all):
        rows = []
        for cls in range(c):
            if cls == background:
                continue
            mb, ms = merge_row(bx, sc_all[cls])
            if 0 < nms_top_k < m:
                # pre-truncate to the nms_top_k best candidates
                kth = -jnp.sort(-ms)[nms_top_k - 1]
                ms = jnp.where(ms >= kth, ms, -1.0)
            alive = _nms_alive(mb, ms, iou_th, score_th,
                               normalized=normalized,
                               nms_eta=nms_eta)
            s = jnp.where(alive, ms, -1.0)
            rows.append((s, mb, jnp.full(m, cls, jnp.float32)))
        s = jnp.concatenate([r[0] for r in rows])
        bb = jnp.concatenate([r[1] for r in rows])
        lab = jnp.concatenate([r[2] for r in rows])
        k = min(keep_top_k, int(s.shape[0]))
        top_s, idx = jax.lax.top_k(s, k)
        keep = top_s > score_th
        out = jnp.concatenate(
            [jnp.where(keep, lab[idx], -1.0)[:, None],
             jnp.where(keep, top_s, -1.0)[:, None],
             jnp.where(keep[:, None], bb[idx], 0.0)], axis=1)
        if k < keep_top_k:
            pad = jnp.full((keep_top_k - k, 6), -1.0, out.dtype)
            pad = pad.at[:, 2:].set(0.0)
            out = jnp.concatenate([out, pad], axis=0)
        return out

    return {"Out": jax.vmap(per_image)(boxes, scores)}
