"""Operator registry: op type -> pure JAX kernel.

Reference parity: paddle/fluid/framework/op_registry.h + op_info.cc. Where the
reference registers per-device OpKernels (CPU Eigen / CUDA), we register ONE
pure JAX function per op; XLA compiles it for TPU/CPU. Gradients need no
per-op registration: the generic ``grad_of`` op (framework/trace.py) computes
them with jax.vjp against the paired forward op — the TPU-native analogue of
GradOpDescMaker.

Kernel signature::

    fn(ctx, ins, attrs) -> {out_slot: [jax.Array, ...]}

  - ``ins``: dict slot -> list of jax.Arrays (slot order = OpDesc order)
  - ``attrs``: JSON-able dict
  - ``ctx``: trace context (ctx.rng() for PRNG keys, ctx.trace_block for
    control-flow sub-blocks). Kernels MUST be pure given (ins, attrs, ctx
    keys) — everything is traced under jax.jit.
"""

import os

_REGISTRY = {}

# PADDLE_TPU_OP_COVERAGE=<path>: append the op type of every kernel
# invocation to <path> — tools/op_coverage.py runs the suite with this to
# report registered-but-never-exercised kernels (the numeric-oracle-tail
# audit; zero overhead when unset).
_COVERAGE_PATH = os.environ.get("PADDLE_TPU_OP_COVERAGE")
_COVERAGE_SEEN = set()


def _track(op_type):
    if op_type not in _COVERAGE_SEEN:
        _COVERAGE_SEEN.add(op_type)
        with open(_COVERAGE_PATH, "a") as f:
            f.write(op_type + "\n")


class OpDef(object):
    __slots__ = ("type", "fn", "nondiff", "uses_rng", "uses_subblock",
                 "differentiable")

    def __init__(self, type, fn, nondiff=(), uses_rng=False,
                 uses_subblock=False, differentiable=True):
        self.type = type
        self.fn = fn
        # input slots excluded from differentiation (besides integer inputs,
        # which jax.vjp already maps to float0 and we drop)
        self.nondiff = tuple(nondiff)
        self.uses_rng = uses_rng
        self.uses_subblock = uses_subblock
        self.differentiable = differentiable


def register_op(type, nondiff=(), uses_rng=False, uses_subblock=False,
                differentiable=True):
    def deco(fn):
        if type in _REGISTRY:
            raise ValueError("op %r already registered" % type)
        if _COVERAGE_PATH:
            import functools
            inner = fn

            @functools.wraps(inner)
            def fn(*a, **kw):
                _track(type)
                return inner(*a, **kw)
        _REGISTRY[type] = OpDef(type, fn, nondiff, uses_rng, uses_subblock,
                                differentiable)
        return fn
    return deco


def get_op(type):
    op = _REGISTRY.get(type)
    if op is None:
        raise NotImplementedError(
            "op %r has no registered JAX kernel in paddle_tpu" % type)
    return op


def has_op(type):
    return type in _REGISTRY


def registered_ops():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# static shape/dtype inference rules (framework/analysis.py's shape pass)
#
# Colocated with the kernel registry the same way the reference colocates
# InferShape with each OpMaker: a rule is the kernel's static twin —
# fn(op, ins, attrs) -> {out_slot: [TensorMeta, ...]} over abstract
# (shape, dtype) metadata, raising ops.shape_rules.ShapeError on a
# violation. Ops WITHOUT a rule infer top (unknown) and never produce a
# diagnostic — the verifier must not false-positive on exotic kernels.
# ---------------------------------------------------------------------------

_SHAPE_RULES = {}


def register_shape_rule(*types):
    def deco(fn):
        for t in types:
            if t in _SHAPE_RULES:
                raise ValueError("shape rule for %r already registered" % t)
            _SHAPE_RULES[t] = fn
        return fn
    return deco


def get_shape_rule(type):
    """The op's static shape/dtype rule, or None (infer unknown)."""
    from . import shape_rules  # noqa: F401  (registers the rule set)
    return _SHAPE_RULES.get(type)
