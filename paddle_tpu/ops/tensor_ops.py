"""Tensor manipulation op kernels.

Reference parity: paddle/fluid/operators/{reshape_op,transpose_op,concat_op,
split_op,slice_op,gather_op,scatter_op,expand_op,stack_op,fill_constant_op,
assign_op,one_hot_op,range_op,arg_min_max,top_k_op,argsort_op,...}.
All shapes are static under the trace, so ops like ``shape`` constant-fold.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from ..framework.dtypes import to_jax_dtype


def _x(ins, slot="X"):
    return ins[slot][0]


@register_op("fill_constant")
def _fill_constant(ctx, ins, attrs):
    shape = attrs.get("shape", [1])
    dtype = to_jax_dtype(attrs.get("dtype", "float32"))
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0),
                            dtype=dtype)}


@register_op("fill_any_like")
def _fill_any_like(ctx, ins, attrs):
    x = _x(ins)
    dtype = attrs.get("dtype")
    dtype = to_jax_dtype(dtype) if dtype else x.dtype
    return {"Out": jnp.full(x.shape, attrs.get("value", 0.0), dtype=dtype)}


@register_op("fill_constant_batch_size_like", nondiff=("Input",))
def _fill_constant_batch_size_like(ctx, ins, attrs):
    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        x.shape[attrs.get("input_dim_idx", 0)]
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0),
                            dtype=to_jax_dtype(attrs.get("dtype",
                                                         "float32")))}


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": jnp.zeros_like(_x(ins))}


@register_op("assign")
def _assign(ctx, ins, attrs):
    return {"Out": _x(ins)}


@register_op("assign_value")
def _assign_value(ctx, ins, attrs):
    vals = attrs["values"]
    shape = attrs["shape"]
    dtype = to_jax_dtype(attrs.get("dtype", "float32"))
    return {"Out": jnp.asarray(np.array(vals).reshape(shape), dtype=dtype)}


@register_op("shape", nondiff=("Input",))
def _shape(ctx, ins, attrs):
    return {"Out": jnp.asarray(ins["Input"][0].shape, dtype=jnp.int32)}


@register_op("increment")
def _increment(ctx, ins, attrs):
    x = _x(ins)
    # keep the counter's dtype: int counters + python-float step would
    # weak-promote to float32 and break loop-carry type invariants
    return {"Out": x + jnp.asarray(attrs.get("step", 1.0), x.dtype)}


@register_op("reshape2")
def _reshape2(ctx, ins, attrs):
    x = _x(ins)
    shape = list(attrs["shape"])
    # fluid semantics: 0 means "copy this dim from input"
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": x.reshape(shape)}


@register_op("flatten2")
def _flatten2(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", 1)
    lead = math.prod(x.shape[:axis]) if axis else 1
    return {"Out": x.reshape(lead, -1)}


@register_op("flatten_contiguous_range")
def _flatten_range(ctx, ins, attrs):
    x = _x(ins)
    start = attrs.get("start_axis", 1) % x.ndim
    stop = attrs.get("stop_axis", -1) % x.ndim
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return {"Out": x.reshape(shape)}


@register_op("transpose2")
def _transpose2(ctx, ins, attrs):
    return {"Out": jnp.transpose(_x(ins), attrs["axis"])}


@register_op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@register_op("split")
def _split(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if num:
        outs = jnp.split(x, num, axis=axis)
    else:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    return {"Out": list(outs)}


@register_op("squeeze2")
def _squeeze2(ctx, ins, attrs):
    x = _x(ins)
    axes = attrs.get("axes", [])
    if not axes:
        return {"Out": jnp.squeeze(x)}
    axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return {"Out": jnp.squeeze(x, axis=axes) if axes else x}


@register_op("unsqueeze2")
def _unsqueeze2(ctx, ins, attrs):
    x = _x(ins)
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": x}


@register_op("stack")
def _stack(ctx, ins, attrs):
    return {"Y": jnp.stack(ins["X"], axis=attrs.get("axis", 0))}


@register_op("unstack")
def _unstack(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    return {"Y": [jnp.squeeze(s, axis=axis)
                  for s in jnp.split(x, x.shape[axis], axis=axis)]}


@register_op("slice")
def _slice(ctx, ins, attrs):
    x = ins["Input"][0]
    axes, starts, ends = attrs["axes"], attrs["starts"], attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    for a in sorted(attrs.get("decrease_axis", []) or [], reverse=True):
        out = jnp.squeeze(out, axis=a)
    return {"Out": out}


@register_op("strided_slice")
def _strided_slice(ctx, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                           attrs["strides"]):
        idx[a] = slice(s, e, st)
    return {"Out": x[tuple(idx)]}


@register_op("gather", nondiff=("Index",))
def _gather(ctx, ins, attrs):
    x, index = ins["X"][0], ins["Index"][0]
    if index.ndim == 2 and index.shape[1] == 1:
        index = index.reshape(-1)
    return {"Out": jnp.take(x, index.astype(jnp.int32),
                            axis=attrs.get("axis", 0) or 0)}


@register_op("gather_nd", nondiff=("Index",))
def _gather_nd(ctx, ins, attrs):
    x, index = ins["X"][0], ins["Index"][0]
    idx = tuple(jnp.moveaxis(index.astype(jnp.int32), -1, 0))
    return {"Out": x[idx]}


@register_op("scatter", nondiff=("Ids",))
def _scatter(ctx, ins, attrs):
    x, ids, updates = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    ids = ids.reshape(-1).astype(jnp.int32)
    if attrs.get("overwrite", True):
        return {"Out": x.at[ids].set(updates)}
    return {"Out": x.at[ids].add(updates)}


@register_op("scatter_nd_add", nondiff=("Index",))
def _scatter_nd_add(ctx, ins, attrs):
    x, index, updates = ins["X"][0], ins["Index"][0], ins["Updates"][0]
    idx = tuple(jnp.moveaxis(index.astype(jnp.int32), -1, 0))
    return {"Out": x.at[idx].add(updates)}


@register_op("index_select", nondiff=("Index",))
def _index_select(ctx, ins, attrs):
    x, index = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take(x, index.astype(jnp.int32),
                            axis=attrs.get("dim", 0))}


@register_op("expand")
def _expand(ctx, ins, attrs):
    x = _x(ins)
    times = attrs["expand_times"]
    return {"Out": jnp.tile(x, tuple(times))}


@register_op("expand_as")
def _expand_as(ctx, ins, attrs):
    x, target = ins["X"][0], ins["target_tensor"][0]
    times = [t // s for t, s in zip(target.shape, x.shape)]
    return {"Out": jnp.tile(x, tuple(times))}


@register_op("tile")
def _tile(ctx, ins, attrs):
    return {"Out": jnp.tile(_x(ins), tuple(attrs["repeat_times"]))}


@register_op("range", nondiff=("Start", "End", "Step"))
def _range(ctx, ins, attrs):
    s = ins["Start"][0].reshape(())
    e = ins["End"][0].reshape(())
    st = ins["Step"][0].reshape(())
    # shapes must be static: require concrete python scalars at build time
    s, e, st = float(s), float(e), float(st)
    n = max(0, int(math.ceil((e - s) / st)))
    return {"Out": (s + st * jnp.arange(n)).astype(ins["Start"][0].dtype)}


@register_op("linspace", nondiff=("Start", "Stop", "Num"))
def _linspace(ctx, ins, attrs):
    s = float(ins["Start"][0].reshape(()))
    e = float(ins["Stop"][0].reshape(()))
    n = int(ins["Num"][0].reshape(()))
    return {"Out": jnp.linspace(s, e, n, dtype=ins["Start"][0].dtype)}


@register_op("arg_max", nondiff=("X",))
def _arg_max(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    out = jnp.argmax(x, axis=axis).astype(jnp.int64)
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, axis)
    return {"Out": out}


@register_op("arg_min", nondiff=("X",))
def _arg_min(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    return {"Out": jnp.argmin(x, axis=axis).astype(jnp.int64)}


@register_op("argsort")
def _argsort(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


@register_op("top_k")
def _top_k(ctx, ins, attrs):
    x = _x(ins)
    k = attrs["k"]
    vals, idx = lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("where")
def _where(ctx, ins, attrs):
    cond, x, y = ins["Condition"][0], ins["X"][0], ins["Y"][0]
    return {"Out": jnp.where(cond, x, y)}


@register_op("where_index", nondiff=("Condition",))
def _where_index(ctx, ins, attrs):
    # Dynamic-shaped in the reference; here only usable outside jit traces.
    cond = ins["Condition"][0]
    return {"Out": jnp.argwhere(cond).astype(jnp.int64)}


@register_op("flip")
def _flip(ctx, ins, attrs):
    return {"Out": jnp.flip(_x(ins), axis=tuple(attrs["axis"]))}


@register_op("roll")
def _roll(ctx, ins, attrs):
    return {"Out": jnp.roll(_x(ins), tuple(attrs["shifts"]),
                            axis=tuple(attrs["axis"]))}


@register_op("tril_triu")
def _tril_triu(ctx, ins, attrs):
    x = _x(ins)
    k = attrs.get("diagonal", 0)
    if attrs.get("lower", True):
        return {"Out": jnp.tril(x, k)}
    return {"Out": jnp.triu(x, k)}


@register_op("eye")
def _eye(ctx, ins, attrs):
    return {"Out": jnp.eye(attrs["num_rows"],
                           attrs.get("num_columns", attrs["num_rows"]),
                           dtype=to_jax_dtype(attrs.get("dtype", "float32")))}


@register_op("diag")
def _diag(ctx, ins, attrs):
    return {"Out": jnp.diag(ins["Diagonal"][0])}


@register_op("sequence_mask", nondiff=("X",))
def _sequence_mask(ctx, ins, attrs):
    x = _x(ins)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError("sequence_mask needs a static maxlen on TPU")
    mask = jnp.arange(maxlen)[None, :] < x.reshape(-1, 1)
    mask = mask.reshape(tuple(x.shape) + (maxlen,))
    return {"Y": mask.astype(to_jax_dtype(attrs.get("out_dtype", "int64")))}


@register_op("take_along_axis", nondiff=("Index",))
def _take_along_axis(ctx, ins, attrs):
    x, index = ins["Input"][0], ins["Index"][0]
    return {"Result": jnp.take_along_axis(x, index.astype(jnp.int32),
                                          axis=attrs.get("Axis", 0))}


@register_op("meshgrid")
def _meshgrid(ctx, ins, attrs):
    outs = jnp.meshgrid(*ins["X"], indexing="ij")
    return {"Out": list(outs)}


@register_op("coalesce_tensor")
def _coalesce_tensor(ctx, ins, attrs):
    # Reference fuses grads into one buffer for NCCL; XLA does its own
    # buffer management, so this is an identity pass-through.
    return {"Output": list(ins["Input"]), "FusedOutput":
            jnp.concatenate([x.reshape(-1) for x in ins["Input"]])}


@register_op("load_tensor", differentiable=False)
def _load_tensor(ctx, ins, attrs):
    """Host-side tensor load at trace time (ref load_op.cc; used by
    startup-style programs, so the file read happens once per compile)."""
    import numpy as np
    arr = np.load(attrs["file_path"])
    if attrs.get("load_as_fp16"):
        arr = arr.astype(np.float16)
    return {"Out": jnp.asarray(arr)}
