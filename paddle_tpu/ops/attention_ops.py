"""Attention op kernels.

Reference parity: the reference composes attention from matmul/softmax ops
(e.g. PaddlePaddle/models transformer, fluid nets.scaled_dot_product_attention).
TPU-native: one fused op so XLA keeps QK^T / softmax / PV in registers, plus
a Pallas flash-attention path (ops/pallas/) for long sequences that tiles the
computation through VMEM without materializing the (T,T) scores in HBM.
"""
import functools

import os

import jax
import jax.numpy as jnp

from .registry import register_op


def _sdpa_xla(q, k, v, mask, scale, causal):
    # q,k,v: (B, H, T, D)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def _sp_routable(impl, q, k, mask, n):
    """Whether this call CAN run sequence-parallel over an n-way axis —
    the env hint must stay a hint: shapes that don't shard keep their
    auto fallback instead of raising inside shard_map."""
    if q.shape[-2] % n or k.shape[-2] % n or q.shape[-2] != k.shape[-2]:
        return False
    if impl == "ulysses":
        if q.shape[1] % n:
            return False
        if mask is not None:
            ax = mask.ndim - 1 if mask.shape[-2] == 1 else mask.ndim - 2
            return mask.shape[ax] % n == 0
        return True
    if mask is not None:
        return mask.shape[-2] == 1 and mask.shape[-1] % n == 0
    return True


@register_op("scaled_dot_product_attention")
def _sdpa(ctx, ins, attrs):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    mask = ins["Mask"][0] if ins.get("Mask") else None
    scale = attrs.get("scale", None)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    causal = attrs.get("causal", False)
    impl = attrs.get("impl", "auto")
    if impl == "auto":
        # perf escape hatch: force a path fleet-wide. For ring/ulysses
        # the env value is a HINT, not a hard override — ops that can't
        # run sequence-parallel (additive mask, no sp mesh installed)
        # keep their auto fallback instead of raising.
        env_impl = os.environ.get("PADDLE_TPU_ATTN_IMPL", "auto")
        if env_impl in ("ring", "ulysses"):
            from ..distributed.mesh import get_mesh
            m = get_mesh()
            if m is not None and attrs.get("sp_axis", "sp") in m.axis_names:
                n = m.shape[attrs.get("sp_axis", "sp")]
                if _sp_routable(env_impl, q, k, mask, n):
                    impl = env_impl
        else:
            impl = env_impl
    if impl == "auto" and q.shape[-2] * k.shape[-2] <= 256 * 256:
        # short sequences: XLA's fused attention beats the tiled kernel
        # (measured 1026 vs 912 samples/s on BERT-base seq128, v5e) — the
        # (T,T) tile only pays for itself once it stops fitting in VMEM
        impl = "xla"
    if impl in ("ring", "ulysses"):
        # sequence-parallel attention over the installed mesh's sp axis —
        # the declarative (static-graph) route to the long-context paths
        # in distributed/{ring,ulysses}_attention.py
        from ..distributed.mesh import get_mesh
        axis = attrs.get("sp_axis", "sp")
        mesh = get_mesh()
        if mesh is None or axis not in mesh.axis_names:
            raise ValueError(
                "fused_attention(impl=%r) needs init_mesh/fleet.init with "
                "a %r mesh axis" % (impl, axis))
        if impl == "ring":
            if mask is not None and mask.shape[-2] != 1:
                raise ValueError(
                    "fused_attention(impl='ring') supports key-padding "
                    "masks (..., 1, T) only — the mask's key axis rides "
                    "the ring with K/V; per-query masks need "
                    "impl='ulysses'")
            from ..distributed.ring_attention import ring_attention
            return {"Out": ring_attention(q, k, v, mask=mask, mesh=mesh,
                                          axis_name=axis, causal=causal,
                                          scale=scale)}
        from ..distributed.ulysses_attention import ulysses_attention
        return {"Out": ulysses_attention(q, k, v, mask=mask, mesh=mesh,
                                         axis_name=axis, causal=causal,
                                         scale=scale)}
    if impl in ("auto", "flash"):
        try:
            from .pallas.flash_attention import flash_attention
            out = flash_attention(q, k, v, mask=mask, scale=scale,
                                  causal=causal)
            return {"Out": out}
        except Exception:
            if impl == "flash":
                raise
    return {"Out": _sdpa_xla(q, k, v, mask, scale, causal)}
