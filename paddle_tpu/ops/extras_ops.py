"""Long-tail op kernels closing the reference layers/nn.py surface.

Reference parity: paddle/fluid/operators/{scatter_nd_add_op (scatter_nd),
gather_tree_op.h, hash_op.h, space_to_depth_op, shuffle_channel_op,
similarity_focus_op, filter_by_instag_op, random_crop_op, ctc_align_op
(ctc_greedy_decoder), interpolate_op (trilinear), cvm_op}. Kernels are
pure JAX; sequential reference algorithms (similarity focus's greedy
row/col elimination, gather_tree's back-trace) become lax.scan loops.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register_op


def _x(ins, slot="X"):
    return ins[slot][0]


@register_op("scatter_nd", nondiff=("Index",))
def _scatter_nd(ctx, ins, attrs):
    """Out[shape]; Out[index[i]] += updates[i] (duplicates accumulate,
    ref scatter_nd op)."""
    index = ins["Index"][0]
    updates = ins["Updates"][0]
    shape = tuple(attrs["shape"])
    zeros = jnp.zeros(shape, updates.dtype)
    k = index.shape[-1]
    idx = tuple(index[..., i] for i in range(k))
    return {"Out": zeros.at[idx].add(updates)}


@register_op("gather_tree", nondiff=("Ids", "Parents"), differentiable=False)
def _gather_tree(ctx, ins, attrs):
    """Beam-search back-trace (ref gather_tree_op.h): walk parents from the
    last step to recover each beam's full token path."""
    ids = ins["Ids"][0]          # (T, B, W)
    parents = ins["Parents"][0]
    t = ids.shape[0]
    last = ids[t - 1]
    parent0 = parents[t - 1]

    def step(carry, inp):
        parent = carry                     # (B, W) beam index per slot
        ids_t, parents_t = inp             # step t's (B, W)
        tok = jnp.take_along_axis(ids_t, parent, axis=1)
        parent = jnp.take_along_axis(parents_t, parent, axis=1)
        return parent, tok

    _, toks = lax.scan(step, parent0, (ids[:t - 1], parents[:t - 1]),
                       reverse=True)
    return {"Out": jnp.concatenate([toks, last[None]], axis=0)}


@register_op("hash", nondiff=("X",), differentiable=False)
def _hash(ctx, ins, attrs):
    """Deterministic multi-seed integer hash of each id row into
    [0, mod_by) (ref hash_op.h uses xxhash; the hash family differs but
    the contract — shape (*dims[:-1], num_hash, 1), bounded values,
    per-seed independence — is the same)."""
    x = _x(ins).astype(jnp.uint32)
    mod_by = int(attrs["mod_by"])
    num_hash = int(attrs.get("num_hash", 1))
    # fold the last dim (the id tuple) with a different seed per hash
    outs = []
    for i in range(num_hash):
        h = jnp.uint32(2166136261 ^ (i * 16777619))
        for j in range(x.shape[-1]):
            h = (h ^ x[..., j]) * jnp.uint32(16777619)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    out = jnp.stack(outs, axis=-1)[..., None]
    return {"Out": out}


@register_op("space_to_depth")
def _space_to_depth(ctx, ins, attrs):
    x = _x(ins)                  # (N, C, H, W)
    b = int(attrs["blocksize"])
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": x.reshape(n, c * b * b, h // b, w // b)}


@register_op("shuffle_channel")
def _shuffle_channel(ctx, ins, attrs):
    x = _x(ins)                  # (N, C, H, W)
    g = int(attrs["group"])
    n, c, h, w = x.shape
    return {"Out": x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
            .reshape(n, c, h, w)}


@register_op("similarity_focus", nondiff=("X",), differentiable=False)
def _similarity_focus(ctx, ins, attrs):
    """Greedy row/column-exclusive maxima mask (ref similarity_focus_op):
    per selected channel slice (B_, C_) pick min(B_, C_) maxima such that
    each row/column is used at most once; OR the masks over indexes."""
    x = _x(ins)                  # (N, A, B_, C_) with axis=1, or axis=2
    axis = int(attrs["axis"])
    indexes = list(attrs["indexes"])
    if axis != 1:
        x = jnp.moveaxis(x, axis, 1)
    n, a, b_, c_ = x.shape
    npick = min(b_, c_)

    def per_slice(t):            # (B_, C_) -> (B_, C_) 0/1 mask
        def pick(carry, _):
            mask, row_used, col_used = carry
            neg = jnp.where(row_used[:, None] | col_used[None, :],
                            -jnp.inf, t)
            flat = jnp.argmax(neg.reshape(-1))
            i, j = flat // c_, flat % c_
            mask = mask.at[i, j].set(1.0)
            return (mask, row_used.at[i].set(True),
                    col_used.at[j].set(True)), None

        (mask, _, _), _ = lax.scan(
            pick, (jnp.zeros((b_, c_), x.dtype),
                   jnp.zeros(b_, bool), jnp.zeros(c_, bool)),
            None, length=npick)
        return mask

    masks = jnp.zeros((n, b_, c_), x.dtype)
    for idx in indexes:
        masks = jnp.maximum(masks, jax.vmap(per_slice)(x[:, idx]))
    out = jnp.broadcast_to(masks[:, None], (n, a, b_, c_))
    if axis != 1:
        out = jnp.moveaxis(out, 1, axis)
    return {"Out": out}


@register_op("filter_by_instag", nondiff=("Ins", "Ins_tag", "Filter_tag"))
def _filter_by_instag(ctx, ins, attrs):
    """Keep rows whose tag set intersects the filter tags (ref
    filter_by_instag_op). Dense/static form: kept rows are packed to the
    top (order preserved), the rest zeroed; LossWeight is the keep mask
    and IndexMap maps packed row -> original row."""
    rows = ins["Ins"][0]                   # (N, D)
    tags = ins["Ins_tag"][0]               # (N, K) int
    filt = ins["Filter_tag"][0]            # (F,) int
    keep = jnp.any(tags[..., None] == filt[None, None, :], axis=(1, 2))
    n = rows.shape[0]
    order = jnp.argsort(~keep, stable=True)    # kept rows first
    packed = jnp.take(rows, order, axis=0)
    kept_sorted = jnp.take(keep, order)
    out = packed * kept_sorted[:, None].astype(rows.dtype)
    return {"Out": out,
            "LossWeight": kept_sorted.astype(rows.dtype).reshape(n, 1),
            "IndexMap": jnp.stack([order.astype(jnp.int64),
                                   jnp.arange(n, dtype=jnp.int64)], axis=1)}


@register_op("random_crop", nondiff=("Seed",), uses_rng=True,
             differentiable=False)
def _random_crop(ctx, ins, attrs):
    """Per-example random spatial crop to attrs['shape'] (ref
    random_crop_op): offsets drawn from the op's deterministic PRNG."""
    x = _x(ins)
    out_shape = tuple(attrs["shape"])      # trailing dims to crop to
    lead = x.ndim - len(out_shape)
    key = ctx.rng()
    starts = []
    for i, os_ in enumerate(out_shape):
        key, sub = jax.random.split(key)
        hi = x.shape[lead + i] - os_ + 1
        starts.append(jax.random.randint(sub, (), 0, hi))
    idx = tuple([slice(None)] * lead)
    out = lax.dynamic_slice(
        x, [jnp.int32(0)] * lead + [s.astype(jnp.int32) for s in starts],
        x.shape[:lead] + out_shape)
    return {"Out": out}


@register_op("ctc_greedy_decoder", nondiff=("Input", "Length"),
             differentiable=False)
def _ctc_greedy_decoder(ctx, ins, attrs):
    """argmax per step -> collapse repeats -> drop blank (ref
    ctc_align_op). Dense form: input (N, T, V) probs + optional lengths;
    returns (N, T) decoded ids padded with -1 plus per-row lengths."""
    probs = ins["Input"][0]
    blank = int(attrs.get("blank", 0))
    n, t, _ = probs.shape
    ids = jnp.argmax(probs, axis=-1)       # (N, T)
    if ins.get("Length"):
        lens = ins["Length"][0].reshape(-1)
        valid = jnp.arange(t)[None, :] < lens[:, None]
    else:
        valid = jnp.ones((n, t), bool)
    prev = jnp.concatenate([jnp.full((n, 1), -1, ids.dtype), ids[:, :-1]],
                           axis=1)
    keep = (ids != blank) & (ids != prev) & valid
    order = jnp.argsort(~keep, axis=1, stable=True)
    packed = jnp.take_along_axis(ids, order, axis=1)
    kept_sorted = jnp.take_along_axis(keep, order, axis=1)
    pad = int(attrs.get("padding_value", -1))
    out = jnp.where(kept_sorted, packed, pad)
    return {"Out": out, "OutLength": jnp.sum(keep, axis=1)
            .astype(jnp.int32)}


@register_op("resize_trilinear", nondiff=("OutSize",))
def _resize_trilinear(ctx, ins, attrs):
    """3-D linear resize of (N, C, D, H, W) (ref interpolate_op trilinear
    path) via jax.image.resize."""
    x = _x(ins)
    out_dhw = tuple(attrs["out_shape"])
    shape = x.shape[:2] + out_dhw
    return {"Out": jax.image.resize(x, shape, method="trilinear")
            .astype(x.dtype)}


@register_op("cvm")
def _cvm(ctx, ins, attrs):
    """Show/click handling for CTR embeddings (ref cvm_op): use_cvm keeps
    D (first two dims replaced with log(show), log(click)); otherwise the
    two leading dims are removed."""
    x = _x(ins)                   # (N, D), D = 2 + emb
    cvm = ins["CVM"][0]           # (N, 2) show, click
    if attrs.get("use_cvm", True):
        logs = jnp.log(jnp.maximum(cvm.astype(jnp.float32), 1e-20) + 1.0)
        return {"Y": jnp.concatenate([logs.astype(x.dtype), x[:, 2:]],
                                     axis=1)}
    return {"Y": x[:, 2:]}


@register_op("deformable_roi_pooling", nondiff=("ROIs",))
def _deformable_roi_pooling(ctx, ins, attrs):
    """Deformable (PS-)RoI pooling (ref deformable_psroi_pooling_op.h):
    each pooled bin's sampling box is shifted by trans_std * Trans before
    average pooling. Dense form: ROIs (R, 5) with batch index in col 0,
    Trans (R, 2, PH, PW)."""
    x = ins["Input"][0]                     # (N, C, H, W)
    rois = ins["ROIs"][0]                   # (R, 5): n, x1, y1, x2, y2
    trans = ins["Trans"][0]                 # (R, 2, PH, PW) offsets
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    ss = float(attrs.get("spatial_scale", 1.0))
    tstd = float(attrs.get("trans_std", 0.1))
    pos_sensitive = bool(attrs.get("position_sensitive", False))
    n, c, h, w = x.shape
    r = rois.shape[0]
    batch_idx = rois[:, 0].astype(jnp.int32)
    boxes = rois[:, 1:]
    feats = jnp.take(x, batch_idx, axis=0)  # (R, C, H, W)

    x1 = boxes[:, 0] * ss
    y1 = boxes[:, 1] * ss
    rw = jnp.maximum(boxes[:, 2] * ss - x1, 0.1)
    rh = jnp.maximum(boxes[:, 3] * ss - y1, 0.1)
    bw = (rw / pw)[:, None, None]
    bh = (rh / ph)[:, None, None]
    jj, ii = jnp.meshgrid(jnp.arange(pw), jnp.arange(ph))  # (PH, PW)
    cx = x1[:, None, None] + (jj[None] + 0.5) * bw
    cy = y1[:, None, None] + (ii[None] + 0.5) * bh
    # deformation: per-bin (dy, dx) scaled by trans_std and roi size
    cy = cy + trans[:, 0] * tstd * rh[:, None, None]
    cx = cx + trans[:, 1] * tstd * rw[:, None, None]
    cy = jnp.clip(cy, 0.0, h - 1.0)
    cx = jnp.clip(cx, 0.0, w - 1.0)
    y0 = jnp.floor(cy).astype(jnp.int32)
    x0 = jnp.floor(cx).astype(jnp.int32)
    y1i = jnp.minimum(y0 + 1, h - 1)
    x1i = jnp.minimum(x0 + 1, w - 1)
    fy = (cy - y0)[:, None]                 # (R, 1, PH, PW)
    fx = (cx - x0)[:, None]

    def gather(feat, yy, xx):
        # feat (R, C, H, W); yy/xx (R, PH, PW) -> (R, C, PH, PW)
        flat = feat.reshape(r, c, h * w)
        idx = (yy * w + xx)[:, None].repeat(c, axis=1)
        return jnp.take_along_axis(flat, idx.reshape(r, c, ph * pw),
                                   axis=2).reshape(r, c, ph, pw)

    v00 = gather(feats, y0, x0)
    v01 = gather(feats, y0, x1i)
    v10 = gather(feats, y1i, x0)
    v11 = gather(feats, y1i, x1i)
    out = (v00 * (1 - fy) * (1 - fx) + v01 * (1 - fy) * fx +
           v10 * fy * (1 - fx) + v11 * fy * fx)
    if pos_sensitive:
        # channel block (i, j) feeds output channel slice for bin (i, j):
        # out2[r, ch, i, j] = out[r, (i, j) block, ch, i, j]
        co = c // (ph * pw)
        out = out.reshape(r, ph, pw, co, ph, pw)
        ii = jnp.arange(ph)[:, None]
        jj = jnp.arange(pw)[None, :]
        # advanced indices at axes 1,2,4,5 (non-adjacent to the slices) ->
        # result (ph, pw, r, co); bring r, co back to the front
        out = out[:, ii, jj, :, ii, jj].transpose(2, 3, 0, 1)
    return {"Output": out}
