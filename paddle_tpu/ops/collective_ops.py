"""Collective communication op kernels.

Reference parity: paddle/fluid/operators/collective/{c_allreduce_*,
c_allgather,c_reducescatter,c_broadcast}.cc (NCCL). TPU-native: XLA
collectives (lax.psum/all_gather/psum_scatter/ppermute) over the ICI mesh.

These kernels are meaningful when traced under shard_map with a bound mesh
axis (paddle_tpu.distributed). Single-device traces degrade to identity, so
the same program runs anywhere — mirroring the reference where ring_id 0 on
one rank is a no-op.
"""
import contextlib
import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from . import quant_ops


def _axis(ctx, attrs):
    """Axis name for the collective; None → not inside shard_map → no-op."""
    name = attrs.get("axis_name", "dp")
    bound = getattr(ctx, "bound_axes", ())
    return name if name in bound else None


def _axis_size(axis_name):
    """lax.axis_size compat: jax 0.4.x has no lax.axis_size, but psum of
    a literal 1 constant-folds to the static axis size at trace time."""
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


def _make_allreduce(op_name, reduce_fn):
    @register_op(op_name, differentiable=True)
    def _kernel(ctx, ins, attrs, _fn=reduce_fn):
        x = ins["X"][0]
        ax = _axis(ctx, attrs)
        return {"Out": x if ax is None else _fn(x, ax)}
    return _kernel


_make_allreduce("c_allreduce_sum", lax.psum)
_make_allreduce("c_allreduce_max", lax.pmax)
_make_allreduce("c_allreduce_min", lax.pmin)
_make_allreduce("c_allreduce_prod",
                lambda x, ax: jnp.exp(lax.psum(jnp.log(x), ax)))


@register_op("c_allgather")
def _c_allgather(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    return {"Out": lax.all_gather(x, ax, axis=0, tiled=True)}


@register_op("c_reducescatter")
def _c_reducescatter(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    return {"Out": lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True)}


@register_op("c_broadcast")
def _c_broadcast(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    root = attrs.get("root", 0)
    idx = lax.axis_index(ax)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": lax.psum(masked, ax)}


@register_op("c_sync_comm_stream")
def _c_sync(ctx, ins, attrs):
    # XLA orders collectives itself; kept for program parity.
    return {"Out": list(ins["X"])}


@register_op("barrier", differentiable=False)
def _barrier(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    return {"Out": x + 0 * lax.psum(jnp.zeros((), x.dtype), ax)}


@register_op("ppermute")
def _ppermute(ctx, ins, attrs):
    """Ring shift (building block of ring attention / pipeline parallel)."""
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    n = _axis_size(ax)
    shift = attrs.get("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": lax.ppermute(x, ax, perm)}


# ---------------------------------------------------------------------------
# block-quantized all-reduce (EQuARX, PAPERS.md)
# ---------------------------------------------------------------------------

def quantized_psum(x, axis_name, block_size=quant_ops.DEFAULT_BLOCK_SIZE,
                   bits=quant_ops.DEFAULT_BITS, mean=False):
    """Quantize -> sum-over-axis -> dequantize, wire-honest: each member
    quantizes its LOCAL contribution (int8 payload + per-block fp32
    scale), the int8 blocks + scales are what cross the axis
    (lax.all_gather of int8), and every member dequantizes + sums the
    gathered contributions in fp32. Deterministic and bitwise-identical
    on every member (the gather axis fixes the summation order), so
    replicated state updated from the result stays replicated.

    ``mean=True`` divides by the axis size — the data-parallel gradient
    sync (global grad = mean over shards of local grads of local-mean
    losses). Accuracy model matches EQuARX: one quantization per
    contribution, exact fp32 accumulation of the dequantized values.
    """
    q, scale = quant_ops.block_quantize(x, block_size, bits)
    gq = lax.all_gather(q, axis_name)          # (n, n_blocks, block) int8
    gs = lax.all_gather(scale, axis_name)      # (n, n_blocks) fp32
    qmax = 2.0 ** (int(bits) - 1) - 1
    deq = gq.astype(jnp.float32) \
        * (jnp.maximum(gs, 1e-12) / qmax)[..., None]
    tot = jnp.sum(deq, axis=0)
    if mean:
        tot = tot / _axis_size(axis_name)
    size = int(np.prod(x.shape)) if x.shape else 1
    return tot.reshape(-1)[:size].reshape(x.shape).astype(x.dtype)


@register_op("c_allreduce_sum_quant")
def _c_allreduce_sum_quant(ctx, ins, attrs):
    """Block-quantized c_allreduce_sum: same contract as c_allreduce_sum
    (identity outside shard_map) but the wire carries int8 blocks + fp32
    scales instead of full-width values. attrs: block_size, bits."""
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    return {"Out": quantized_psum(
        x, ax, block_size=int(attrs.get("block_size",
                                        quant_ops.DEFAULT_BLOCK_SIZE)),
        bits=int(attrs.get("bits", quant_ops.DEFAULT_BITS)))}


# ---------------------------------------------------------------------------
# gradient-sync scope: how the compiler's quantize_collectives option
# reaches the trace engine
# ---------------------------------------------------------------------------

class QuantizedSyncContext(object):
    """Per-compile gradient-sync policy + static byte accounting.

    Installed around the step trace by CompiledProgram when
    ``BuildStrategy.quantize_collectives`` is on; framework/trace.py
    consults :func:`current_grad_sync` and calls :meth:`sync` once per
    parameter gradient as it is produced, so every downstream consumer
    (grad clip, regularizer, gradient-merge ACCUMULATION, optimizer)
    sees the synced value — the same semantics pjit's implicit psum
    gives, with fp32 accumulation staying exact because only the
    cross-host sync is quantized.

    ``raw_bytes``/``wire_bytes`` accumulate at TRACE time (shapes are
    static), i.e. exactly once per compiled step; the dispatch wrapper
    multiplies by the window length and feeds
    ``resilience.record_bytes("collective", ...)`` per dispatch.
    """

    def __init__(self, axis_name, block_size=quant_ops.DEFAULT_BLOCK_SIZE,
                 bits=quant_ops.DEFAULT_BITS, mean=True, min_size=None,
                 merge_window=False):
        self.axis_name = axis_name
        self.block_size = int(block_size)
        self.bits = int(bits)
        self.mean = bool(mean)
        # tensors below one block ride the EXACT full-width sync: a
        # sub-block payload (biases, LayerNorm scales) costs MORE on the
        # wire quantized (block padding + scale) than raw, and its
        # accuracy is the cheapest to keep
        self.min_size = self.block_size if min_size is None \
            else int(min_size)
        # merge_window: params under a detected gradient-merge
        # accumulator defer their sync to the MERGE BOUNDARY (once per
        # k steps, under lax.cond on the program's own apply predicate)
        # instead of syncing the raw gradient every micro step — see
        # sync_merged and framework/trace._maybe_sync_param_grads
        self.merge_window = bool(merge_window)
        self.raw_bytes = 0
        self.wire_bytes = 0
        self.synced = []      # grad var names, in trace order
        self.synced_exact = []
        self.synced_merged = []   # grads synced once-per-k at the boundary

    def sync(self, name, g):
        size = int(np.prod(g.shape)) if g.shape else 1
        itemsize = jnp.dtype(g.dtype).itemsize
        if size < self.min_size:
            self.raw_bytes += size * itemsize
            self.wire_bytes += size * itemsize
            self.synced_exact.append(name)
            red = lax.pmean if self.mean else lax.psum
            return red(g, self.axis_name)
        raw, wire = quant_ops.quantized_wire_bytes(
            size, itemsize, self.block_size, self.bits)
        self.raw_bytes += raw
        self.wire_bytes += wire
        self.synced.append(name)
        return quantized_psum(g, self.axis_name, self.block_size,
                              self.bits, mean=self.mean)

    def sync_merged(self, name, g, pred, every_k=None):
        """Merge-boundary sync: the dp reduction runs under lax.cond on
        the program's own apply predicate, so the k-1 non-apply steps of
        every merge window ship ZERO gradient bytes (the accumulation
        stays local, exact fp32 — the bitwise invariant holds on the
        LOCAL sums). Byte accounting amortizes by every_k when the
        merge factor is statically known (avg=True merges expose it via
        the scale op); an unknown k books the full per-step cost — a
        conservative over-count, never an under-count."""
        size = int(np.prod(g.shape)) if g.shape else 1
        itemsize = jnp.dtype(g.dtype).itemsize
        if size < self.min_size:
            raw = wire = size * itemsize
            self.synced_exact.append(name)
            red = lax.pmean if self.mean else lax.psum

            def sync_fn(v):
                return red(v, self.axis_name)
        else:
            raw, wire = quant_ops.quantized_wire_bytes(
                size, itemsize, self.block_size, self.bits)
            self.synced.append(name)

            def sync_fn(v):
                return quantized_psum(v, self.axis_name, self.block_size,
                                      self.bits, mean=self.mean)
        scale = 1.0 / every_k if every_k else 1.0
        self.raw_bytes += raw * scale
        self.wire_bytes += wire * scale
        self.synced_merged.append(name)
        return lax.cond(jnp.reshape(pred, ()).astype(bool), sync_fn,
                        lambda v: v, g)


_sync_tls = threading.local()


@contextlib.contextmanager
def grad_sync_scope(sync_ctx):
    """Install ``sync_ctx`` for traces started on this thread (jit traces
    run synchronously in the caller, so a thread-local is exact)."""
    prev = getattr(_sync_tls, "ctx", None)
    _sync_tls.ctx = sync_ctx
    try:
        yield sync_ctx
    finally:
        _sync_tls.ctx = prev


def current_grad_sync():
    return getattr(_sync_tls, "ctx", None)
