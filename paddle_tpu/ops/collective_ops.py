"""Collective communication op kernels.

Reference parity: paddle/fluid/operators/collective/{c_allreduce_*,
c_allgather,c_reducescatter,c_broadcast}.cc (NCCL). TPU-native: XLA
collectives (lax.psum/all_gather/psum_scatter/ppermute) over the ICI mesh.

These kernels are meaningful when traced under shard_map with a bound mesh
axis (paddle_tpu.distributed). Single-device traces degrade to identity, so
the same program runs anywhere — mirroring the reference where ring_id 0 on
one rank is a no-op.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _axis(ctx, attrs):
    """Axis name for the collective; None → not inside shard_map → no-op."""
    name = attrs.get("axis_name", "dp")
    bound = getattr(ctx, "bound_axes", ())
    return name if name in bound else None


def _axis_size(axis_name):
    """lax.axis_size compat: jax 0.4.x has no lax.axis_size, but psum of
    a literal 1 constant-folds to the static axis size at trace time."""
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


def _make_allreduce(op_name, reduce_fn):
    @register_op(op_name, differentiable=True)
    def _kernel(ctx, ins, attrs, _fn=reduce_fn):
        x = ins["X"][0]
        ax = _axis(ctx, attrs)
        return {"Out": x if ax is None else _fn(x, ax)}
    return _kernel


_make_allreduce("c_allreduce_sum", lax.psum)
_make_allreduce("c_allreduce_max", lax.pmax)
_make_allreduce("c_allreduce_min", lax.pmin)
_make_allreduce("c_allreduce_prod",
                lambda x, ax: jnp.exp(lax.psum(jnp.log(x), ax)))


@register_op("c_allgather")
def _c_allgather(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    return {"Out": lax.all_gather(x, ax, axis=0, tiled=True)}


@register_op("c_reducescatter")
def _c_reducescatter(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    return {"Out": lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True)}


@register_op("c_broadcast")
def _c_broadcast(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    root = attrs.get("root", 0)
    idx = lax.axis_index(ax)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": lax.psum(masked, ax)}


@register_op("c_sync_comm_stream")
def _c_sync(ctx, ins, attrs):
    # XLA orders collectives itself; kept for program parity.
    return {"Out": list(ins["X"])}


@register_op("barrier", differentiable=False)
def _barrier(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    return {"Out": x + 0 * lax.psum(jnp.zeros((), x.dtype), ax)}


@register_op("ppermute")
def _ppermute(ctx, ins, attrs):
    """Ring shift (building block of ring attention / pipeline parallel)."""
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": x}
    n = _axis_size(ax)
    shift = attrs.get("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": lax.ppermute(x, ax, perm)}
