"""Metric op kernels (accuracy, auc).

Reference parity: paddle/fluid/operators/metrics/{accuracy_op,auc_op}.cc.
"""
import jax.numpy as jnp

from .registry import register_op


@register_op("accuracy", nondiff=("Out", "Indices", "Label"),
             differentiable=False)
def _accuracy(ctx, ins, attrs):
    indices = ins["Indices"][0]          # (N, k) top-k indices
    label = ins["Label"][0].reshape(-1, 1)
    correct = jnp.any(indices == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = indices.shape[0]
    return {"Accuracy": (num_correct / total).reshape((1,)),
            "Correct": num_correct.astype(jnp.int32).reshape((1,)),
            "Total": jnp.asarray([total], dtype=jnp.int32)}


@register_op("auc", nondiff=("Predict", "Label", "StatPos", "StatNeg"),
             differentiable=False)
def _auc(ctx, ins, attrs):
    """Streaming AUC with binned positive/negative histograms, matching the
    reference auc_op's bucket algorithm."""
    predict = ins["Predict"][0]
    label = ins["Label"][0].reshape(-1)
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    num_thresholds = attrs.get("num_thresholds", 4095)
    score = predict[:, -1] if predict.ndim == 2 else predict.reshape(-1)
    idx = jnp.clip((score * num_thresholds).astype(jnp.int32), 0,
                   num_thresholds)
    pos = jnp.zeros_like(stat_pos).at[idx].add(
        (label > 0).astype(stat_pos.dtype))
    neg = jnp.zeros_like(stat_neg).at[idx].add(
        (label <= 0).astype(stat_neg.dtype))
    stat_pos = stat_pos + pos
    stat_neg = stat_neg + neg
    # integrate: sum over bins from high to low threshold
    tp = jnp.cumsum(stat_pos[::-1])[::-1].astype(jnp.float64)
    fp = jnp.cumsum(stat_neg[::-1])[::-1].astype(jnp.float64)
    tot_pos = tp[0]
    tot_neg = fp[0]
    # trapezoid over ROC points (appending origin)
    tp_next = jnp.concatenate([tp[1:], jnp.zeros((1,), tp.dtype)])
    fp_next = jnp.concatenate([fp[1:], jnp.zeros((1,), fp.dtype)])
    area = jnp.sum((fp - fp_next) * (tp + tp_next) / 2.0)
    auc = jnp.where((tot_pos > 0) & (tot_neg > 0),
                    area / jnp.maximum(tot_pos * tot_neg, 1.0), 0.0)
    return {"AUC": auc.astype(jnp.float32).reshape((1,)),
            "StatPosOut": stat_pos, "StatNegOut": stat_neg}
