"""Linear-chain CRF kernels.

Reference parity: paddle/fluid/operators/{linear_chain_crf_op,
crf_decoding_op}.cc. The reference iterates sequences on CPU with LoD;
TPU-native: dense (N, T, C) emissions + (N,) lengths, forward algorithm and
Viterbi as lax.scan over time — differentiable (grad via vjp-of-scan) and
batch-parallel on the VPU.

Transition layout matches the reference: w[0]=start, w[1]=stop,
w[2:2+C, :] = transition[from, to].
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _unpack_transition(w):
    start, stop, trans = w[0], w[1], w[2:]
    return start, stop, trans


@register_op("linear_chain_crf", nondiff=("Label", "Length"))
def _linear_chain_crf(ctx, ins, attrs):
    """ins: Emission (N,T,C), Transition (C+2,C), Label (N,T,1) or (N,T),
    optional Length (N,). outs: LogLikelihood (N,1) (+ alpha)."""
    em = ins["Emission"][0].astype(jnp.float32)
    w = ins["Transition"][0].astype(jnp.float32)
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label.reshape(label.shape[:2])
    label = label.astype(jnp.int32)
    n, t, c = em.shape
    start, stop, trans = _unpack_transition(w)
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((n,), t, jnp.int32)
    steps = jnp.arange(t)
    valid = steps[None, :] < length[:, None]          # (N,T)

    # ---- partition function: alpha recursion over time ----
    def fwd(alpha, xs):
        em_t, valid_t = xs                            # (N,C), (N,)
        # alpha'(j) = logsumexp_i alpha(i) + trans(i,j) + em(j)
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.scipy.special.logsumexp(scores, axis=1) + em_t
        alpha = jnp.where(valid_t[:, None], new, alpha)
        return alpha, alpha

    alpha0 = start[None, :] + em[:, 0, :]
    alphas, _ = fwd(alpha0, (em[:, 0, :], jnp.zeros((n,), bool)))  # no-op
    alpha_last, _ = lax.scan(
        fwd, alpha0,
        (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:]))
    log_z = jax.scipy.special.logsumexp(alpha_last + stop[None, :], axis=1)

    # ---- gold path score ----
    first_em = jnp.take_along_axis(em[:, 0, :], label[:, :1], axis=1)[:, 0]
    path = start[label[:, 0]] + first_em

    def gold(carry, xs):
        path, prev_lbl = carry
        em_t, lbl_t, valid_t = xs
        em_score = jnp.take_along_axis(em_t, lbl_t[:, None], axis=1)[:, 0]
        tr_score = trans[prev_lbl, lbl_t]
        path = jnp.where(valid_t, path + em_score + tr_score, path)
        prev_lbl = jnp.where(valid_t, lbl_t, prev_lbl)
        return (path, prev_lbl), None

    (path, last_lbl), _ = lax.scan(
        gold, (path, label[:, 0]),
        (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(label, 0, 1)[1:],
         jnp.swapaxes(valid, 0, 1)[1:]))
    path = path + stop[last_lbl]

    ll = (path - log_z)[:, None]
    return {"LogLikelihood": ll,
            "Alpha": lax.stop_gradient(alpha_last),
            "EmissionExps": lax.stop_gradient(jnp.exp(em)),
            "TransitionExps": lax.stop_gradient(jnp.exp(w))}


@register_op("warpctc", nondiff=("Label", "LogitsLength", "LabelLength"))
def _warpctc(ctx, ins, attrs):
    """CTC loss (reference: paddle/fluid/operators/warpctc_op.{h,cc} wrapping
    baidu-research/warp-ctc). The reference calls a hand-written CUDA library;
    TPU-native: log-space alpha recursion over the blank-interleaved extended
    label sequence as one lax.scan — batch-parallel on the VPU, exact gradient
    via vjp-of-scan (no custom backward needed).

    ins: Logits (T, N, C) time-major unnormalized (softmax applied inside,
    matching warp-ctc), Label (N, Lmax) int, optional LogitsLength (N,),
    LabelLength (N,). attrs: blank (default 0), norm_by_times.
    outs: Loss (N, 1).
    """
    logits = ins["Logits"][0].astype(jnp.float32)
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label.reshape(label.shape[:2])
    label = label.astype(jnp.int32)
    t, n, c = logits.shape
    lmax = label.shape[1]
    blank = int(attrs.get("blank", 0))
    if ins.get("LogitsLength"):
        in_len = ins["LogitsLength"][0].reshape(-1).astype(jnp.int32)
    else:
        in_len = jnp.full((n,), t, jnp.int32)
    if ins.get("LabelLength"):
        lbl_len = ins["LabelLength"][0].reshape(-1).astype(jnp.int32)
    else:
        lbl_len = jnp.full((n,), lmax, jnp.int32)

    logp = jax.nn.log_softmax(logits, axis=-1)        # (T,N,C)
    neg_inf = jnp.float32(-1e30)

    # extended sequence: blank, l1, blank, l2, ..., lL, blank  → S = 2L+1
    s = 2 * lmax + 1
    pos = jnp.arange(s)
    ext = jnp.where(pos[None, :] % 2 == 1,
                    label[:, jnp.clip(pos // 2, 0, lmax - 1)],
                    blank)                             # (N,S)
    valid_s = pos[None, :] < (2 * lbl_len[:, None] + 1)
    # skip-transition allowed into s when ext[s] != blank and ext[s]!=ext[s-2]
    ext_m2 = jnp.concatenate(
        [jnp.full((n, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    allow_skip = (pos[None, :] >= 2) & (ext != blank) & (ext != ext_m2)

    def emit(logp_t):                                  # (N,C) -> (N,S)
        return jnp.take_along_axis(logp_t, ext, axis=1)

    alpha0 = jnp.where((pos[None, :] < 2) & valid_s, emit(logp[0]), neg_inf)

    def step(alpha, xs):
        logp_t, active = xs                            # (N,C), (N,)
        a1 = jnp.concatenate(
            [jnp.full((n, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate(
            [jnp.full((n, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(allow_skip, a2, neg_inf)
        tot = jax.scipy.special.logsumexp(jnp.stack([alpha, a1, a2]), axis=0)
        new = jnp.where(valid_s, tot + emit(logp_t), neg_inf)
        alpha = jnp.where(active[:, None], new, alpha)
        return alpha, None

    active = (jnp.arange(1, t)[:, None] < in_len[None, :])     # (T-1,N)
    alpha, _ = lax.scan(step, alpha0, (logp[1:], active))

    # p(label) = alpha[2L] + alpha[2L-1] at t = in_len-1
    end = 2 * lbl_len                                   # last blank index
    a_end = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
    a_end1 = jnp.take_along_axis(
        alpha, jnp.maximum(end - 1, 0)[:, None], axis=1)[:, 0]
    a_end1 = jnp.where(lbl_len > 0, a_end1, neg_inf)
    ll = jnp.logaddexp(a_end, a_end1)
    loss = -ll                                          # finite sentinel
    if attrs.get("norm_by_times"):
        # reference normalizes the *gradient* by sequence length, leaving the
        # loss value untouched — same trick, expressed functionally (applied
        # while loss is still finite so inf examples don't turn into NaN)
        scale = 1.0 / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        loss = (lax.stop_gradient(loss * (1.0 - scale)) + loss * scale)
    # infeasible alignment (in_len too short for label + required blanks):
    # report inf like warp-ctc/torch, but keep the gradient finite (zero for
    # those examples) instead of NaN-poisoning the whole batch
    loss = jnp.where(ll > 0.5 * neg_inf, loss, jnp.inf)
    return {"Loss": loss[:, None]}


@register_op("crf_decoding", nondiff=("Emission", "Transition", "Label",
                                      "Length"), differentiable=False)
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode. outs: ViterbiPath (N,T,1) int64."""
    em = ins["Emission"][0].astype(jnp.float32)
    w = ins["Transition"][0].astype(jnp.float32)
    n, t, c = em.shape
    start, stop, trans = _unpack_transition(w)
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((n,), t, jnp.int32)
    valid = jnp.arange(t)[None, :] < length[:, None]

    def vit(carry, xs):
        score = carry                                  # (N,C)
        em_t, valid_t = xs
        cand = score[:, :, None] + trans[None, :, :]   # (N, from, to)
        best_prev = jnp.argmax(cand, axis=1)           # (N,C)
        new = jnp.max(cand, axis=1) + em_t
        new = jnp.where(valid_t[:, None], new, score)
        bp = jnp.where(valid_t[:, None], best_prev,
                       jnp.arange(c)[None, :])
        return new, bp

    score0 = start[None, :] + em[:, 0, :]
    final, bps = lax.scan(
        vit, score0,
        (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:]))
    final = final + stop[None, :]
    last = jnp.argmax(final, axis=1)                   # (N,)

    def back(carry, bp):
        lbl = carry
        prev = jnp.take_along_axis(bp, lbl[:, None], axis=1)[:, 0]
        return prev, lbl

    _, path_rev = lax.scan(back, last, bps, reverse=True)
    # path_rev holds labels for steps 1..T-1 (each yields its own label);
    # prepend the step-0 label via one more backpointer application
    first = jnp.take_along_axis(bps[0], path_rev[0][:, None],
                                axis=1)[:, 0] if t > 1 else last
    if t > 1:
        path = jnp.concatenate([first[None], path_rev], axis=0)
    else:
        path = last[None]
    path = jnp.swapaxes(path, 0, 1)                    # (N,T)
    path = jnp.where(valid, path, 0)
    return {"ViterbiPath": path[..., None].astype(jnp.int64)}
