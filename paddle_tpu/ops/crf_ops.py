"""Linear-chain CRF kernels.

Reference parity: paddle/fluid/operators/{linear_chain_crf_op,
crf_decoding_op}.cc. The reference iterates sequences on CPU with LoD;
TPU-native: dense (N, T, C) emissions + (N,) lengths, forward algorithm and
Viterbi as lax.scan over time — differentiable (grad via vjp-of-scan) and
batch-parallel on the VPU.

Transition layout matches the reference: w[0]=start, w[1]=stop,
w[2:2+C, :] = transition[from, to].
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _unpack_transition(w):
    start, stop, trans = w[0], w[1], w[2:]
    return start, stop, trans


@register_op("linear_chain_crf", nondiff=("Label", "Length"))
def _linear_chain_crf(ctx, ins, attrs):
    """ins: Emission (N,T,C), Transition (C+2,C), Label (N,T,1) or (N,T),
    optional Length (N,). outs: LogLikelihood (N,1) (+ alpha)."""
    em = ins["Emission"][0].astype(jnp.float32)
    w = ins["Transition"][0].astype(jnp.float32)
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label.reshape(label.shape[:2])
    label = label.astype(jnp.int32)
    n, t, c = em.shape
    start, stop, trans = _unpack_transition(w)
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((n,), t, jnp.int32)
    steps = jnp.arange(t)
    valid = steps[None, :] < length[:, None]          # (N,T)

    # ---- partition function: alpha recursion over time ----
    def fwd(alpha, xs):
        em_t, valid_t = xs                            # (N,C), (N,)
        # alpha'(j) = logsumexp_i alpha(i) + trans(i,j) + em(j)
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.scipy.special.logsumexp(scores, axis=1) + em_t
        alpha = jnp.where(valid_t[:, None], new, alpha)
        return alpha, alpha

    alpha0 = start[None, :] + em[:, 0, :]
    alphas, _ = fwd(alpha0, (em[:, 0, :], jnp.zeros((n,), bool)))  # no-op
    alpha_last, _ = lax.scan(
        fwd, alpha0,
        (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:]))
    log_z = jax.scipy.special.logsumexp(alpha_last + stop[None, :], axis=1)

    # ---- gold path score ----
    first_em = jnp.take_along_axis(em[:, 0, :], label[:, :1], axis=1)[:, 0]
    path = start[label[:, 0]] + first_em

    def gold(carry, xs):
        path, prev_lbl = carry
        em_t, lbl_t, valid_t = xs
        em_score = jnp.take_along_axis(em_t, lbl_t[:, None], axis=1)[:, 0]
        tr_score = trans[prev_lbl, lbl_t]
        path = jnp.where(valid_t, path + em_score + tr_score, path)
        prev_lbl = jnp.where(valid_t, lbl_t, prev_lbl)
        return (path, prev_lbl), None

    (path, last_lbl), _ = lax.scan(
        gold, (path, label[:, 0]),
        (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(label, 0, 1)[1:],
         jnp.swapaxes(valid, 0, 1)[1:]))
    path = path + stop[last_lbl]

    ll = (path - log_z)[:, None]
    return {"LogLikelihood": ll,
            "Alpha": lax.stop_gradient(alpha_last),
            "EmissionExps": lax.stop_gradient(jnp.exp(em)),
            "TransitionExps": lax.stop_gradient(jnp.exp(w))}


@register_op("crf_decoding", nondiff=("Emission", "Transition", "Label",
                                      "Length"), differentiable=False)
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode. outs: ViterbiPath (N,T,1) int64."""
    em = ins["Emission"][0].astype(jnp.float32)
    w = ins["Transition"][0].astype(jnp.float32)
    n, t, c = em.shape
    start, stop, trans = _unpack_transition(w)
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((n,), t, jnp.int32)
    valid = jnp.arange(t)[None, :] < length[:, None]

    def vit(carry, xs):
        score = carry                                  # (N,C)
        em_t, valid_t = xs
        cand = score[:, :, None] + trans[None, :, :]   # (N, from, to)
        best_prev = jnp.argmax(cand, axis=1)           # (N,C)
        new = jnp.max(cand, axis=1) + em_t
        new = jnp.where(valid_t[:, None], new, score)
        bp = jnp.where(valid_t[:, None], best_prev,
                       jnp.arange(c)[None, :])
        return new, bp

    score0 = start[None, :] + em[:, 0, :]
    final, bps = lax.scan(
        vit, score0,
        (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:]))
    final = final + stop[None, :]
    last = jnp.argmax(final, axis=1)                   # (N,)

    def back(carry, bp):
        lbl = carry
        prev = jnp.take_along_axis(bp, lbl[:, None], axis=1)[:, 0]
        return prev, lbl

    _, path_rev = lax.scan(back, last, bps, reverse=True)
    # path_rev holds labels for steps 1..T-1 (each yields its own label);
    # prepend the step-0 label via one more backpointer application
    first = jnp.take_along_axis(bps[0], path_rev[0][:, None],
                                axis=1)[:, 0] if t > 1 else last
    if t > 1:
        path = jnp.concatenate([first[None], path_rev], axis=0)
    else:
        path = last[None]
    path = jnp.swapaxes(path, 0, 1)                    # (N,T)
    path = jnp.where(valid, path, 0)
    return {"ViterbiPath": path[..., None].astype(jnp.int64)}
