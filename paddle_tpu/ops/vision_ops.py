"""Spatial / vision op kernels: 3-D conv-pool family, sampling grids,
deformable conv, im2col, ROI variants, video ops.

Reference parity: paddle/fluid/operators/{conv_op (3d), conv_transpose_op,
pool_op (3d), affine_grid_op, grid_sampler_op, pixel_shuffle_op, lrn_op,
unfold_op, temporal_shift_op, row_conv_op, deformable_conv_op,
psroi_pool_op, prroi_pool_op}. The reference dispatches to cuDNN/CUDA
kernels; here everything is lax convolutions, reduce_windows and batched
bilinear gathers that XLA tiles for the MXU, and every op is
differentiable through the generic vjp pairing (framework/trace.py).
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


# ---------------------------------------------------------------------------
# conv3d_transpose / pool3d (conv3d kernel lives in nn_ops.py)
# ---------------------------------------------------------------------------

@register_op("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    """Ref conv_transpose_op.cc (3-D): filter layout (in_c, out_c/g, kd,
    kh, kw); computed as the exact vjp of the forward conv3d (see
    nn_ops._conv_transpose_nd)."""
    from .nn_ops import _conv_transpose_nd
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _triple(attrs.get("strides", [1, 1, 1]))
    pads = _triple(attrs.get("paddings", [0, 0, 0]))
    dil = _triple(attrs.get("dilations", [1, 1, 1]))
    groups = attrs.get("groups", 1) or 1
    out_sp = attrs.get("output_size")
    out = _conv_transpose_nd(x, w, strides, pads, dil, groups,
                             ("NCDHW", "OIDHW", "NCDHW"),
                             out_sp=tuple(out_sp) if out_sp else None)
    return {"Output": out}


@register_op("pool3d")
def _pool3d(ctx, ins, attrs):
    """Ref pool_op.h 3-D path: max/avg over (kd, kh, kw) windows;
    adaptive mode splits each spatial dim into equal cells (requires
    divisibility — the XLA-static analogue of the reference's per-cell
    floor/ceil bounds)."""
    x = ins["X"][0]                       # (N, C, D, H, W)
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": fn(x, axis=(2, 3, 4), keepdims=True)}
    ks = _triple(attrs.get("ksize", [2, 2, 2]))
    if attrs.get("adaptive", False):
        od, oh, ow = ks
        n, c, d, h, w = x.shape
        if d % od or h % oh or w % ow:
            raise NotImplementedError(
                "adaptive pool3d needs input divisible by output size "
                "(got %sx%sx%s -> %sx%sx%s)" % (d, h, w, od, oh, ow))
        x8 = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": fn(x8, axis=(3, 5, 7))}
    strides = _triple(attrs.get("strides", ks))
    pads = _triple(attrs.get("paddings", [0, 0, 0]))
    window = (1, 1) + ks
    strides5 = (1, 1) + strides
    pads2 = [(p, p) for p in pads]
    if attrs.get("ceil_mode", False):
        # ceil-mode output needs extra high-side padding so the last
        # (partial) window exists; the padded region never enters avg
        # counts when exclusive (the ones-count reduce_window pads zeros)
        # and is -inf for max.
        for i in range(3):
            i_sz, k, s, p = x.shape[2 + i], ks[i], strides[i], pads[i]
            out_sz = -(-(i_sz + 2 * p - k) // s) + 1
            if (out_sz - 1) * s >= i_sz + p:
                out_sz -= 1  # last window must start inside input+left-pad
            extra = (out_sz - 1) * s + k - (i_sz + 2 * p)
            pads2[i] = (p, p + max(0, extra))
    padding = ((0, 0), (0, 0)) + tuple(pads2)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides5, padding)
    else:
        s = lax.reduce_window(x, 0.0, lax.add, window, strides5, padding)
        if attrs.get("exclusive", True):
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                    strides5, padding)
            out = s / cnt
        else:
            out = s / (ks[0] * ks[1] * ks[2])
    return {"Out": out}


# ---------------------------------------------------------------------------
# affine_grid / grid_sampler (ref affine_grid_op.h, grid_sampler_op.h —
# both use align_corners semantics and zero padding outside the map)
# ---------------------------------------------------------------------------

@register_op("affine_grid", nondiff=("OutputShape",))
def _affine_grid(ctx, ins, attrs):
    theta = ins["Theta"][0]               # (N, 2, 3)
    shape = attrs["output_shape"]         # [N, C, H, W]
    h, w = int(shape[2]), int(shape[3])
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)         # (H, W)
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)   # (H, W, 3)
    out = jnp.einsum("hwk,njk->nhwj", base.astype(theta.dtype), theta)
    return {"Output": out}                # (N, H, W, 2)


def _grid_sample_2d(x, gx, gy):
    """Bilinear sample x (N,C,H,W) at pixel coords gx/gy (N,H',W');
    out-of-range points contribute zero (ref GetGridPointValue)."""
    n, c, h, w = x.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    fx = gx - x0
    fy = gy - y0
    nidx = jnp.arange(n)[:, None, None]

    def tap(yi, xi, wgt):
        valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        v = x[nidx, :, jnp.clip(yi, 0, h - 1).astype(jnp.int32),
              jnp.clip(xi, 0, w - 1).astype(jnp.int32)]   # (N,H',W',C)
        return v * (wgt * valid)[..., None]

    out = (tap(y0, x0, (1 - fy) * (1 - fx)) +
           tap(y0, x0 + 1, (1 - fy) * fx) +
           tap(y0 + 1, x0, fy * (1 - fx)) +
           tap(y0 + 1, x0 + 1, fy * fx))
    return out.transpose(0, 3, 1, 2)      # (N, C, H', W')


@register_op("grid_sampler")
def _grid_sampler(ctx, ins, attrs):
    x, grid = ins["X"][0], ins["Grid"][0]   # grid (N, H', W', 2) in [-1,1]
    h, w = x.shape[2], x.shape[3]
    gx = (grid[..., 0] + 1.0) * 0.5 * (w - 1)
    gy = (grid[..., 1] + 1.0) * 0.5 * (h - 1)
    return {"Output": _grid_sample_2d(x, gx, gy)}


# ---------------------------------------------------------------------------
# pixel_shuffle / lrn / unfold / temporal_shift / row_conv
# ---------------------------------------------------------------------------

@register_op("pixel_shuffle")
def _pixel_shuffle(ctx, ins, attrs):
    x = ins["X"][0]                       # (N, C*r*r, H, W)
    r = int(attrs["upscale_factor"])
    n, c, h, w = x.shape
    oc = c // (r * r)
    y = x.reshape(n, oc, r, r, h, w)
    y = y.transpose(0, 1, 4, 2, 5, 3)     # (N, OC, H, r, W, r)
    return {"Out": y.reshape(n, oc, h * r, w * r)}


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    """Ref lrn_op.cc: mid = k + alpha * sum_{window n over C} x^2;
    out = x * mid^-beta."""
    x = ins["X"][0]                       # (N, C, H, W)
    n_sz = int(attrs.get("n", 5))
    k = float(attrs.get("k", 1.0))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    half = (n_sz - 1) // 2
    sq = jnp.square(x)
    acc = lax.reduce_window(
        sq, 0.0, lax.add, (1, n_sz, 1, 1), (1, 1, 1, 1),
        ((0, 0), (half, n_sz - 1 - half), (0, 0), (0, 0)))
    mid = k + alpha * acc
    return {"Out": x * jnp.power(mid, -beta), "MidOut": mid}


@register_op("unfold")
def _unfold(ctx, ins, attrs):
    """im2col (ref unfold_op.h): (N,C,H,W) -> (N, C*kh*kw, L), patch
    channel order (c, kh, kw) with c slowest — matches the reference's
    Im2ColFunctor layout."""
    x = ins["X"][0]
    kh, kw = [int(v) for v in attrs["kernel_sizes"]]
    sh, sw = [int(v) for v in attrs.get("strides", [1, 1])]
    pads = [int(v) for v in attrs.get("paddings", [0, 0])]
    if len(pads) == 4:        # [top, left, bottom, right]
        pad_cfg = [(pads[0], pads[2]), (pads[1], pads[3])]
    else:
        pad_cfg = [(pads[0], pads[0]), (pads[1], pads[1])]
    dh, dw = [int(v) for v in attrs.get("dilations", [1, 1])]
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), pad_cfg,
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n = x.shape[0]
    return {"Y": patches.reshape(n, patches.shape[1], -1)}


@register_op("temporal_shift")
def _temporal_shift(ctx, ins, attrs):
    """Ref temporal_shift_op.h: x (N*T, C, H, W); first fold of channels
    reads from t+1, second fold from t-1, rest unchanged; zero padded."""
    x = ins["X"][0]
    t = int(attrs["seg_num"])
    ratio = float(attrs.get("shift_ratio", 0.25))
    nt, c, h, w = x.shape
    n = nt // t
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    xr = x.reshape(n, t, c, h, w)
    zeros = jnp.zeros_like(xr[:, :1])
    fwd = jnp.concatenate([xr[:, 1:], zeros], axis=1)    # reads t+1
    bwd = jnp.concatenate([zeros, xr[:, :-1]], axis=1)   # reads t-1
    out = jnp.concatenate([fwd[:, :, :c1], bwd[:, :, c1:c2], xr[:, :, c2:]],
                          axis=2)
    return {"Out": out.reshape(nt, c, h, w)}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    """Ref row_conv_op.cc (lookahead convolution, dense batch form):
    out[b,t,d] = sum_{i=0..k} x[b,t+i,d] * w[i,d]."""
    x, w = ins["X"][0], ins["Filter"][0]   # (B,T,D), (k+1,D)
    ctx_len = w.shape[0]
    b, t, d = x.shape
    pad = jnp.concatenate(
        [x, jnp.zeros((b, ctx_len - 1, d), x.dtype)], axis=1)
    out = jnp.zeros_like(x)
    for i in range(ctx_len):               # static, small
        out = out + pad[:, i:i + t, :] * w[i][None, None, :]
    return {"Out": out}


# ---------------------------------------------------------------------------
# deformable conv (ref deformable_conv_op.cu / _v1): bilinear-sampled
# im2col at learned offsets, then one big MXU matmul
# ---------------------------------------------------------------------------

@register_op("deformable_conv", nondiff=())
def _deformable_conv(ctx, ins, attrs):
    x = ins["Input"][0]                   # (N, C, H, W)
    offset = ins["Offset"][0]             # (N, 2*dg*kh*kw, OH, OW), (y,x)
    w = ins["Filter"][0]                  # (O, C/g, kh, kw)
    mask = ins["Mask"][0] if ins.get("Mask") else None  # (N, dg*kh*kw,...)
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0])
    dil = attrs.get("dilations", [1, 1])
    groups = attrs.get("groups", 1) or 1
    dg = attrs.get("deformable_groups", 1) or 1
    n, c, h, ww_ = x.shape
    o, _, kh, kw = w.shape
    oh = (h + 2 * pads[0] - (dil[0] * (kh - 1) + 1)) // strides[0] + 1
    ow = (ww_ + 2 * pads[1] - (dil[1] * (kw - 1) + 1)) // strides[1] + 1
    k = kh * kw

    # base sampling positions per (kernel tap, output pixel)
    oy = jnp.arange(oh) * strides[0] - pads[0]
    ox = jnp.arange(ow) * strides[1] - pads[1]
    ky = jnp.arange(kh) * dil[0]
    kx = jnp.arange(kw) * dil[1]
    base_y = oy[None, None, :, None] + ky[:, None, None, None]  # kh,1,OH,1
    base_x = ox[None, None, None, :] + kx[None, :, None, None]  # 1,kw,1,OW
    base_y = jnp.broadcast_to(base_y, (kh, kw, oh, ow)).reshape(k, oh, ow)
    base_x = jnp.broadcast_to(base_x, (kh, kw, oh, ow)).reshape(k, oh, ow)

    off = offset.reshape(n, dg, k, 2, oh, ow)
    gy = base_y[None, None] + off[:, :, :, 0]     # (N, dg, K, OH, OW)
    gx = base_x[None, None] + off[:, :, :, 1]
    if mask is not None:
        m = mask.reshape(n, dg, k, oh, ow)
    else:
        m = jnp.ones((n, dg, k, oh, ow), x.dtype)

    # bilinear sample each deformable group's channels at its offsets
    xg = x.reshape(n, dg, c // dg, h, ww_)
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    fx = gx - x0
    fy = gy - y0
    nidx = jnp.arange(n)[:, None, None, None, None]
    didx = jnp.arange(dg)[None, :, None, None, None]

    def tap(yi, xi, wgt):
        valid = (xi >= 0) & (xi < ww_) & (yi >= 0) & (yi < h)
        v = xg[nidx, didx, :, jnp.clip(yi, 0, h - 1).astype(jnp.int32),
               jnp.clip(xi, 0, ww_ - 1).astype(jnp.int32)]
        return v * (wgt * valid)[..., None]      # (N,dg,K,OH,OW,C/dg)

    cols = (tap(y0, x0, (1 - fy) * (1 - fx)) +
            tap(y0, x0 + 1, (1 - fy) * fx) +
            tap(y0 + 1, x0, fy * (1 - fx)) +
            tap(y0 + 1, x0 + 1, fy * fx))
    cols = cols * m[..., None]
    # (N, dg, K, OH, OW, C/dg) -> (N, C, K, OH, OW)
    cols = cols.transpose(0, 1, 5, 2, 3, 4).reshape(n, c, k, oh, ow)
    cg = c // groups
    cols = cols.reshape(n, groups, cg, k, oh, ow)
    wg = w.reshape(groups, o // groups, cg, k)
    out = jnp.einsum("ngckhw,gock->ngohw",
                     cols, wg).reshape(n, o, oh, ow)
    return {"Output": out}


# ---------------------------------------------------------------------------
# position-sensitive / precise ROI pooling
# ---------------------------------------------------------------------------

def _roi_sample_bins(x_per_roi, rois, ph, pw, sr, h, w, spatial_scale,
                     ch_index=None):
    """Average of an sr x sr bilinear sample grid per output bin.
    x_per_roi: (R, C, H, W) feature slices already gathered per roi."""
    r = rois.shape[0]
    x1 = rois[:, 0] * spatial_scale
    y1 = rois[:, 1] * spatial_scale
    rw = jnp.maximum(rois[:, 2] * spatial_scale - x1, 0.1)
    rh = jnp.maximum(rois[:, 3] * spatial_scale - y1, 0.1)
    iy = (jnp.arange(sr) + 0.5) / sr
    gy = y1[:, None, None] + (jnp.arange(ph)[None, :, None] +
                              iy[None, None, :]) * (rh / ph)[:, None, None]
    gx = x1[:, None, None] + (jnp.arange(pw)[None, :, None] +
                              iy[None, None, :]) * (rw / pw)[:, None, None]
    gy = jnp.clip(gy.reshape(r, ph * sr), 0.0, h - 1.0)
    gx = jnp.clip(gx.reshape(r, pw * sr), 0.0, w - 1.0)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x0 = jnp.floor(gx).astype(jnp.int32)
    y1i = jnp.minimum(y0 + 1, h - 1)
    x1i = jnp.minimum(x0 + 1, w - 1)
    fy = gy - y0
    fx = gx - x0
    ridx = jnp.arange(r)[:, None, None]
    ya, yb = y0[:, :, None], y1i[:, :, None]
    xa, xb = x0[:, None, :], x1i[:, None, :]
    v00 = x_per_roi[ridx, :, ya, xa]      # (R, PH*S, PW*S, C)
    v01 = x_per_roi[ridx, :, ya, xb]
    v10 = x_per_roi[ridx, :, yb, xa]
    v11 = x_per_roi[ridx, :, yb, xb]
    fyb = fy[:, :, None, None]
    fxb = fx[:, None, :, None]
    vals = (v00 * (1 - fyb) * (1 - fxb) + v01 * (1 - fyb) * fxb +
            v10 * fyb * (1 - fxb) + v11 * fyb * fxb)
    c = x_per_roi.shape[1]
    vals = vals.reshape(r, ph, sr, pw, sr, c).mean(axis=(2, 4))
    return vals.transpose(0, 3, 1, 2)     # (R, C, PH, PW)


@register_op("psroi_pool", nondiff=("ROIs", "RoisNum"))
def _psroi_pool(ctx, ins, attrs):
    """Position-sensitive ROI pooling (ref psroi_pool_op.h): bin (i,j) of
    output channel c averages input channel c*ph*pw + i*pw + j over the
    bin. The reference averages integer pixels; here each bin averages a
    fixed bilinear sample grid — the static-shape TPU equivalent (same
    estimator roi_align uses)."""
    from .detection_ops import _roi_batch_index
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    n, c, h, w = x.shape
    r = rois.shape[0]
    oc = int(attrs["output_channels"])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    if ins.get("RoisNum"):
        bidx = _roi_batch_index(ins["RoisNum"][0], r, n)
    else:
        bidx = jnp.zeros((r,), jnp.int32)
    # (R, oc, ph, pw, H, W): channel (o, i, j) = o*ph*pw + i*pw + j
    xb = x[bidx].reshape(r, oc, ph, pw, h, w)
    sampled = _roi_sample_bins(
        xb.reshape(r, oc * ph * pw, h, w), rois, ph, pw, 2, h, w, scale)
    sampled = sampled.reshape(r, oc, ph, pw, ph, pw)
    ii = jnp.arange(ph)
    jj = jnp.arange(pw)
    out = sampled[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]
    return {"Out": out}


@register_op("prroi_pool", nondiff=("ROIs", "BatchRoINums"))
def _prroi_pool(ctx, ins, attrs):
    """Precise ROI pooling (ref prroi_pool_op.h): exact integral of the
    bilinearly-interpolated map over each bin, approximated with a dense
    4x4 sample grid per bin (converges to the integral; fully
    differentiable w.r.t. both features and coords)."""
    from .detection_ops import _roi_batch_index
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    n, c, h, w = x.shape
    r = rois.shape[0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    if ins.get("BatchRoINums"):
        bidx = _roi_batch_index(ins["BatchRoINums"][0], r, n)
    else:
        bidx = jnp.zeros((r,), jnp.int32)
    out = _roi_sample_bins(x[bidx], rois, ph, pw, 4, h, w, scale)
    return {"Out": out}
