"""DataFeeder — convert python/numpy samples into feed dicts.

Reference parity: python/paddle/fluid/data_feeder.py.
"""
import numpy as np

from .framework.dtypes import to_jax_dtype


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    def feed(self, iterable):
        """iterable: list of samples, each a tuple aligned with feed_list."""
        columns = list(zip(*iterable))
        out = {}
        for var, col in zip(self.feed_vars, columns):
            arr = np.stack([np.asarray(x) for x in col])
            dtype = np.dtype(to_jax_dtype(var.dtype))
            out[var.name] = arr.astype(dtype, copy=False)
        return out
