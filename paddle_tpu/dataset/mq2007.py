"""MQ2007 learning-to-rank dataset (ref python/paddle/dataset/mq2007.py).

Contract: ``__reader__(filepath, format, shuffle, fill_missing)`` with
format in {"pointwise", "pairwise", "listwise"}:
  pointwise -> (float32[46] features, score)
  pairwise  -> (high_features, low_features) preference pairs
  listwise  -> (query_list_of_score, query_list_of_features)
plus the Query/QueryList record classes.  Synthetic payload: per-query
documents whose relevance is a noisy linear function of the 46 LETOR
features, so ranking losses order documents meaningfully.
"""
import functools

import numpy as np

from . import synthetic

FEATURE_DIM = 46
N_QUERIES = {"train": 120, "test": 40}
DOCS_PER_QUERY = (5, 15)


class Query(object):
    """One (query, document) pair: relevance score + 46-dim LETOR
    feature vector (ref mq2007.py:50)."""

    def __init__(self, query_id=-1, relevance_score=-1,
                 feature_vector=None, description=""):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector or []
        self.description = description

    def __str__(self):
        return "%s %s %s" % (str(self.relevance_score), str(self.query_id),
                             " ".join(str(f) for f in self.feature_vector))


class QueryList(object):
    """All documents of one query id (ref mq2007.py:104)."""

    def __init__(self, querylist=None):
        self.query_id = -1
        self.querylist = querylist or []
        if self.querylist:
            self.query_id = self.querylist[0].query_id

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda x: -x.relevance_score)

    def _add_query(self, query):
        if self.query_id == -1:
            self.query_id = query.query_id
        self.querylist.append(query)


def _make_querylists(split):
    rng_w = synthetic.rng_for("mq2007", "w")
    w = rng_w.normal(0, 1, FEATURE_DIM)
    lists = []
    for q in range(N_QUERIES[split]):
        rng = synthetic.rng_for("mq2007", split, q)
        ql = QueryList()
        for d in range(int(rng.randint(*DOCS_PER_QUERY))):
            fv = rng.normal(0, 1, FEATURE_DIM)
            score = int(np.clip(np.round(
                fv.dot(w) / np.sqrt(FEATURE_DIM) * 1.2 +
                rng.normal(0, 0.3) + 1.0), 0, 2))
            ql._add_query(Query(query_id=q, relevance_score=score,
                                feature_vector=list(fv.astype(np.float32))))
        ql._correct_ranking_()
        lists.append(ql)
    return lists


def gen_plain_txt(querylist):
    """(query_id, score, features) rows (ref mq2007.py:148)."""
    for query in querylist:
        yield querylist.query_id, query.relevance_score, \
            np.array(query.feature_vector)


def gen_point(querylist):
    """Pointwise: (features, score) (ref mq2007.py:169)."""
    for query in querylist:
        yield np.array(query.feature_vector), query.relevance_score


def gen_pair(querylist, partial_order="full"):
    """Pairwise preference samples (ref mq2007.py:188): yields
    (high_feature, low_feature) for doc pairs with differing scores."""
    docs = sorted(querylist, key=lambda x: -x.relevance_score)
    for i, hi in enumerate(docs):
        for lo in docs[i + 1:]:
            if hi.relevance_score > lo.relevance_score:
                yield (np.array(hi.feature_vector),
                       np.array(lo.feature_vector))
                if partial_order != "full":
                    break


def gen_list(querylist):
    """Listwise: (scores, features) per query (ref mq2007.py:231)."""
    relevance_score_list = [[q.relevance_score] for q in querylist]
    feature_vector_list = [q.feature_vector for q in querylist]
    yield np.array(relevance_score_list), np.array(feature_vector_list)


def query_filter(querylists):
    """Drop queries whose docs all share one relevance level
    (ref mq2007.py:251)."""
    filtered = []
    for ql in querylists:
        if len({q.relevance_score for q in ql}) > 1:
            filtered.append(ql)
    return filtered


def load_from_text(filepath, shuffle=False, fill_missing=-1):
    """Synthetic equivalent of parsing the LETOR text file: the
    train/test substring of ``filepath`` picks the split."""
    split = "test" if "test" in str(filepath) else "train"
    return _make_querylists(split)


def __reader__(filepath, format="pairwise", shuffle=False, fill_missing=-1):
    querylists = query_filter(
        load_from_text(filepath, shuffle=shuffle,
                       fill_missing=fill_missing))
    gen = {"plain_txt": gen_plain_txt, "pointwise": gen_point,
           "pairwise": gen_pair, "listwise": gen_list}[format]
    for ql in querylists:
        for sample in gen(ql):
            yield sample


train = functools.partial(__reader__, filepath="MQ2007/Fold1/train.txt")
test = functools.partial(__reader__, filepath="MQ2007/Fold1/test.txt")


def fetch():
    next(train())
