"""CIFAR-10/100 dataset (ref python/paddle/dataset/cifar.py).

Reference contract: creators yield ``(image, label)`` with image a
float32[3072] (CHW flattened, values in [0, 1]) and label int.  CIFAR-10
has 10 coarse classes, CIFAR-100 has 100.  Synthetic payload: per-class
color/texture prototypes plus noise (see common.py for the offline
rationale).
"""
import numpy as np

from . import synthetic

__all__ = ['train100', 'test100', 'train10', 'test10']

TRAIN_SIZE = 50000
TEST_SIZE = 10000


def _proto(tag, n_class, label):
    rng = synthetic.rng_for("cifar", tag, "proto", label)
    base = rng.uniform(0.2, 0.8, size=(3, 1, 1)).astype(np.float32)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    tex = np.sin(2 * np.pi * (rng.uniform(1, 4) * yy +
                              rng.uniform(1, 4) * xx))[None] * 0.15
    return np.clip(base + tex, 0, 1)


def reader_creator(tag, n_class, split, size, cycle=False):
    protos = {}

    def reader():
        while True:
            for i in range(size):
                rng = synthetic.rng_for("cifar", tag, split, i)
                label = int(rng.randint(n_class))
                if label not in protos:
                    protos[label] = _proto(tag, n_class, label)
                img = protos[label] + rng.normal(0, 0.12, (3, 32, 32))
                img = np.clip(img, 0, 1).astype(np.float32)
                yield img.reshape(3072), label
            if not cycle:
                break

    return reader


def train100():
    """CIFAR-100 train creator (ref cifar.py:78)."""
    return reader_creator("cifar100", 100, "train", TRAIN_SIZE)


def test100():
    """CIFAR-100 test creator (ref cifar.py:93)."""
    return reader_creator("cifar100", 100, "test", TEST_SIZE)


def train10(cycle=False):
    """CIFAR-10 train creator (ref cifar.py:108)."""
    return reader_creator("cifar10", 10, "train", TRAIN_SIZE, cycle=cycle)


def test10(cycle=False):
    """CIFAR-10 test creator (ref cifar.py:126)."""
    return reader_creator("cifar10", 10, "test", TEST_SIZE, cycle=cycle)


def fetch():
    next(train10()())
