"""Dataset package.

Two reference namespaces merge here:
  * corpus modules (ref python/paddle/dataset/__init__.py) — mnist,
    cifar, imdb, … with deterministic synthetic payloads matching the
    reference record schemas (air-gapped TPU pods; see common.py);
  * the fluid Dataset API (ref python/paddle/fluid/dataset.py) —
    DatasetFactory / InMemoryDataset / QueueDataset re-exported from
    dataset_api.py, so ``paddle_tpu.dataset.DatasetFactory()`` keeps
    working as before.
"""
from .dataset_api import (DatasetFactory, DatasetBase, QueueDataset,
                          InMemoryDataset)
from . import common
from . import mnist
from . import cifar
from . import uci_housing
from . import imdb
from . import imikolov
from . import movielens
from . import sentiment
from . import conll05
from . import wmt14
from . import wmt16
from . import mq2007
from . import flowers
from . import voc2012
from . import image

__all__ = [
    'mnist', 'imikolov', 'imdb', 'cifar', 'movielens', 'conll05',
    'sentiment', 'uci_housing', 'wmt14', 'wmt16', 'mq2007', 'flowers',
    'voc2012', 'image', 'common',
    'DatasetFactory', 'DatasetBase', 'QueueDataset', 'InMemoryDataset',
]
