"""MNIST dataset (ref python/paddle/dataset/mnist.py).

Same reader contract as the reference: ``train()``/``test()`` yield
``(image, label)`` with image a float32[784] in [-1, 1] and label an
int in [0, 10).  Payload is synthetic (see common.py): each class has a
fixed blurred prototype digit-blob; samples are the prototype plus
per-sample noise, so linear/MLP classifiers separate the classes and
book-style convergence tests behave like on the real corpus.
"""
import numpy as np

from . import synthetic

__all__ = ['train', 'test']

TRAIN_SIZE = 60000
TEST_SIZE = 10000


def _prototypes():
    rng = synthetic.rng_for("mnist", "protos")
    protos = []
    for c in range(10):
        img = np.zeros((28, 28), np.float32)
        # a handful of class-specific gaussian strokes
        for _ in range(6):
            cy, cx = rng.randint(4, 24, size=2)
            yy, xx = np.mgrid[0:28, 0:28]
            img += np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) /
                          (2.0 * rng.uniform(2.0, 9.0)))
        protos.append(img / img.max())
    return np.stack(protos)


_PROTOS = None


def reader_creator(split, size):
    def reader():
        global _PROTOS
        if _PROTOS is None:
            _PROTOS = _prototypes()
        for i in range(size):
            rng = synthetic.rng_for("mnist", split, i)
            label = int(rng.randint(10))
            img = _PROTOS[label] + rng.normal(0, 0.25, (28, 28))
            img = np.clip(img, 0.0, 1.0).astype(np.float32)
            yield img.reshape(784) * 2.0 - 1.0, label

    return reader


def train():
    """MNIST training-set creator: 60k (float32[784] in [-1,1], int label)
    samples (ref mnist.py:91)."""
    return reader_creator("train", TRAIN_SIZE)


def test():
    """MNIST test-set creator: 10k samples (ref mnist.py:108)."""
    return reader_creator("test", TEST_SIZE)


def fetch():
    next(train()())
