"""imikolov (PTB-style) language-model dataset
(ref python/paddle/dataset/imikolov.py).

Contract: ``build_dict(min_word_freq)`` -> word->id with '<unk>' and
'<e>' entries; ``train(word_idx, n, data_type)`` yields n-gram tuples
(DataType.NGRAM) or whole sentences bracketed by <s>/<e> ids
(DataType.SEQ).  Synthetic sentences follow a Zipf marginal so
frequency cutoffs work.
"""
import numpy as np

from . import synthetic

__all__ = ['train', 'test', 'build_dict']

VOCAB = 2000
TRAIN_SIZE = 3000
TEST_SIZE = 500


class DataType(object):
    NGRAM = 1
    SEQ = 2


def _sentence(split, i):
    rng = synthetic.rng_for("imikolov", split, i)
    n = int(rng.randint(5, 30))
    return ["w%04d" % w for w in synthetic.zipf_sentence(rng, VOCAB, n)]


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = {}
    for sent in f:
        for w in sent:
            word_freq[w] = word_freq.get(w, 0) + 1
        word_freq['<s>'] = word_freq.get('<s>', 0) + 1
        word_freq['<e>'] = word_freq.get('<e>', 0) + 1
    return word_freq


def build_dict(min_word_freq=50):
    """Frequency-filtered dict over train+test, '<unk>' appended
    (ref imikolov.py:54)."""
    word_freq = word_count(
        (_sentence("train", i) for i in range(TRAIN_SIZE)),
        word_count((_sentence("test", i) for i in range(TEST_SIZE))))
    if '<unk>' in word_freq:
        del word_freq['<unk>']
    word_freq = [x for x in word_freq.items() if x[1] > min_word_freq]
    word_freq_sorted = sorted(word_freq, key=lambda el: (-el[1], el[0]))
    words, _ = list(zip(*word_freq_sorted))
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx['<unk>'] = len(words)
    return word_idx


def reader_creator(split, size, word_idx, n, data_type):
    def reader():
        UNK = word_idx['<unk>']
        for i in range(size):
            if DataType.NGRAM == data_type:
                l = ['<s>'] + _sentence(split, i) + ['<e>']
                if len(l) >= n:
                    l = [word_idx.get(w, UNK) for w in l]
                    for j in range(n, len(l) + 1):
                        yield tuple(l[j - n:j])
            elif DataType.SEQ == data_type:
                l = _sentence(split, i)
                l = [word_idx.get(w, UNK) for w in l]
                src_seq = [word_idx['<s>']] + l
                trg_seq = l + [word_idx['<e>']]
                if n > 0 and len(l) > n:
                    continue
                yield src_seq, trg_seq
            else:
                assert False, 'Unknown data type'

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    """Train creator (ref imikolov.py:114)."""
    return reader_creator("train", TRAIN_SIZE, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    """Test creator (ref imikolov.py:134)."""
    return reader_creator("test", TEST_SIZE, word_idx, n, data_type)


def fetch():
    next(train(build_dict(), 5)())
