"""Dataset cache / download plumbing (ref python/paddle/dataset/common.py).

The reference downloads public corpora into ``~/.cache/paddle/dataset``.
This build targets air-gapped TPU pods (zero egress), so :func:`download`
only ever *resolves* files: an already-cached file (placed there by the
user or a mirror job) is returned, a missing one raises a clear error
instead of attempting a network fetch.  The per-corpus modules in this
package therefore ship deterministic synthetic generators with the same
record schemas, so model scripts written against ``paddle.dataset.*``
run unmodified; point ``PADDLE_TPU_DATASET_ROOT`` at a real mirror to
swap in genuine payloads where a module supports it.
"""
import errno
import glob
import hashlib
import os
import pickle

__all__ = [
    'DATA_HOME', 'download', 'md5file', 'split', 'cluster_files_reader',
]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATASET_ROOT",
    os.path.expanduser(os.path.join('~', '.cache', 'paddle_tpu', 'dataset')))


def must_mkdirs(path):
    try:
        os.makedirs(path)
    except OSError as exc:
        if exc.errno != errno.EEXIST:
            raise


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve a dataset file in the local cache; never hits the network.

    Returns the cached path if present (md5 verified when ``md5sum`` is
    given); raises ``RuntimeError`` otherwise — this environment has no
    egress, so fetching is the operator's job, not the framework's.
    """
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, url.split('/')[-1] if save_name is None else save_name)
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise RuntimeError(
                "cached file %s exists but its md5 does not match %s" %
                (filename, md5sum))
        return filename
    raise RuntimeError(
        "dataset file %s is not in the local cache (%s) and this "
        "environment has no network egress; mirror it there manually or "
        "use the synthetic readers in paddle_tpu.dataset.*" %
        (url.split('/')[-1], dirname))


def fetch_all():
    """Materialize every synthetic corpus cache (parity with the
    reference's paddle.dataset.common.fetch_all crawler)."""
    import importlib
    for name in ('mnist', 'cifar', 'uci_housing', 'imdb', 'imikolov',
                 'movielens', 'conll05', 'sentiment', 'wmt14', 'wmt16',
                 'voc2012', 'flowers', 'mq2007'):
        mod = importlib.import_module('paddle_tpu.dataset.' + name)
        if hasattr(mod, 'fetch'):
            mod.fetch()


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Shard a reader's samples into files of ``line_count`` records each
    (ref common.py:128)."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
                lines = []
                indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Round-robin shard reader over files matching ``files_pattern``
    (ref common.py:166): trainer ``i`` of ``n`` reads every n-th file."""

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_file_list = [
            fn for idx, fn in enumerate(file_list)
            if idx % trainer_count == trainer_id
        ]
        for fn in my_file_list:
            with open(fn, "rb") as f:
                for line in loader(f):
                    yield line

    return reader
