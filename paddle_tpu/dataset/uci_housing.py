"""UCI Housing regression dataset (ref python/paddle/dataset/uci_housing.py).

Contract: ``train()``/``test()`` yield ``(features, price)`` with
features float32[13] (normalized) and price float32[1].  The synthetic
payload is drawn from a fixed linear ground-truth with noise, so linear
regression converges exactly as the book chapter expects.
``fluid_model()`` (ref :125, which downloads a pre-trained fluid model)
here *trains* a tiny regressor with this framework and saves it via
``save_inference_model`` — same artifact contract, produced locally.
"""
import os

import numpy as np

from . import synthetic
from .common import DATA_HOME, must_mkdirs

__all__ = ['train', 'test']

feature_names = [
    'CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS', 'RAD', 'TAX',
    'PTRATIO', 'B', 'LSTAT'
]

FEATURE_NUM = 13
TRAIN_SIZE = 404
TEST_SIZE = 102

_W = None
_B = 22.5


def _truth():
    global _W
    if _W is None:
        _W = synthetic.rng_for("uci", "w").uniform(
            -3, 3, FEATURE_NUM).astype(np.float32)
    return _W


def _sample(split, i):
    rng = synthetic.rng_for("uci", split, i)
    x = rng.normal(0, 1, FEATURE_NUM).astype(np.float32)
    y = np.array([x.dot(_truth()) + _B + rng.normal(0, 1.0)], np.float32)
    return x, y


def feature_range(maximums, minimums):  # parity no-op (ref :47 plots)
    pass


def train():
    """404 normalized (x[13], y[1]) samples (ref uci_housing.py:85)."""

    def reader():
        for i in range(TRAIN_SIZE):
            yield _sample("train", i)

    return reader


def test():
    """102 held-out samples (ref uci_housing.py:105)."""

    def reader():
        for i in range(TEST_SIZE):
            yield _sample("test", i)

    return reader


def fluid_model():
    """Path to a saved inference model for this dataset (ref :125).  The
    reference downloads one; we fit a linear regressor on the synthetic
    corpus with paddle_tpu itself and cache the saved model."""
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu import io

    dirname = os.path.join(DATA_HOME, "fit_a_line.inference.model")
    if os.path.exists(os.path.join(dirname, "__model__.json")):
        return dirname
    must_mkdirs(dirname)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[FEATURE_NUM], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1, name="fc_pred")
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        optimizer.SGD(0.01).minimize(loss)
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        xs, ys = zip(*list(train()()))
        feed = {"x": np.stack(xs), "y": np.stack(ys)}
        for _ in range(200):
            exe.run(main, feed=feed, fetch_list=[loss])
        io.save_inference_model(dirname, ["x"], [pred], exe,
                                main_program=main)
    return dirname


def predict_reader():
    """First 10 test samples, features only (ref uci_housing.py:136)."""

    def reader():
        for i in range(10):
            yield (_sample("test", i)[0],)

    return reader


def fetch():
    next(train()())
