"""WMT14 EN->FR translation dataset (ref python/paddle/dataset/wmt14.py).

Contract: ``train(dict_size)``/``test(dict_size)`` yield
``(src_ids, trg_ids, trg_ids_next)`` where src is <s>-/<e>-bracketed,
trg is <s>-prefixed, trg_next is <e>-suffixed — exactly the teacher-
forcing triplet the reference emits (ref wmt14.py:81-113).  Special ids:
<s>=0, <e>=1, <unk>=2.  Synthetic sentence pairs share a latent "meaning"
sequence so attention models can actually learn the mapping.
"""
import numpy as np

from . import synthetic

__all__ = ['train', 'test', 'get_dict', 'convert']

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2

TRAIN_SIZE = 2000
TEST_SIZE = 400
GEN_SIZE = 100


def _dicts(dict_size):
    words = [START, END, UNK] + \
        ["src%05d" % i for i in range(dict_size - 3)]
    src = dict(zip(words, range(len(words))))
    trgw = [START, END, UNK] + \
        ["trg%05d" % i for i in range(dict_size - 3)]
    trg = dict(zip(trgw, range(len(trgw))))
    return src, trg


def _pair(split, i, dict_size):
    rng = synthetic.rng_for("wmt14", split, i)
    n = int(rng.randint(4, 30))
    latent = [3 + int(w) % (dict_size - 3)
              for w in synthetic.zipf_sentence(rng, dict_size - 3, n)]
    # target is a noisy affine re-indexing of the source "meaning"
    trg = [3 + (w - 3 + 7) % (dict_size - 3) for w in latent]
    if n > 6:
        trg = trg[:-1]
    return latent, trg


def reader_creator(split, size, dict_size):
    def reader():
        for i in range(size):
            src_ids, trg_ids = _pair(split, i, dict_size)
            src_ids = [0] + src_ids + [1]
            trg_ids_next = trg_ids + [1]
            trg_ids = [0] + trg_ids
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(dict_size):
    """Train creator of teacher-forcing triplets (ref wmt14.py:117)."""
    return reader_creator("train", TRAIN_SIZE, dict_size)


def test(dict_size):
    """Test creator (ref wmt14.py:133)."""
    return reader_creator("test", TEST_SIZE, dict_size)


def gen(dict_size):
    """Generation split (ref wmt14.py:149)."""
    return reader_creator("gen", GEN_SIZE, dict_size)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); id->word when reverse (ref wmt14.py:155)."""
    src_dict, trg_dict = _dicts(dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict


def fetch():
    next(train(100)())


def convert(path):  # parity stub: recordio conversion is cache-side
    pass
