"""Movie-review sentiment dataset (ref python/paddle/dataset/sentiment.py,
NLTK movie_reviews wrapper).

Contract: ``get_word_dict()`` -> frequency-ranked word->id;
``train()``/``test()`` yield ``(word_id_list, 0/1)``.  The synthetic
corpus reuses the imdb generator family with its own seed namespace.
"""
import numpy as np

from . import synthetic

__all__ = ['train', 'test', 'get_word_dict']

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
VOCAB = 3000
_SENTI = 30


def _words(i):
    rng = synthetic.rng_for("sentiment", i)
    label = int(rng.randint(2))
    n = int(rng.randint(15, 80))
    ids = synthetic.zipf_sentence(rng, VOCAB, n)
    base = 80 + (0 if label else _SENTI)
    for _ in range(max(3, n // 6)):
        ids[int(rng.randint(n))] = base + int(rng.randint(_SENTI))
    return ["w%04d" % w for w in ids], label


_WORD_DICT = None
_DATA = None


def get_word_dict():
    """Frequency-sorted (word, id) over the whole corpus, cached at
    module level like the reference's download cache
    (ref sentiment.py:70)."""
    global _WORD_DICT
    if _WORD_DICT is None:
        words_freq = {}
        for i in range(NUM_TOTAL_INSTANCES):
            for w in _words(i)[0]:
                words_freq[w] = words_freq.get(w, 0) + 1
        words_sort_list = sorted(words_freq.items(),
                                 key=lambda x: (-x[1], x[0]))
        _WORD_DICT = dict(
            (w, i) for i, (w, _) in enumerate(words_sort_list))
    return _WORD_DICT


def load_sentiment_data():
    global _DATA
    if _DATA is None:
        word_idx = get_word_dict()
        _DATA = [([word_idx[w] for w in ws], lab)
                 for ws, lab in (_words(i)
                                 for i in range(NUM_TOTAL_INSTANCES))]
    return _DATA


def reader_creator(data):
    def reader():
        for each in data:
            yield each

    return reader


def train():
    """First 1600 labeled reviews (ref sentiment.py:133)."""
    return reader_creator(load_sentiment_data()[:NUM_TRAINING_INSTANCES])


def test():
    """Remaining 400 reviews (ref sentiment.py:141)."""
    return reader_creator(load_sentiment_data()[NUM_TRAINING_INSTANCES:])


def fetch():
    next(train()())
