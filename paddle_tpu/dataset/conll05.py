"""CoNLL-2005 semantic-role-labeling dataset
(ref python/paddle/dataset/conll05.py).

Contract (ref conll05.py:150-205): ``test()`` yields 9-tuples
``(word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark,
label_idx)`` — all length-T lists; ctx_* are the predicate's +-2-window
words broadcast to T; mark flags that window; labels are IOB SRL tags.
``get_dict()`` -> (word_dict, verb_dict, label_dict);
``get_embedding()`` -> float32[len(word_dict), 32] pretrained-style
embedding matrix (synthetic, deterministic).
"""
import numpy as np

from . import synthetic

__all__ = ['test', 'get_dict', 'get_embedding']

UNK_IDX = 0
WORD_VOCAB = 1000
VERB_VOCAB = 50
TEST_SIZE = 300
_LABELS = ['B-A0', 'I-A0', 'B-A1', 'I-A1', 'B-A2', 'I-A2', 'B-V', 'I-V',
           'B-AM-TMP', 'I-AM-TMP', 'O']
EMB_DIM = 32


def load_label_dict(filename=None):
    return {l: i for i, l in enumerate(_LABELS)}


def get_dict():
    """(word_dict, verb_dict, label_dict) (ref conll05.py:205)."""
    word_dict = synthetic.make_vocab(WORD_VOCAB)
    word_dict['bos'] = len(word_dict)
    word_dict['eos'] = len(word_dict)
    verb_dict = synthetic.make_vocab(VERB_VOCAB, prefix="v")
    return word_dict, verb_dict, load_label_dict()


def get_embedding():
    """Deterministic float32[|V|, 32] word-embedding matrix (the
    reference returns a downloaded binary; ours is generated)
    (ref conll05.py:218)."""
    word_dict, _, _ = get_dict()
    rng = synthetic.rng_for("conll05", "emb")
    return rng.normal(0, 0.1, (len(word_dict), EMB_DIM)).astype(np.float32)


def _sentence(i):
    rng = synthetic.rng_for("conll05", "test", i)
    T = int(rng.randint(5, 25))
    words = [int(w) for w in synthetic.zipf_sentence(rng, WORD_VOCAB, T)]
    verb_index = int(rng.randint(T))
    verb = int(rng.randint(VERB_VOCAB))
    labels = ['O'] * T
    labels[verb_index] = 'B-V'
    # a plausible A0 span before the verb, A1 span after
    if verb_index > 1:
        s = int(rng.randint(0, verb_index - 1))
        labels[s] = 'B-A0'
        for j in range(s + 1, verb_index):
            labels[j] = 'I-A0'
    if verb_index < T - 2:
        s = int(rng.randint(verb_index + 1, T - 1))
        labels[s] = 'B-A1'
        for j in range(s + 1, T):
            labels[j] = 'I-A1'
    return words, verb_index, verb, labels


def reader_creator(word_dict=None, predicate_dict=None, label_dict=None):
    bos = word_dict['bos']
    eos = word_dict['eos']

    def ctx(words, j):
        if 0 <= j < len(words):
            return words[j]
        return bos if j < 0 else eos

    def reader():
        for i in range(TEST_SIZE):
            words, vi, verb, labels = _sentence(i)
            T = len(words)
            mark = [0] * T
            for j in range(max(0, vi - 2), min(T, vi + 3)):
                mark[j] = 1
            yield (words,
                   [ctx(words, vi - 2)] * T, [ctx(words, vi - 1)] * T,
                   [ctx(words, vi)] * T, [ctx(words, vi + 1)] * T,
                   [ctx(words, vi + 2)] * T,
                   [verb] * T, mark,
                   [label_dict[l] for l in labels])

    return reader


def test():
    """SRL test-set creator of 9-slot samples (ref conll05.py:225)."""
    word_dict, verb_dict, label_dict = get_dict()
    return reader_creator(word_dict, verb_dict, label_dict)


def fetch():
    next(test()())
