"""Shared helpers for the synthetic corpus generators.

Every module in this package derives its payload from a deterministic
stream seeded on (corpus name, split, index), so readers are stable
across processes/hosts (important for data-parallel determinism,
SURVEY §5) and restartable without any materialized cache.
"""
import hashlib

import numpy as np

__all__ = ["seed_for", "rng_for", "zipf_sentence", "make_vocab"]


def seed_for(*parts):
    """Stable 32-bit seed from a tuple of strings/ints."""
    h = hashlib.md5("|".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:4], "little")


def rng_for(*parts):
    return np.random.RandomState(seed_for(*parts))


def make_vocab(n, prefix="w"):
    """word -> id dict of n synthetic word strings (id = rank)."""
    width = len(str(n - 1))
    return {"%s%0*d" % (prefix, width, i): i for i in range(n)}


def zipf_sentence(rng, vocab_size, length, a=1.3):
    """A sentence of word-ids with a Zipf-like marginal — keeps frequency
    structure (stopwords vs tail) so build_dict cutoffs behave like on
    real text."""
    ids = rng.zipf(a, size=length)
    return list(np.minimum(ids - 1, vocab_size - 1).astype(np.int64))
