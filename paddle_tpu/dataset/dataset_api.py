"""Dataset API.

Reference parity: python/paddle/fluid/dataset.py (DatasetFactory,
InMemoryDataset, QueueDataset) + framework/data_set.cc. Backed by the
native C++ record plane (paddle_tpu/native): InMemoryDataset loads + global
shuffles in host RAM; QueueDataset streams through the C++ ring buffer.
Two on-disk formats, auto-detected per file:
  * ptrec binary records (native/dataplane.cc ring-buffer reader)
  * MultiSlot text (native ms_parse_file — the reference
    MultiSlotDataFeed format that incubate.data_generator emits)
"""
import random

import numpy as np

from ..native.recordio import RecordReader
from ..native.multislot import MultiSlotTextReader

_PTREC_MAGIC = b"crtp"  # u32 0x70747263 little-endian on disk


class DatasetFactory(object):
    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        return QueueDataset()


class DatasetBase(object):
    def __init__(self):
        self._paths = []
        self._batch_size = 1
        self._use_vars = []
        self._slot_dtypes = []
        self._thread = 2
        self._format = "auto"

    def set_filelist(self, filelist):
        self._paths = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread = thread_num

    def set_use_var(self, var_list):
        self._use_vars = [v.name if hasattr(v, "name") else v
                          for v in var_list]
        # plain string names carry no dtype — leave None so the multislot
        # path (which must know int vs float per slot) raises instead of
        # silently mis-parsing id slots as floats
        self._slot_dtypes = [getattr(v, "dtype", None) for v in var_list]

    def set_length_buckets(self, buckets, by, pad_slots=None):
        """Length-bucketed batching for ragged data.

        Samples are grouped by the length of slot `by` into the smallest
        bucket that fits; each batch's ragged slots pad to the BUCKET
        width, not the global max. Two wins on TPU: one stable shape per
        bucket means one Executor compile-cache entry per bucket, and no
        MXU work is wasted padding every batch to max_len — the
        dense+lengths answer to the reference's zero-padding LoD kernels
        (sequence_ops/sequence_pool_op.h:29, which walk ragged offsets).

        buckets: ascending capacities, e.g. (32, 64, 128, 256). A sample
        longer than the largest bucket raises a named error.
        by: name of the slot whose length assigns the bucket.
        pad_slots: slots padded to the bucket width (default: [by]);
        each gets a "<name>__lens" int64 vector alongside."""
        bl = sorted(int(b) for b in buckets)
        if not bl:
            raise ValueError("set_length_buckets needs at least one bucket")
        self._buckets = bl
        self._bucket_by = by
        self._bucket_pad = list(pad_slots) if pad_slots is not None \
            else [by]
        if by not in self._bucket_pad:
            self._bucket_pad.append(by)

    def set_data_format(self, fmt):
        """"ptrec" | "multislot_text" | "auto" (default: sniff each
        file's leading magic bytes)."""
        if fmt not in ("ptrec", "multislot_text", "auto"):
            raise ValueError("unknown data format %r" % (fmt,))
        self._format = fmt

    @staticmethod
    def _detect_format(path):
        try:
            with open(path, "rb") as f:
                magic = f.read(4)
        except OSError:
            return "ptrec"
        return "ptrec" if magic == _PTREC_MAGIC else "multislot_text"

    def _multislot_slots(self):
        slots = list(zip(self._use_vars, self._slot_dtypes))
        if not slots or any(d is None for _, d in slots):
            raise ValueError(
                "multislot text needs set_use_var(...) with Variable "
                "objects (or anything carrying .name/.dtype) to declare "
                "the slot order and int/float dtypes")
        return slots

    def _sample_iter(self):
        """Per-file format detection; consecutive same-format files are
        grouped so ptrec runs keep their threaded ring-buffer reads."""
        if self._format == "auto":
            fmts = [self._detect_format(p) for p in self._paths]
        else:
            fmts = [self._format] * len(self._paths)
        runs = []
        for p, f in zip(self._paths, fmts):
            if runs and runs[-1][0] == f:
                runs[-1][1].append(p)
            else:
                runs.append([f, [p]])
        for fmt, paths in runs:
            if fmt == "multislot_text":
                for s in MultiSlotTextReader(
                        paths, self._multislot_slots()).samples():
                    yield s
            else:
                for s in RecordReader(
                        paths, num_threads=self._thread).samples():
                    # normalize to dicts when slot names are declared so
                    # a batch spanning a ptrec/text boundary collates
                    # uniformly
                    if self._use_vars and not isinstance(s, dict):
                        s = dict(zip(self._use_vars, s))
                    yield s

    def _batches(self, sample_iter):
        if getattr(self, "_buckets", None):
            return self._bucketed_batches(sample_iter)
        return self._plain_batches(sample_iter)

    def _plain_batches(self, sample_iter):
        buf = []
        for sample in sample_iter:
            buf.append(sample)
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf = []
        if buf:
            yield self._collate(buf)

    def _as_dict(self, sample):
        if isinstance(sample, dict):
            return sample
        if not self._use_vars:
            raise ValueError(
                "length bucketing needs dict samples or set_use_var(...) "
                "to name tuple slots")
        return dict(zip(self._use_vars, sample))

    def _bucketed_batches(self, sample_iter):
        bufs = {b: [] for b in self._buckets}
        by = self._bucket_by
        for sample in sample_iter:
            sample = self._as_dict(sample)
            # every pad slot must fit the assigned bucket: the bucket is
            # picked by the longest one, with a named error past the cap
            ln = max(int(np.asarray(sample[s]).shape[0])
                     for s in self._bucket_pad)
            for b in self._buckets:
                if ln <= b:
                    break
            else:
                longest = max(self._bucket_pad,
                              key=lambda s: np.asarray(sample[s]).shape[0])
                raise ValueError(
                    "sample slot %r has length %d, longer than the "
                    "largest bucket %d"
                    % (longest, np.asarray(sample[longest]).shape[0],
                       self._buckets[-1]))
            bufs[b].append(sample)
            if len(bufs[b]) == self._batch_size:
                yield self._collate(bufs[b], width=b)
                bufs[b] = []
        for b in self._buckets:
            if bufs[b]:
                yield self._collate(bufs[b], width=b)

    def _collate(self, samples, width=None):
        """Stack a batch; ragged slots (variable-length MultiSlot values)
        are padded to the batch max and a "<name>__lens" int64 vector is
        added — the dense+lengths encoding of the reference's LoD batch
        (PORTING.md difference #1). With length bucketing, `width` pins
        the designated pad_slots to the bucket capacity so every batch
        from one bucket has the same shape (one compile per bucket)."""
        if isinstance(samples[0], dict):
            out = {}
            pad_slots = self._bucket_pad if width is not None else ()
            for n in samples[0]:
                cols = [np.asarray(s[n]) for s in samples]
                lens = [c.shape[0] for c in cols]
                pinned = n in pad_slots
                if not pinned and len(set(lens)) == 1:
                    out[n] = np.stack(cols)
                    continue
                w = width if pinned else max(lens)
                padded = np.zeros((len(cols), w) + cols[0].shape[1:],
                                  cols[0].dtype)
                for i, c in enumerate(cols):
                    padded[i, :c.shape[0]] = c
                out[n] = padded
                out[n + "__lens"] = np.asarray(lens, np.int64)
            return out
        if width is not None:
            return self._collate([self._as_dict(s) for s in samples],
                                 width=width)
        cols = list(zip(*samples))
        return {n: np.stack(c)
                for n, c in zip(self._use_vars, cols)}


class QueueDataset(DatasetBase):
    """Streaming dataset: C++ threaded readers + ring buffer (ptrec) or
    the native MultiSlot text parser."""

    def __iter__(self):
        return self._batches(self._sample_iter())


class InMemoryDataset(DatasetBase):
    """Load-then-global-shuffle dataset (reference InMemoryDataset:
    load_into_memory + local/global_shuffle)."""

    def __init__(self):
        super(InMemoryDataset, self).__init__()
        self._samples = []
        self._seed = 0

    def load_into_memory(self):
        self._samples = list(self._sample_iter())

    def local_shuffle(self):
        random.Random(self._seed).shuffle(self._samples)
        self._seed += 1

    def global_shuffle(self, fleet=None):
        # single-host view of the reference's cross-node shuffle; on a pod
        # every host holds its own file shards and shuffles locally, which
        # is the same sample distribution the reference converges to
        self.local_shuffle()

    def get_memory_data_size(self):
        return len(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        return self._batches(iter(self._samples))
