"""Dataset API.

Reference parity: python/paddle/fluid/dataset.py (DatasetFactory,
InMemoryDataset, QueueDataset) + framework/data_set.cc. Backed by the
native C++ record plane (paddle_tpu/native): InMemoryDataset loads + global
shuffles in host RAM; QueueDataset streams through the C++ ring buffer.
"""
import random

import numpy as np

from ..native.recordio import RecordReader


class DatasetFactory(object):
    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        return QueueDataset()


class DatasetBase(object):
    def __init__(self):
        self._paths = []
        self._batch_size = 1
        self._use_vars = []
        self._thread = 2

    def set_filelist(self, filelist):
        self._paths = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread = thread_num

    def set_use_var(self, var_list):
        self._use_vars = [v.name if hasattr(v, "name") else v
                          for v in var_list]

    def _collate(self, samples):
        cols = list(zip(*samples))
        return {n: np.stack(c)
                for n, c in zip(self._use_vars, cols)}


class QueueDataset(DatasetBase):
    """Streaming dataset: C++ threaded readers + ring buffer."""

    def __iter__(self):
        reader = RecordReader(self._paths, num_threads=self._thread)
        buf = []
        for sample in reader.samples():
            buf.append(sample)
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf = []
        if buf:
            yield self._collate(buf)


class InMemoryDataset(DatasetBase):
    """Load-then-global-shuffle dataset (reference InMemoryDataset:
    load_into_memory + local/global_shuffle)."""

    def __init__(self):
        super(InMemoryDataset, self).__init__()
        self._samples = []
        self._seed = 0

    def load_into_memory(self):
        reader = RecordReader(self._paths, num_threads=self._thread)
        self._samples = list(reader.samples())

    def local_shuffle(self):
        random.Random(self._seed).shuffle(self._samples)
        self._seed += 1

    def global_shuffle(self, fleet=None):
        # single-host view of the reference's cross-node shuffle; on a pod
        # every host holds its own file shards and shuffles locally, which
        # is the same sample distribution the reference converges to
        self.local_shuffle()

    def get_memory_data_size(self):
        return len(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        buf = []
        for sample in self._samples:
            buf.append(sample)
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf = []
        if buf:
            yield self._collate(buf)
