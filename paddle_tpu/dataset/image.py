"""Image preprocessing utilities (ref python/paddle/dataset/image.py).

The reference wraps OpenCV; this build is pure numpy + PIL (both baked
into the image) with the same function contracts: images are HWC uint8
(or float) arrays in RGB order unless stated; ``to_chw`` converts for
the conv stack's NCHW layout.
"""
import numpy as np

try:
    from PIL import Image as _PILImage
except Exception:  # pragma: no cover
    _PILImage = None

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar"
]


def _require_pil():
    if _PILImage is None:
        raise RuntimeError("PIL is unavailable; image decoding disabled")


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pack a tarball of images into pickled (data, label) batch files
    (ref image.py:80).  Retained for API parity; operates on a local
    tarball only (no download)."""
    import os
    import pickle
    import tarfile

    batch_dir = data_file + "_batch"
    out_path = "%s/%s_%s" % (batch_dir, dataset_name, "batch")
    meta_file = "%s/%s_%s.txt" % (batch_dir, dataset_name, "batch")
    if os.path.exists(out_path):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    tf = tarfile.open(data_file)
    mems = tf.getmembers()
    data, labels, file_id = [], [], 0
    for mem in mems:
        if mem.name in img2label:
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                output = {'label': labels, 'data': data}
                with open("%s/batch_%d" % (out_path, file_id), "wb") as f:
                    pickle.dump(output, f, protocol=2)
                file_id += 1
                data, labels = [], []
    if data:
        output = {'label': labels, 'data': data}
        with open("%s/batch_%d" % (out_path, file_id), "wb") as f:
            pickle.dump(output, f, protocol=2)
    with open(meta_file, 'a') as meta:
        for file in os.listdir(out_path):
            meta.write(os.path.abspath("%s/%s" % (out_path, file)) + "\n")
    return meta_file


def load_image_bytes(bytes_, is_color=True):
    """Decode an encoded image buffer to an HWC (or HW) uint8 array
    (ref image.py:141)."""
    _require_pil()
    import io
    img = _PILImage.open(io.BytesIO(bytes_))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(file, is_color=True):
    """Decode an image file (ref image.py:167)."""
    _require_pil()
    img = _PILImage.open(file)
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def resize_short(im, size):
    """Scale so the SHORT edge becomes ``size``, keeping aspect ratio
    (ref image.py:197)."""
    h, w = im.shape[:2]
    if h > w:
        h_new, w_new = size * h // w, size
    else:
        h_new, w_new = size, size * w // h
    if _PILImage is not None:
        mode = "RGB" if im.ndim == 3 else "L"
        pimg = _PILImage.fromarray(im.astype(np.uint8), mode=mode)
        return np.asarray(pimg.resize((w_new, h_new),
                                      _PILImage.Resampling.BILINEAR))
    # numpy nearest fallback
    ys = (np.arange(h_new) * h / h_new).astype(int)
    xs = (np.arange(w_new) * w / w_new).astype(int)
    return im[ys][:, xs]


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (ref image.py:225)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the centered size x size window (ref image.py:249)."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def random_crop(im, size, is_color=True):
    """Crop a uniformly random size x size window (ref image.py:277)."""
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def left_right_flip(im, is_color=True):
    """Horizontal mirror (ref image.py:305)."""
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short -> (random|center) crop -> maybe flip -> CHW float
    -> maybe mean-subtract (ref image.py:327)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype('float32')
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform (ref image.py:383)."""
    im = load_image(filename, is_color)
    return simple_transform(im, resize_size, crop_size, is_train, is_color,
                            mean)
