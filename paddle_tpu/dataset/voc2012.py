"""PASCAL VOC2012 segmentation dataset (ref python/paddle/dataset/voc2012.py).

Contract: creators yield ``(image, label)`` — image uint8[3, H, W],
label uint8[H, W] with class ids 0..20 and 255 for void boundary
pixels.  Synthetic payload: random rectangles of random classes over a
textured background, with a 1-pixel 255 boundary around each object —
enough structure for segmentation smoke training.
"""
import numpy as np

from . import synthetic

__all__ = ['train', 'test', 'val']

TRAIN_SIZE = 200
TEST_SIZE = 50
VAL_SIZE = 50
_H = _W = 96
N_CLASSES = 21


def _sample(split, i):
    rng = synthetic.rng_for("voc", split, i)
    img = rng.randint(0, 255, (3, _H, _W)).astype(np.uint8)
    label = np.zeros((_H, _W), np.uint8)
    for _ in range(int(rng.randint(1, 4))):
        c = int(rng.randint(1, N_CLASSES))
        y0, x0 = rng.randint(0, _H - 16), rng.randint(0, _W - 16)
        h, w = rng.randint(8, _H - y0), rng.randint(8, _W - x0)
        y1, x1 = min(_H, y0 + h), min(_W, x0 + w)
        label[y0:y1, x0:x1] = c
        # void boundary ring, as in real VOC annotations
        label[y0, x0:x1] = 255
        label[y1 - 1, x0:x1] = 255
        label[y0:y1, x0] = 255
        label[y0:y1, x1 - 1] = 255
        img[:, y0:y1, x0:x1] = (
            img[:, y0:y1, x0:x1] // 2 + int(rng.randint(0, 128)))
    return img, label


def reader_creator(split, size):
    def reader():
        for i in range(size):
            yield _sample(split, i)

    return reader


def train():
    """Segmentation train creator (ref voc2012.py:69)."""
    return reader_creator("train", TRAIN_SIZE)


def test():
    """Test creator (ref voc2012.py:76)."""
    return reader_creator("test", TEST_SIZE)


def val():
    """Validation creator (ref voc2012.py:83)."""
    return reader_creator("val", VAL_SIZE)


def fetch():
    next(train()())
