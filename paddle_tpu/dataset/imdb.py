"""IMDB sentiment dataset (ref python/paddle/dataset/imdb.py).

Contract: ``build_dict(pattern, cutoff)`` -> word->id dict (ids ordered
by descending frequency, '<unk>' appended last); ``train(word_idx)`` /
``test(word_idx)`` yield ``(word_id_list, label)`` with label 0/1.
Synthetic corpus: Zipf-distributed reviews where a small set of
class-keyed sentiment words is over-sampled for one polarity, so
bag-of-words / LSTM classifiers genuinely separate the labels.
"""
import re

import numpy as np

from . import synthetic

__all__ = ['build_dict', 'train', 'test']

VOCAB = 5000
TRAIN_SIZE = 2000
TEST_SIZE = 500
_SENTI = 40  # first ids after stopwords carry class signal


def _words(split, i):
    rng = synthetic.rng_for("imdb", split, i)
    label = int(rng.randint(2))
    n = int(rng.randint(20, 120))
    ids = synthetic.zipf_sentence(rng, VOCAB, n)
    # inject polarity words: ids [100, 100+_SENTI) positive,
    # [140, 140+_SENTI) negative
    base = 100 + (0 if label else _SENTI)
    for _ in range(max(3, n // 8)):
        ids[int(rng.randint(n))] = base + int(rng.randint(_SENTI))
    return ["w%04d" % w for w in ids], label


def tokenize(pattern):
    """Yield tokenized documents for the split named by ``pattern``
    (the reference greps a tarball with an aclImdb path regex; the
    synthetic corpus keys off the train/test substring)."""
    split = "train" if "train" in str(pattern) else "test"
    size = TRAIN_SIZE if split == "train" else TEST_SIZE
    for i in range(size):
        yield _words(split, i)[0]


def build_dict(pattern, cutoff):
    """Frequency-sorted word dict over the split, dropping words with
    frequency <= cutoff; '<unk>' gets the last id (ref imdb.py:59)."""
    word_freq = {}
    for doc in tokenize(pattern):
        for w in doc:
            word_freq[w] = word_freq.get(w, 0) + 1
    word_freq = [x for x in word_freq.items() if x[1] > cutoff]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary))
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx['<unk>'] = len(words)
    return word_idx


def reader_creator(split, size, word_idx):
    unk = word_idx['<unk>']

    def reader():
        for i in range(size):
            words, label = _words(split, i)
            yield [word_idx.get(w, unk) for w in words], label

    return reader


def train(word_idx):
    """Train creator: (ids, 0/1) (ref imdb.py:97)."""
    return reader_creator("train", TRAIN_SIZE, word_idx)


def test(word_idx):
    """Test creator (ref imdb.py:114)."""
    return reader_creator("test", TEST_SIZE, word_idx)


def word_dict():
    """Default dict over the train split (ref imdb.py:131)."""
    return build_dict(re.compile(r"train"), 150)


def fetch():
    next(train(word_dict())())
