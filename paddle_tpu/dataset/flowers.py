"""102-category flowers dataset (ref python/paddle/dataset/flowers.py).

Contract: creators yield ``(image, label)`` with image float32[3*H*W]
(CHW flattened, [0,1]) after the default mapper, label int in [0, 102).
``mapper`` / ``use_xmap`` / ``cycle`` arguments are honored the same
way.  Synthetic payload: class-colored radial "petal" patterns + noise.
"""
import functools

import numpy as np

from . import synthetic
from ..reader.decorator import map_readers, xmap_readers

__all__ = ['train', 'test', 'valid']

TRAIN_SIZE = 400
TEST_SIZE = 100
VAL_SIZE = 100
N_CLASSES = 102
_H = _W = 64


def default_mapper(is_train, sample):
    """img, label -> transformed img (flattened CHW), label
    (ref flowers.py:63).  Train mode adds a random crop-style jitter."""
    img, label = sample
    if is_train:
        rng = np.random.RandomState(int(img.sum() * 1e3) & 0x7fffffff)
        img = np.roll(img, int(rng.randint(-4, 5)), axis=-1)
    return img.reshape(-1).astype(np.float32), label


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def _sample(split, i):
    rng = synthetic.rng_for("flowers", split, i)
    label = int(rng.randint(N_CLASSES))
    crng = synthetic.rng_for("flowers", "proto", label)
    color = crng.uniform(0.3, 1.0, (3, 1, 1)).astype(np.float32)
    petals = int(crng.randint(3, 9))
    yy, xx = np.mgrid[0:_H, 0:_W].astype(np.float32)
    cy, cx = _H / 2.0, _W / 2.0
    theta = np.arctan2(yy - cy, xx - cx)
    r = np.sqrt((yy - cy) ** 2 + (xx - cx) ** 2) / (_H / 2.0)
    petal = (np.cos(petals * theta) * 0.5 + 0.5) * np.clip(1 - r, 0, 1)
    img = color * petal[None] + rng.normal(0, 0.08, (3, _H, _W))
    return np.clip(img, 0, 1).astype(np.float32), label


def reader_creator(split, size, mapper, buffered_size=1024,
                   use_xmap=True, cycle=False):
    def reader():
        while True:
            for i in range(size):
                yield _sample(split, i)
            if not cycle:
                break

    if use_xmap:
        return xmap_readers(mapper, reader, min(4, buffered_size),
                            buffered_size)
    return map_readers(mapper, reader)


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True,
          cycle=False):
    """Train creator (ref flowers.py:146)."""
    return reader_creator("train", TRAIN_SIZE, mapper, buffered_size,
                          use_xmap, cycle)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True,
         cycle=False):
    """Test creator (ref flowers.py:175)."""
    return reader_creator("test", TEST_SIZE, mapper, buffered_size,
                          use_xmap, cycle)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    """Validation creator (ref flowers.py:204)."""
    return reader_creator("val", VAL_SIZE, mapper, buffered_size, use_xmap)


def fetch():
    next(train(use_xmap=False)())
