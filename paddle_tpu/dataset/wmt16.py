"""WMT16 EN<->DE translation dataset (ref python/paddle/dataset/wmt16.py).

Contract (ref wmt16.py:109-145): creators take (src_dict_size,
trg_dict_size, src_lang) and yield ``(src_ids, trg_ids, trg_ids_next)``
with <s>=0, <e>=1, <unk>=2 in both vocabularies; ``get_dict(lang,
dict_size, reverse)`` returns the per-language dict.  Synthetic pairs
share a latent sequence (same scheme as wmt14, separate namespace).
"""
import numpy as np

from . import synthetic

__all__ = [
    "train", "test", "validation", "get_dict", "fetch", "convert"
]

TRAIN_SIZE = 2000
TEST_SIZE = 400
VAL_SIZE = 400


def _lang_words(lang, n):
    return ["<s>", "<e>", "<unk>"] + \
        ["%s%05d" % (lang, i) for i in range(n - 3)]


def _pair(split, i, src_size, trg_size):
    rng = synthetic.rng_for("wmt16", split, i)
    n = int(rng.randint(4, 30))
    src = [3 + int(w) % (src_size - 3)
           for w in synthetic.zipf_sentence(rng, src_size - 3, n)]
    trg = [3 + (w - 3 + 11) % (trg_size - 3) for w in src]
    if n > 8:
        trg = trg[:-2]
    return src, trg


def reader_creator(split, size, src_dict_size, trg_dict_size, src_lang):
    def reader():
        for i in range(size):
            src_ids, trg_ids = _pair(split, i, src_dict_size,
                                     trg_dict_size)
            src_ids = [0] + src_ids + [1]
            trg_ids_next = trg_ids + [1]
            trg_ids = [0] + trg_ids
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    """Train creator (ref wmt16.py:147)."""
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    return reader_creator("train", TRAIN_SIZE, src_dict_size,
                          trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    """Test creator (ref wmt16.py:196)."""
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    return reader_creator("test", TEST_SIZE, src_dict_size, trg_dict_size,
                          src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    """Validation creator (ref wmt16.py:245)."""
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    return reader_creator("val", VAL_SIZE, src_dict_size, trg_dict_size,
                          src_lang)


def get_dict(lang, dict_size, reverse=False):
    """Per-language word dict (ref wmt16.py:292)."""
    words = _lang_words(lang, dict_size)
    if reverse:
        return dict(enumerate(words))
    return {w: i for i, w in enumerate(words)}


def fetch():
    next(train(100, 100)())


def convert(path, src_dict_size, trg_dict_size, src_lang):  # parity stub
    pass
