"""MovieLens-1M-style recommendation dataset
(ref python/paddle/dataset/movielens.py).

Contract: samples are ``user.value() + movie.value() + [rating]`` =
``[user_id, gender, age_bucket, job_id, movie_id, [category_ids],
[title_word_ids], rating]``; plus the meta accessors (max ids, category
list, title dict, MovieInfo/UserInfo records).  Synthetic catalogue:
deterministic users/movies with genre-conditioned ratings so factored
models (e.g. DeepFM) can fit real structure.
"""
import numpy as np

from . import synthetic

__all__ = [
    'train', 'test', 'get_movie_title_dict', 'max_movie_id', 'max_user_id',
    'age_table', 'movie_categories', 'max_job_id', 'user_info', 'movie_info'
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_MOVIES = 400
_N_USERS = 600
_N_RATINGS = 8000
_CATEGORIES = [
    'Action', 'Adventure', 'Animation', "Children's", 'Comedy', 'Crime',
    'Documentary', 'Drama', 'Fantasy', 'Film-Noir', 'Horror', 'Musical',
    'Mystery', 'Romance', 'Sci-Fi', 'Thriller', 'War', 'Western'
]
_TITLE_VOCAB = 500
_MAX_JOB = 20


class MovieInfo(object):
    """Movie id, title and categories (ref movielens.py:48)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [
            self.index, [CATEGORIES_DICT[c] for c in self.categories],
            [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]
        ]

    def __str__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)

    __repr__ = __str__


class UserInfo(object):
    """User id, gender, age bucket and job (ref movielens.py:74)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __str__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)

    __repr__ = __str__


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
USER_INFO = None


def __initialize_meta_info__():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO
    if MOVIE_INFO is not None:
        return
    CATEGORIES_DICT = {c: i for i, c in enumerate(_CATEGORIES)}
    MOVIE_TITLE_DICT = {"t%04d" % i: i for i in range(_TITLE_VOCAB)}
    MOVIE_INFO = {}
    for m in range(1, _N_MOVIES + 1):
        rng = synthetic.rng_for("ml", "movie", m)
        cats = list(rng.choice(_CATEGORIES,
                               size=int(rng.randint(1, 4)), replace=False))
        title = " ".join("t%04d" % rng.randint(_TITLE_VOCAB)
                         for _ in range(int(rng.randint(1, 5))))
        MOVIE_INFO[m] = MovieInfo(index=m, categories=cats, title=title)
    USER_INFO = {}
    for u in range(1, _N_USERS + 1):
        rng = synthetic.rng_for("ml", "user", u)
        USER_INFO[u] = UserInfo(
            index=u, gender='M' if rng.rand() < 0.5 else 'F',
            age=age_table[int(rng.randint(len(age_table)))],
            job_id=int(rng.randint(_MAX_JOB)))


def _rating(u, m):
    """Deterministic genre-affinity rating in [1, 5]."""
    __initialize_meta_info__()
    rng = synthetic.rng_for("ml", "rate", u, m)
    affin = synthetic.rng_for("ml", "affin", u).normal(
        0, 1, len(_CATEGORIES))
    cats = [CATEGORIES_DICT[c] for c in MOVIE_INFO[m].categories]
    score = 3.0 + float(np.mean([affin[c] for c in cats])) + \
        rng.normal(0, 0.5)
    return float(np.clip(np.round(score), 1, 5))


def __reader__(rand_seed=0, test_ratio=0.1, is_test=False):
    __initialize_meta_info__()
    rng = synthetic.rng_for("ml", "pairs", rand_seed)
    for _ in range(_N_RATINGS):
        in_test = rng.rand() < test_ratio
        u = int(rng.randint(1, _N_USERS + 1))
        m = int(rng.randint(1, _N_MOVIES + 1))
        if in_test != is_test:
            continue
        usr, mov = USER_INFO[u], MOVIE_INFO[m]
        yield usr.value() + mov.value() + [[_rating(u, m)]]


def __reader_creator__(**kwargs):
    return lambda: __reader__(**kwargs)


train = __reader_creator__(is_test=False)
test = __reader_creator__(is_test=True)


def get_movie_title_dict():
    __initialize_meta_info__()
    return MOVIE_TITLE_DICT


def max_movie_id():
    __initialize_meta_info__()
    return max(MOVIE_INFO, key=lambda m: MOVIE_INFO[m].index)


def max_user_id():
    __initialize_meta_info__()
    return max(USER_INFO, key=lambda u: USER_INFO[u].index)


def max_job_id():
    __initialize_meta_info__()
    return max(USER_INFO.values(), key=lambda u: u.job_id).job_id


def movie_categories():
    __initialize_meta_info__()
    return CATEGORIES_DICT


def user_info():
    __initialize_meta_info__()
    return USER_INFO


def movie_info():
    __initialize_meta_info__()
    return MOVIE_INFO


def unittest():
    for train_count, _ in enumerate(train()()):
        pass
    for test_count, _ in enumerate(test()()):
        pass
    print(train_count, test_count)


def fetch():
    __initialize_meta_info__()
