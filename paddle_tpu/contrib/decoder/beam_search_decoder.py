"""Contrib seq2seq decoder API
(ref python/paddle/fluid/contrib/decoder/beam_search_decoder.py).

Same user surface as the reference — InitState / StateCell (with the
``@state_cell.state_updater`` decorator) / TrainingDecoder /
BeamSearchDecoder — with the execution model redesigned for XLA:

* the reference drives a While op over LoD tensor-arrays and the LoD
  ``beam_search`` op; dynamic beam structures are hostile to static
  shapes, so here the beam frontier is a dense flattened (batch*beam)
  axis and decode() unrolls ``max_len`` steps at trace time (the same
  design as models/transformer.py beam decode, which is verified exact
  against its serial oracle);
* finished beams are frozen by masking (forced end_id continuation at
  zero added score) instead of shrinking — ``early_stop`` therefore
  documents itself as a no-op: a fixed-trip XLA loop costs the same and
  the masked tail changes nothing.

The user's state updater is an ordinary layer-building function, so it
is simply re-invoked once per unrolled step.
"""
import contextlib

from ... import layers
from ...layers.control_flow import DynamicRNN

__all__ = ['InitState', 'StateCell', 'TrainingDecoder',
           'BeamSearchDecoder']


class _DecoderType(object):
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial hidden state (ref :43): either an existing var, or a
    constant tensor shaped like ``init_boot``."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype='float32'):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                'init_boot must be provided to infer the shape of '
                'InitState.\n')
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell(object):
    """Named states + step inputs + a registered updater (ref :159).
    ``compute_state`` binds the step inputs and runs the updater, which
    reads via get_input/get_state and writes via set_state;
    ``update_states`` commits the staged states (inside a
    TrainingDecoder it forwards to the RNN memory update)."""

    def __init__(self, inputs, states, out_state, name=None):
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError('state must be an InitState object.')
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = dict(inputs)
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state
        if self._out_state not in self._cur_states:
            raise ValueError('out_state must be one state in states')

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError('StateCell has already entered a decoder.')
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder:
            raise ValueError('StateCell not in decoder, invalid leaving '
                             'operation.')
        if self._cur_decoder_obj is not decoder_obj:
            raise ValueError('Inconsistent decoder object in StateCell.')
        self._in_decoder = False
        self._cur_decoder_obj = None

    def state_updater(self, updater):
        """Decorator registering the per-step transition fn (ref :300)."""
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell is not self:
                raise TypeError('Updater should only accept a StateCell '
                                'object as argument.')
            updater(state_cell)

        return _decorator

    def get_state(self, state_name):
        if state_name not in self._cur_states:
            raise ValueError('Unknown state %s.' % state_name)
        cur = self._cur_states[state_name]
        return cur.value if isinstance(cur, InitState) else cur

    def get_input(self, input_name):
        if input_name not in self._inputs or \
                self._inputs[input_name] is None:
            raise ValueError('Invalid input %s.' % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        if state_name not in self._cur_states:
            raise ValueError('Unknown state %s.' % state_name)
        self._cur_states[state_name] = state_value

    def compute_state(self, inputs):
        """Bind step inputs and run the updater (ref :106)."""
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError(
                    'Unknown input %s. Please make sure %s in input place'
                    ' holder.' % (input_name, input_name))
            self._inputs[input_name] = input_value
        if self._state_updater is None:
            raise ValueError('No state updater registered; decorate one '
                             'with @state_cell.state_updater.')
        self._state_updater(self)

    def update_states(self):
        """Commit staged states; inside a TrainingDecoder this updates
        the underlying RNN memories (ref :131)."""
        if self._in_decoder and \
                getattr(self._cur_decoder_obj, "type", None) == \
                _DecoderType.TRAINING:
            self._cur_decoder_obj._commit_states(self)

    def out_state(self):
        return self.get_state(self._out_state)


class TrainingDecoder(object):
    """Teacher-forced decoder RNN (ref :384): states become DynamicRNN
    memories; block() is a step scope."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._drnn = DynamicRNN(name=name)
        self._type = _DecoderType.TRAINING
        self._mems = {}
        self._static = {}

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self):
        self._assert_in_decoder_block('state_cell')
        return self._state_cell

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError('decoder.block() can only be invoked once')
        self._status = TrainingDecoder.IN_DECODER
        with self._drnn.block():
            # materialize every state as an RNN memory seeded by its
            # InitState value
            for name in self._state_cell._state_names:
                init = self._state_cell._cur_states[name]
                mem = self._drnn.memory(init=init.value)
                self._mems[name] = mem
                self._state_cell._cur_states[name] = mem
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    def step_input(self, x):
        self._assert_in_decoder_block('step_input')
        return self._drnn.step_input(x)

    def static_input(self, x):
        """Whole-sequence side input visible unchanged at every step
        (ref :470).  Dense design: the var broadcasts naturally inside
        the traced step, so it passes through."""
        self._assert_in_decoder_block('static_input')
        self._static[x.name] = x
        return x

    def output(self, *outputs):
        self._assert_in_decoder_block('output')
        self._drnn.output(*outputs)

    def _commit_states(self, cell):
        for name, mem in self._mems.items():
            new = cell._cur_states[name]
            if new is not mem:
                self._drnn.update_memory(mem, new)
                cell._cur_states[name] = mem

    def __call__(self):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError('Output of training decoder can only be '
                             'visited outside the block.')
        return self._drnn()

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError('%s should be invoked inside block of '
                             'TrainingDecoder object.' % method)


class BeamSearchDecoder(object):
    """Beam-search inference decoder (ref :523).  decode() builds the
    default embedding -> state cell -> softmax fc -> topk flow; the
    result is dense: translation_ids (N, beam, max_len) int64 (end_id
    padded) and translation_scores (N, beam) accumulated log-probs,
    sorted best-first."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict={}, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._type = _DecoderType.BEAM_SEARCH
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict)
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        self._name = name
        self._outputs = None

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self):
        return self._state_cell

    def early_stop(self):
        """No-op by design: the unrolled loop has a fixed trip count for
        XLA and finished beams are already frozen by the end_id mask, so
        stopping early would change cost, not results."""

    def _tile_beams(self, var):
        """(N, ...) -> (N*beam, ...) repeating each row beam times."""
        b = self._beam_size
        shape = list(var.shape)
        expanded = layers.expand(layers.unsqueeze(var, axes=[1]),
                                 [1, b] + [1] * (len(shape) - 1))
        return layers.reshape(expanded, [-1] + shape[1:])

    def decode(self):
        """Default decode flow (ref :653), dense-beam edition."""
        cell = self._state_cell
        b, v = self._beam_size, self._target_dict_dim
        neg_inf = -1e9
        # (N, 1) inits -> (N, b); only beam 0 live at t=0 so the first
        # expansion draws b distinct words
        ids = layers.cast(
            layers.expand(layers.reshape(self._init_ids, [-1, 1]),
                          [1, b]), "int64")                 # (N, b)
        scores = layers.expand(
            layers.reshape(self._init_scores, [-1, 1]), [1, b])
        first = layers.fill_constant_batch_size_like(
            ids, shape=[-1, 1], dtype='float32', value=0.0)
        if b > 1:
            dead0 = layers.fill_constant_batch_size_like(
                ids, shape=[-1, b - 1], dtype='float32', value=neg_inf)
            scores = layers.elementwise_add(
                scores, layers.concat([first, dead0], axis=1))
        # expand every state and side input across beams once
        for name in cell._state_names:
            cell.set_state(name, self._tile_beams(cell.get_state(name)))
        tiled_inputs = {k: self._tile_beams(var)
                        for k, var in self._input_var_dict.items()}
        for k in tiled_inputs:
            if k not in cell._inputs:
                raise ValueError('Variable ' + k +
                                 ' not found in StateCell!\n')
        end_const = layers.fill_constant([1], "int64", self._end_id)
        v_const = layers.fill_constant([1], "int64", v)
        # (1, V) one-hot of end_id -> additive mask that is 0 at end_id
        # and -inf elsewhere: the only free continuation of a dead beam
        end_row = layers.scale(layers.scale(
            layers.one_hot(layers.reshape(end_const, [1, 1]), v),
            scale=-1.0, bias=1.0), scale=neg_inf)
        end_row = layers.reshape(end_row, [1, 1, v])

        # the loop is UNROLLED, so every parameter created inside it must
        # carry a pinned name to be shared across steps (the reference's
        # While body creates each param once; here re-creation with the
        # same name resolves to the same Parameter)
        from ...param_attr import ParamAttr
        from ...framework import unique_name
        if self._name is None:
            # unique per decoder: two anonymous decoders in one program
            # must not silently share embedding/fc weights
            self._name = unique_name.generate("beam_decoder")
        uid = self._name
        emb_attr = ParamAttr(name=uid + "_emb_w")
        fc_w_attr = ParamAttr(name=uid + "_fc_w")
        fc_b_attr = ParamAttr(name=uid + "_fc_b")
        from ...framework.program import default_main_program
        blk = default_main_program().global_block()

        hist = None                       # (N*b, t) selected prefixes
        n_params_after_first_step = None
        for t in range(self._max_len):
            flat_ids = layers.reshape(ids, [-1, 1])        # (N*b, 1)
            emb = layers.embedding(flat_ids,
                                   size=[v, self._word_dim],
                                   dtype='float32',
                                   is_sparse=self._sparse_emb,
                                   param_attr=emb_attr)
            emb = layers.reshape(emb, [-1, self._word_dim])
            feed = dict(tiled_inputs)
            for input_name in cell._inputs:
                if input_name not in feed:
                    feed[input_name] = emb
            cell.compute_state(inputs=feed)
            prob = layers.fc(cell.out_state(), size=v, act='softmax',
                             param_attr=fc_w_attr, bias_attr=fc_b_attr)
            if t == 0:
                n_params_after_first_step = len(
                    blk.all_parameters())
            elif t == 1 and len(blk.all_parameters()) != \
                    n_params_after_first_step:
                raise ValueError(
                    "the state updater created new parameters on the "
                    "second decode step: in this unrolled decoder every "
                    "layer inside the updater must pin its weights with "
                    "a named ParamAttr so all steps share them")
            logp = layers.reshape(layers.log(prob), [-1, b, v])
            if t > 0:
                ended = layers.cast(layers.equal(ids, end_const),
                                    "float32")             # (N, b)
                live3 = layers.unsqueeze(
                    layers.scale(ended, scale=-1.0, bias=1.0), [2])
                logp = layers.elementwise_add(
                    layers.elementwise_mul(logp, live3),
                    layers.elementwise_mul(
                        end_row, layers.unsqueeze(ended, [2])))
            total = layers.elementwise_add(
                logp, layers.unsqueeze(scores, [2]))       # (N, b, V)
            scores, top = layers.topk(
                layers.reshape(total, [-1, b * v]), k=b)   # (N, b)
            beam_idx = layers.elementwise_floordiv(top, v_const)
            ids = layers.elementwise_mod(top, v_const)     # (N, b) int64
            # flat gather indices = row_offset + chosen beam
            flat_sel = layers.reshape(beam_idx, [-1])      # (N*b,)
            ones = layers.fill_constant_batch_size_like(
                flat_sel, [-1], "int64", 1)
            pos = layers.cumsum(ones, axis=0, exclusive=True)  # 0..N*b-1
            b_const = layers.fill_constant([1], "int64", b)
            row = layers.elementwise_mul(
                layers.elementwise_floordiv(pos, b_const), b_const)
            gather_idx = layers.elementwise_add(flat_sel, row)
            for name in cell._state_names:
                cell.set_state(name, layers.gather(cell.get_state(name),
                                                   gather_idx))
            # back-trace: beam j at step t+1 may descend from a different
            # beam at step t, so the recorded history must be re-gathered
            # along the winning beams too
            new_ids = layers.reshape(ids, [-1, 1])         # (N*b, 1)
            if hist is None:
                hist = new_ids
            else:
                hist = layers.concat(
                    [layers.gather(hist, gather_idx), new_ids], axis=1)
        trans_ids = layers.reshape(hist, [-1, b, self._max_len])
        self._outputs = (trans_ids, scores)
        self._state_cell._leave_decoder(self)

    def __call__(self):
        if self._outputs is None:
            raise ValueError('decode() must be called before the decoder '
                             'output is read.')
        return self._outputs
