"""Contrib seq2seq decoders
(ref python/paddle/fluid/contrib/decoder/__init__.py)."""
from .beam_search_decoder import *  # noqa: F401,F403
from . import beam_search_decoder

__all__ = beam_search_decoder.__all__
