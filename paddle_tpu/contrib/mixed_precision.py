"""Automatic mixed precision (AMP).

Reference parity: python/paddle/fluid/contrib/mixed_precision/
(decorate, AutoMixedPrecisionLists, static+dynamic loss scaling).

TPU-first: default compute dtype is bfloat16 — same exponent range as fp32,
so loss scaling is OFF by default (reference needs it for fp16 on V100).
The fp16 path with static/dynamic loss scaling is kept for parity.

Mechanics: a program pass rewrites the already-built forward — inputs of
white-list ops (matmul/conv/mul) are cast to the compute dtype, black-list
ops (softmax_with_cross_entropy, layer_norm stats, sums/means) stay fp32.
Parameters remain fp32 masters; XLA fuses/dedupes the inserted casts, so a
parameter is cast once per step regardless of fan-out.
"""
from ..framework.program import Operator
from ..framework import unique_name
from ..layer_helper import LayerHelper
from .. import layers

WHITE_LIST = {"mul", "matmul", "conv2d", "depthwise_conv2d",
              "conv2d_transpose", "conv3d", "scaled_dot_product_attention",
              "lstm_seq", "gru_seq"}
BLACK_LIST = {"softmax_with_cross_entropy", "cross_entropy", "layer_norm",
              "batch_norm", "group_norm", "instance_norm", "mean",
              "reduce_mean", "reduce_sum", "sum", "softmax", "log_softmax",
              "exp", "log", "square", "sqrt", "rsqrt",
              "sigmoid_cross_entropy_with_logits", "accuracy", "auc"}


class AutoMixedPrecisionLists(object):
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(WHITE_LIST) | set(custom_white_list or ())
        self.black_list = set(BLACK_LIST) | set(custom_black_list or ())


def _cast_program_io(block, loss_name, lists, dtype):
    """Insert casts so white-list ops run in `dtype`. Operates up to the
    loss producer; rebuilds the op list in one pass."""
    last = -1
    for i, op in enumerate(block.ops):
        if loss_name in op.output_names():
            last = i
    low_version = {}   # fp32 var name -> low-precision cast name
    new_ops = []

    def cast_to(name, target):
        var = block._find_var_recursive(name)
        if var is None or var.dtype not in ("float32",):
            return name
        key = (name, target)
        if key in low_version:
            return low_version[key]
        out = unique_name.generate(name + ".cast_" + target)
        block.create_var(name=out, shape=var.shape, dtype=target,
                         stop_gradient=var.stop_gradient)
        new_ops.append(Operator(
            block, "cast", {"X": [name]}, {"Out": [out]},
            {"in_dtype": "float32", "out_dtype": target,
             "op_role": "amp"}))
        low_version[key] = out
        return out

    produced_low = set()
    for i, op in enumerate(block.ops):
        if i > last >= 0:
            new_ops.append(op)
            continue
        if op.type in lists.white_list:
            op.inputs = {slot: [cast_to(n, dtype) for n in names]
                         for slot, names in op.inputs.items()}
            for n in op.output_names():
                v = block._find_var_recursive(n)
                if v is not None and v.dtype == "float32":
                    v.dtype = dtype
                    produced_low.add(n)
            new_ops.append(op)
        elif op.type in lists.black_list:
            # force fp32 inputs
            op.inputs = {slot: [cast_to(n, "float32")
                                if n in produced_low else n
                                for n in names]
                         for slot, names in op.inputs.items()}
            new_ops.append(op)
        else:
            new_ops.append(op)
    block.ops = new_ops
    block.program._version += 1


class OptimizerWithMixedPrecision(object):
    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio, dtype):
        self._optimizer = optimizer
        self._amp_lists = amp_lists
        self._init_loss_scaling = init_loss_scaling
        self._dynamic = use_dynamic_loss_scaling
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dtype = dtype
        self._loss_scaling = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        block = loss.block
        _cast_program_io(block, loss.name, self._amp_lists, self._dtype)
        # bf16 has fp32's exponent range: plain path, no scaling needed
        use_scaling = (self._dtype == "float16" or
                       self._init_loss_scaling != 1.0)
        if not use_scaling:
            return self._optimizer.minimize(loss, startup_program,
                                            parameter_list, no_grad_set)

        self._loss_scaling = layers.create_global_var(
            [1], self._init_loss_scaling, "float32", persistable=True,
            name=unique_name.generate("loss_scaling"))
        scaled_loss = layers.elementwise_mul(loss, self._loss_scaling)
        params_grads = self._optimizer.backward(
            scaled_loss, startup_program, parameter_list, no_grad_set)

        # check finiteness over all grads, unscale, zero on overflow
        finite_flags = [layers.isfinite(g) for _, g in params_grads]
        all_finite = finite_flags[0]
        for f in finite_flags[1:]:
            all_finite = layers.logical_and(all_finite, f)
        inv_scale = layers.elementwise_div(
            layers.fill_constant([1], "float32", 1.0), self._loss_scaling)
        new_pgs = []
        zero = layers.fill_constant([1], "float32", 0.0)
        for p, g in params_grads:
            g32 = layers.cast(g, "float32") if g.dtype != "float32" else g
            unscaled = layers.elementwise_mul(g32, inv_scale)
            safe = layers.where(all_finite, unscaled,
                                layers.zeros_like(unscaled))
            new_pgs.append((p, safe))

        if self._dynamic:
            self._append_dynamic_scale_update(all_finite)
        self._optimizer.apply_gradients(new_pgs)
        return [], new_pgs

    def _append_dynamic_scale_update(self, all_finite):
        """reference update_loss_scaling op: grow scale after N clean steps,
        shrink on overflow — in-graph counters, no host sync."""
        good = layers.create_global_var([1], 0.0, "float32",
                                        persistable=True,
                                        name=unique_name.generate(
                                            "good_steps"))
        one = layers.fill_constant([1], "float32", 1.0)
        good_next = layers.where(all_finite,
                                 layers.elementwise_add(good, one),
                                 layers.zeros_like(good))
        grow = layers.greater_equal(
            good_next, layers.fill_constant([1], "float32",
                                            float(self._incr_every)))
        scale_grown = layers.elementwise_mul(
            self._loss_scaling,
            layers.fill_constant([1], "float32", self._incr_ratio))
        scale_shrunk = layers.elementwise_mul(
            self._loss_scaling,
            layers.fill_constant([1], "float32", self._decr_ratio))
        new_scale = layers.where(
            all_finite,
            layers.where(grow, scale_grown, self._loss_scaling),
            scale_shrunk)
        good_final = layers.where(grow, layers.zeros_like(good_next),
                                  good_next)
        from ..layers import tensor as T
        T.assign(new_scale, self._loss_scaling)
        T.assign(good_final, good)


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=False, dtype="bfloat16"):
    """fluid.contrib.mixed_precision.decorate work-alike; dtype="bfloat16"
    (TPU default, no scaling) or "float16" (parity path with scaling)."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists or AutoMixedPrecisionLists(),
        init_loss_scaling, use_dynamic_loss_scaling, incr_every_n_steps,
        decr_every_n_nan_or_inf, incr_ratio, decr_ratio, dtype)
