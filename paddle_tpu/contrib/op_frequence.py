"""Op frequency statistics (ref python/paddle/fluid/contrib/op_frequence.py).

Counts single-op and adjacent-op-pair frequencies over a Program —
the reference used it to pick fusion candidates; here it doubles as a
quick check of what the XLA fuser will see.
"""
from collections import Counter, OrderedDict

from ..framework import program as program_mod

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Return (uni_op_freq, adj_2_op_freq) as frequency-sorted
    OrderedDicts (ref op_frequence.py:23)."""
    if not isinstance(program, program_mod.Program):
        raise TypeError("'program' should be an instance of Program.")

    uni_op_freq = Counter()
    adj_2_op_freq = Counter()
    for block in program.blocks:
        op_in_block = len(block.ops)
        for i, op in enumerate(block.ops):
            uni_op_freq[op.type] += 1
            if i < op_in_block - 1:
                adj_2_op_freq["%s->%s" % (op.type,
                                          block.ops[i + 1].type)] += 1

    uni = OrderedDict(sorted(uni_op_freq.items(),
                             key=lambda x: (-x[1], x[0])))
    adj = OrderedDict(sorted(adj_2_op_freq.items(),
                             key=lambda x: (-x[1], x[0])))
    return uni, adj
