"""Model PARAMs/FLOPs summary (ref python/paddle/fluid/contrib/model_stat.py).

``summary(main_prog)`` walks the Program IR and prints a per-layer
table of parameter counts and forward FLOPs for the common compute ops
(conv2d, fc/mul/matmul, pool2d, norm, activations).  Counting follows
the reference conventions (2x multiply-add for convs/fc); shapes come
straight from the Program's inferred var shapes, so it works on any
built model without running it.
"""
from collections import OrderedDict

__all__ = ["summary"]

_ACTS = ("sigmoid", "tanh", "relu", "leaky_relu", "prelu", "gelu", "swish")


def _numel(shape):
    n = 1
    for d in shape:
        n *= max(int(d), 1) if d != -1 else 1
    return n


def _var_shape(block, name):
    var = block._find_var_recursive(name) if hasattr(
        block, "_find_var_recursive") else block.var(name)
    return tuple(var.shape)


def _summary_op(block, op):
    """(in_shape, out_shape, params, flops) or None for non-compute ops."""
    t = op.type
    if t in ("conv2d", "depthwise_conv2d"):
        w = _var_shape(block, op.input("Filter")[0])
        ins = _var_shape(block, op.input("Input")[0])
        outs = _var_shape(block, op.output("Output")[0])
        c_out, c_in, k_h, k_w = w
        h_out, w_out = outs[-2], outs[-1]
        groups = op.attr("groups", 1) or 1
        kernel_ops = k_h * k_w * (c_in / groups)
        bias = 1 if op.input("Bias") else 0
        params = c_out * (kernel_ops + bias)
        flops = 2 * h_out * w_out * c_out * (kernel_ops + bias)
    elif t == "pool2d":
        ins = _var_shape(block, op.input("X")[0])
        outs = _var_shape(block, op.output("Out")[0])
        c_out, h_out, w_out = outs[-3], outs[-2], outs[-1]
        k = op.attr("ksize", [1, 1])
        params = 0
        flops = h_out * w_out * c_out * (k[0] * k[1])
    elif t in ("mul", "matmul"):
        w = _var_shape(block, op.input("Y")[0])
        ins = _var_shape(block, op.input("X")[0])
        outs = _var_shape(block, op.output("Out")[0])
        if len(w) != 2:
            return None
        k_in, k_out = w
        # bias lives in a separate elementwise op in this IR
        params = k_in * k_out
        flops = 2 * k_in * k_out * (_numel(ins) // max(k_in, 1))
    elif t == "elementwise_add":
        # fc/conv bias shows up as elementwise_add with a rank-1
        # Parameter operand — attribute it here so PARAMs stay complete
        yv = block._find_var_recursive(op.input("Y")[0]) if hasattr(
            block, "_find_var_recursive") else None
        if yv is None or not getattr(yv, "persistable", False) or \
                len(yv.shape or ()) != 1:
            return None
        ins = _var_shape(block, op.input("X")[0])
        outs = _var_shape(block, op.output("Out")[0])
        params = yv.shape[0]
        flops = _numel(outs)
    elif t in _ACTS:
        ins = _var_shape(block, op.input("X")[0])
        outs = _var_shape(block, op.output("Out")[0])
        params = 1 if t == "prelu" else 0
        flops = _numel(ins)
    elif t in ("batch_norm", "layer_norm", "group_norm", "instance_norm"):
        ins = _var_shape(block, op.input("X")[0])
        out_slot = "Y" if op.output("Y") else "Out"
        outs = _var_shape(block, op.output(out_slot)[0])
        c_in = ins[1] if len(ins) > 1 else ins[-1]
        params = c_in * 2
        flops = 2 * _numel(ins)
    else:
        return None
    return ins[1:], outs[1:], int(params), int(flops)


def summary(main_prog):
    """Print (and return) the layer table + totals (ref model_stat.py:40).

    Returns (rows, (total_params, total_flops)) so tests/tools can
    consume the numbers instead of scraping stdout.
    """
    collected = []
    for block in main_prog.blocks:
        for op in block.ops:
            res = _summary_op(block, op)
            if res is None:
                continue
            info = OrderedDict()
            info["type"] = op.type
            info["input_shape"] = res[0]
            info["out_shape"] = res[1]
            info["PARAMs"] = res[2]
            info["FLOPs"] = res[3]
            collected.append(info)
    total_params = sum(r["PARAMs"] for r in collected)
    total_flops = sum(r["FLOPs"] for r in collected)
    hdr = "%-4s %-12s %-20s %-20s %12s %14s" % (
        "No.", "TYPE", "INPUT", "OUTPUT", "PARAMs", "FLOPs")
    print(hdr)
    print("-" * len(hdr))
    for i, r in enumerate(collected):
        print("%-4d %-12s %-20s %-20s %12d %14d" % (
            i, r["type"], str(tuple(r["input_shape"])),
            str(tuple(r["out_shape"])), r["PARAMs"], r["FLOPs"]))
    print("Total PARAMs: %d (%.4fM)" % (total_params,
                                        total_params / 1e6))
    print("Total FLOPs: %d (%.2fG)" % (total_flops, total_flops / 1e9))
    return collected, (total_params, total_flops)
