"""Train-memory estimator (ref python/paddle/fluid/contrib/memory_usage_calc.py).

``memory_usage(program, batch_size)`` sums the byte size of every
variable in the Program (batch dim -1 resolved to ``batch_size``) and
returns a (low, high) estimate range in MB, mirroring the reference's
DEBUG tool.  On this framework the estimate maps to pre-XLA buffer
demand — actual HBM use is lower after XLA's liveness reuse and
donation, which is why a range is reported.
"""
from ..framework import program as program_mod
from ..framework.dtypes import dtype_size

__all__ = ["memory_usage"]

DEBUG = False

dtype_to_size = None  # kept for reference-API symmetry; see dtype_size


def memory_usage(program, batch_size):
    """Estimate the program's memory demand in MB (ref :46): returns
    (min_MB, max_MB)."""
    if not isinstance(program, program_mod.Program):
        raise TypeError(
            "Calculating Memory Usage requires Program as its Parameter. "
            "But you passed in %s" % type(program))
    if batch_size <= 0:
        raise ValueError("The batch size need to be positive.")

    total_memory = 0.0
    processed = set()
    for block in program.blocks:
        for var in block.vars.values():
            if var.name in processed or var.shape is None:
                continue
            processed.add(var.name)
            data_count = 1
            neg_dim_count = 0
            for x in var.shape:
                if x < 0:
                    if neg_dim_count >= 1:
                        raise ValueError(
                            "Var %s has more than one negative dim." %
                            var.name)
                    neg_dim_count = 1
                    data_count *= batch_size * (-x)
                else:
                    data_count *= x
            var_memory = data_count * dtype_size(var.dtype)
            if DEBUG:
                print("%s memory usage: %d" % (var.name, var_memory))
            total_memory += var_memory
    if DEBUG:
        print("total memory usage: %.2f" % total_memory)

    # the reference reports a +-30% band around the static sum; XLA's
    # reuse typically lands at or below the low end
    min_memory = total_memory * 0.7 / (1024 ** 2)
    max_memory = total_memory * 1.3 / (1024 ** 2)
    return min_memory, max_memory
