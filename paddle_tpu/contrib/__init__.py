"""contrib: mixed precision, quantization, extended optimizers.

Reference parity: python/paddle/fluid/contrib/*.
"""
from . import mixed_precision
from . import extend_optimizer
from . import quantize
from . import slim
from . import layers
from . import decoder
from . import trainer
from . import inferencer
from . import reader
from .reader import distributed_batch_reader
from .trainer import Trainer
from .inferencer import Inferencer
from . import model_stat
from . import memory_usage_calc
from . import op_frequence
from .memory_usage_calc import memory_usage
from .model_stat import summary
from .op_frequence import op_freq_statistic
