"""contrib: mixed precision, quantization, extended optimizers.

Reference parity: python/paddle/fluid/contrib/*.
"""
from . import mixed_precision
from . import extend_optimizer
from . import quantize
from . import slim
