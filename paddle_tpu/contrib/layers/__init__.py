"""Contrib layers (ref python/paddle/fluid/contrib/layers/__init__.py)."""
from .nn import *  # noqa: F401,F403
from .rnn_impl import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403

from . import nn
from . import rnn_impl
from . import metric_op

__all__ = nn.__all__ + rnn_impl.__all__ + metric_op.__all__
