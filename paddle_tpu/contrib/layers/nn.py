"""Contrib layers (ref python/paddle/fluid/contrib/layers/nn.py).

LoD-shaped contrib ops follow the package's dense+lengths convention:
where the reference takes ragged LoD tensors, these take padded tensors
plus explicit length vars (see layers/sequence_lod.py).  The one
reference entry intentionally absent is ``search_pyramid_hash`` — a
CPU-side xxhash sparse-feature trick with no MXU mapping; SURVEY
records the design decision.
"""
from ...layer_helper import LayerHelper
from ... import layers

__all__ = [
    "fused_elemwise_activation", "var_conv_2d", "match_matrix_tensor",
    "sequence_topk_avg_pooling", "tree_conv", "fused_embedding_seq_pool",
    "multiclass_nms2", "shuffle_batch",
]


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """Fused binary+unary compound (ref contrib nn.py:41).  The
    reference hand-fuses e.g. elementwise_add+relu into one CUDA
    kernel; XLA performs that fusion automatically, so this emits the
    composed ops and returns (out, intermediate) with identical
    semantics — the attr set is validated the same way."""
    if not isinstance(functor_list, (list, tuple)) or \
            len(functor_list) != 2:
        raise ValueError("functor_list should be a list of size 2")
    binary = {"elementwise_add", "elementwise_sub", "elementwise_mul"}
    unary = {"relu", "sigmoid", "tanh", "scale", "gelu"}

    def apply_one(name, a, b=None):
        if name in binary:
            return getattr(layers, name)(a, b, axis=axis)
        if name == "scale":
            return layers.scale(a, scale=scale)
        return getattr(layers, name)(a)

    f1, f2 = functor_list
    # fluid convention: functor_list[0] is the OUTER functor —
    # [binary, unary] => binary(x, unary(y)); [unary, binary] =>
    # unary(binary(x, y)) (ref fused_elemwise_activation_op.h
    # BinaryCompound/UnaryCompound)
    if f1 in binary and f2 in unary:
        intermediate = apply_one(f2, y)
        out = apply_one(f1, x, intermediate)
    elif f1 in unary and f2 in binary:
        intermediate = apply_one(f2, x, y)
        out = apply_one(f1, intermediate)
    else:
        raise ValueError("functor_list must pair one binary elementwise "
                         "op with one unary activation, got %r" %
                         (functor_list,))
    return (out, intermediate) if save_intermediate_out else out


def var_conv_2d(input, row, col, input_channel, output_channel,
                filter_size, stride=1, param_attr=None, act=None,
                dtype="float32", name=None):
    """Variable-size 2-D conv (ref contrib nn.py:105).  input:
    (N, C_in, H_max, W_max) padded; row/col: (N,) valid extents
    (replacing the reference's row/col LoD inputs)."""
    helper = LayerHelper("var_conv_2d", param_attr=param_attr, name=name,
                         act=act, dtype=dtype)
    fs = filter_size if isinstance(filter_size, (list, tuple)) else \
        [filter_size, filter_size]
    st = stride if isinstance(stride, (list, tuple)) else [stride, stride]
    w = helper.create_parameter(
        helper.param_attr,
        shape=[output_channel, input_channel, fs[0], fs[1]], dtype=dtype)
    n, h, wd = input.shape[0], input.shape[2], input.shape[3]
    out = helper.create_variable_for_type_inference(
        dtype, (n, output_channel, (h + st[0] - 1) // st[0],
                (wd + st[1] - 1) // st[1]))
    helper.append_op(
        "var_conv_2d",
        inputs={"X": [input.name], "W": [w.name], "RowLen": [row.name],
                "ColLen": [col.name]},
        outputs={"Out": [out.name]},
        attrs={"stride": list(st)})
    return helper.append_activation(out)


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None):
    """Bilinear semantic match matrix (ref contrib nn.py:221).
    x: (N, Tx, D1), y: (N, Ty, D2) dense -> (N, channel_num, Tx, Ty)."""
    helper = LayerHelper("match_matrix_tensor", param_attr=param_attr,
                         act=act, name=name, dtype=dtype)
    d1, d2 = x.shape[-1], y.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[d1, channel_num, d2], dtype=dtype)
    out = helper.create_variable_for_type_inference(
        dtype, (x.shape[0], channel_num, x.shape[1], y.shape[1]))
    helper.append_op(
        "match_matrix_tensor",
        inputs={"X": [x.name], "Y": [y.name], "W": [w.name]},
        outputs={"Out": [out.name]},
        attrs={"dim_t": channel_num})
    return helper.append_activation(out), w


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """Top-k column-average pooling of a match matrix
    (ref contrib nn.py:304).  input: (N, C, Tx, Ty); row/col: (N,)
    lengths -> (N, Tx, C * len(topks))."""
    helper = LayerHelper("sequence_topk_avg_pooling", input=input)
    n, c, tx = input.shape[0], input.shape[1], input.shape[2]
    out = helper.create_variable_for_type_inference(
        input.dtype, (n, tx, c * len(topks)))
    helper.append_op(
        "sequence_topk_avg_pooling",
        inputs={"X": [input.name], "RowLen": [row.name],
                "ColLen": [col.name]},
        outputs={"Out": [out.name]},
        attrs={"topks": list(topks), "channel_num": channel_num})
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution (ref contrib nn.py:372).  nodes_vector:
    (N, M, F); edge_set: (N, E, 2) [parent, child], negative-padded.
    Returns (N, M, output_size, num_filters)."""
    helper = LayerHelper("tree_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = nodes_vector.dtype
    f = nodes_vector.shape[-1]
    w = helper.create_parameter(
        helper.param_attr, shape=[f, 3, output_size, num_filters],
        dtype=dtype)
    n, m = nodes_vector.shape[0], nodes_vector.shape[1]
    out = helper.create_variable_for_type_inference(
        dtype, (n, m, output_size, num_filters))
    helper.append_op(
        "tree_conv",
        inputs={"NodesVector": [nodes_vector.name],
                "EdgeSet": [edge_set.name], "Filter": [w.name]},
        outputs={"Out": [out.name]},
        attrs={"max_depth": max_depth})
    if helper.bias_attr:
        out = helper.append_bias_op(out, dim_start=2, dim_end=3)
    return helper.append_activation(out)


def fused_embedding_seq_pool(input, size, is_sparse=False,
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32"):
    """Embedding lookup + sequence pool in one go (ref contrib
    nn.py:437).  input: (N, T) or (N, T, 1) ids -> (N, D).  The
    reference fuses to skip materializing (N*T, D); XLA achieves the
    same fusion from the composed graph, so this emits
    embedding(+masked padding) then sequence_pool."""
    if combiner not in ("sum", "average", "max"):
        raise ValueError("unsupported combiner %r" % combiner)
    emb = layers.embedding(input, size=size, is_sparse=is_sparse,
                           padding_idx=padding_idx, param_attr=param_attr,
                           dtype=dtype)
    pool_type = {"average": "average"}.get(combiner, combiner)
    return layers.sequence_pool(emb, pool_type)


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    """multiclass_nms variant that can also return kept-box indices
    (ref contrib nn.py:503) — delegates to the detection layer, which
    already computes Index."""
    return layers.multiclass_nms(
        bboxes, scores, score_threshold, nms_top_k, keep_top_k,
        nms_threshold=nms_threshold, normalized=normalized,
        nms_eta=nms_eta, background_label=background_label,
        return_index=return_index, name=name)


def shuffle_batch(x, seed=None):
    """Random whole-row shuffle (ref contrib nn.py:729); permutation is
    drawn from the deterministic per-op PRNG stream unless a seed attr
    pins it."""
    helper = LayerHelper("shuffle_batch", input=x)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    idx = helper.create_variable_for_type_inference("int64",
                                                    (x.shape[0],))
    helper.append_op(
        "shuffle_batch",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "ShuffleIdx": [idx.name]},
        # -1 = unseeded; seed=0 is a legal pinned seed
        attrs={"startup_seed": -1 if seed is None else int(seed)})
    return out
