"""Contrib metric ops (ref python/paddle/fluid/contrib/layers/metric_op.py).

``ctr_metric_bundle`` emits the same six CTR monitoring aggregates the
reference computes with specialized ops; here they are ordinary graph
ops fused by XLA into the step.
"""
from ... import layers

__all__ = ['ctr_metric_bundle']


def ctr_metric_bundle(input, label):
    """For click-probability ``input`` and 0/1 ``label`` (both (N, 1)):
    returns (squared_error_sum, abs_error_sum, prob_sum, q_sum(=prob_sum
    of positive calibration), pos_count, total_count) — the running
    numerators a CTR dashboard aggregates across batches
    (ref metric_op.py:30)."""
    diff = layers.elementwise_sub(input, layers.cast(label, input.dtype))
    sqrerr = layers.reduce_sum(layers.square(diff))
    abserr = layers.reduce_sum(layers.abs(diff))
    prob = layers.reduce_sum(input)
    q = layers.reduce_sum(layers.elementwise_mul(input, input))
    pos = layers.reduce_sum(layers.cast(label, input.dtype))
    # runtime row count — static shape may be -1 (dynamic batch) and the
    # final partial batch differs from the graph-time shape anyway
    total = layers.reduce_sum(layers.fill_constant_batch_size_like(
        input, shape=[-1, 1], dtype=input.dtype, value=1.0))
    return sqrerr, abserr, prob, q, pos, total
