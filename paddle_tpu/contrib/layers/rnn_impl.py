"""Multi-layer/bidirectional RNN compositions
(ref python/paddle/fluid/contrib/layers/rnn_impl.py).

The reference builds these from per-step basic ops inside a StaticRNN;
here each direction/layer is one ``lstm_seq``/``gru_seq`` op — a single
lax.scan over time in the traced step, which XLA unrolls onto the MXU
far better than op-per-timestep graphs.  Padding is handled the
dense+lengths way: when ``sequence_length`` is given, post-step states
are masked so each sequence's last *valid* state propagates (identical
to the reference's mask/tril trick).

Returns match the reference: basic_gru -> (rnn_out, last_hidden);
basic_lstm -> (rnn_out, last_hidden, last_cell); last states have shape
(num_layers * num_directions, batch, hidden).
"""
from ... import layers
from ...dygraph.layers import Layer
from ...dygraph.nn import run_op, apply_eager

__all__ = ['BasicGRUUnit', 'basic_gru', 'BasicLSTMUnit', 'basic_lstm']


class BasicGRUUnit(Layer):
    """Single-step GRU cell for dygraph (ref rnn_impl.py:22).
    forward(input (N, D), pre_hidden (N, H)) -> new_hidden."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype='float32'):
        super(BasicGRUUnit, self).__init__(dtype=dtype)
        self._hidden_size = hidden_size
        self._gate_act = gate_activation or "sigmoid"
        self._act = activation or "tanh"
        self._dtype = dtype
        self._built = False

    def _build_once(self, input):
        d = input.shape()[-1] if callable(getattr(input, "shape", None)) \
            else input.shape[-1]
        h = self._hidden_size
        self._gate_weight = self.add_parameter(
            "gate_weight", self.create_parameter([d + h, 2 * h]))
        self._candidate_weight = self.add_parameter(
            "candidate_weight", self.create_parameter([d + h, h]))
        self._gate_bias = self.add_parameter(
            "gate_bias", self.create_parameter([2 * h], is_bias=True))
        self._candidate_bias = self.add_parameter(
            "candidate_bias", self.create_parameter([h], is_bias=True))
        self._built = True

    def forward(self, input, pre_hidden):
        import jax.numpy as jnp
        if not self._built:
            self._build_once(input)
        h = self._hidden_size

        def step(x, hp, gw, gb, cw, cb):
            concat = jnp.concatenate([x, hp], axis=-1)
            gates = jnp.matmul(concat, gw) + gb
            if self._gate_act == "sigmoid":
                gates = 1.0 / (1.0 + jnp.exp(-gates))
            else:
                gates = jnp.tanh(gates)
            u, r = gates[..., :h], gates[..., h:]
            cand_in = jnp.concatenate([x, r * hp], axis=-1)
            c = jnp.matmul(cand_in, cw) + cb
            c = jnp.tanh(c) if self._act == "tanh" else \
                1.0 / (1.0 + jnp.exp(-c))
            return u * hp + (1.0 - u) * c

        return apply_eager(step, input, pre_hidden, self._gate_weight,
                           self._gate_bias, self._candidate_weight,
                           self._candidate_bias)


class BasicLSTMUnit(Layer):
    """Single-step LSTM cell for dygraph (ref rnn_impl.py:632).
    forward(input, pre_hidden, pre_cell) -> (new_hidden, new_cell)."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype='float32'):
        super(BasicLSTMUnit, self).__init__(dtype=dtype)
        self._hidden_size = hidden_size
        self._forget_bias = forget_bias
        self._built = False

    def _build_once(self, input):
        d = input.shape()[-1] if callable(getattr(input, "shape", None)) \
            else input.shape[-1]
        h = self._hidden_size
        self._weight = self.add_parameter(
            "weight", self.create_parameter([d + h, 4 * h]))
        self._bias = self.add_parameter(
            "bias", self.create_parameter([4 * h], is_bias=True))
        self._built = True

    def forward(self, input, pre_hidden, pre_cell):
        import jax.numpy as jnp
        if not self._built:
            self._build_once(input)
        h = self._hidden_size
        fb = self._forget_bias

        def step(x, hp, cp, w, b):
            gates = jnp.matmul(jnp.concatenate([x, hp], axis=-1), w) + b
            i, f, c, o = (gates[..., :h], gates[..., h:2 * h],
                          gates[..., 2 * h:3 * h], gates[..., 3 * h:])
            sig = lambda v: 1.0 / (1.0 + jnp.exp(-v))
            new_c = cp * sig(f + fb) + sig(i) * jnp.tanh(c)
            new_h = jnp.tanh(new_c) * sig(o)
            return new_h, new_c

        return apply_eager(step, input, pre_hidden, pre_cell,
                           self._weight, self._bias)


def _slice_init(init, idx, batch, hidden):
    """init: (L*Dirs, N, H) -> (N, H) slice for layer/direction idx."""
    if init is None:
        return None
    s = layers.slice(init, axes=[0], starts=[idx], ends=[idx + 1])
    return layers.reshape(s, [batch, hidden])


def _gather_steps(seq_out, idx):
    # one_hot over time then weighted sum — static-shape gather
    t = seq_out.shape[1]
    oh = layers.one_hot(layers.unsqueeze(idx, axes=[1]), t)  # (N,1,T)? ->
    oh = layers.reshape(oh, [seq_out.shape[0], 1, t])
    out = layers.matmul(oh, seq_out)  # (N, 1, H)
    return out


def _one_direction(x, init_h, init_c, hidden_size, is_reverse, cell_type,
                   param_attr, bias_attr, dtype, sequence_length):
    """x: (N, T, D) -> (out (N, T, H), last_h, last_c|None).

    Padded reverse direction: a plain is_reverse scan would consume the
    PAD tail before the valid steps, contaminating every state.  With
    sequence_length we instead reverse each VALID prefix
    (sequence_reverse), run a forward scan, and un-reverse the outputs
    — fluid's semantics, built on the length-aware reverse kernel."""
    from ...layers.sequence_lod import sequence_reverse
    length_aware_reverse = is_reverse and sequence_length is not None
    if length_aware_reverse:
        x = sequence_reverse(x, lengths=sequence_length)
        is_reverse = False
    if cell_type == "gru":
        proj = layers.fc(x, size=3 * hidden_size, num_flatten_dims=2,
                         param_attr=param_attr, bias_attr=False)
        out = layers.dynamic_gru(proj, hidden_size, param_attr=param_attr,
                                 bias_attr=bias_attr,
                                 is_reverse=is_reverse, h_0=init_h,
                                 dtype=dtype)
        cell_seq = None
    else:
        proj = layers.fc(x, size=4 * hidden_size, num_flatten_dims=2,
                         param_attr=param_attr, bias_attr=False)
        out, cell_seq = layers.dynamic_lstm(
            proj, 4 * hidden_size, h_0=init_h, c_0=init_c,
            param_attr=param_attr, bias_attr=bias_attr,
            is_reverse=is_reverse, dtype=dtype)
    if sequence_length is not None:
        # zero padded steps so downstream pooling ignores them
        mask = layers.cast(
            layers.sequence_mask(sequence_length, maxlen=x.shape[1]),
            dtype)
        mask3 = layers.unsqueeze(mask, axes=[2])
        out = layers.elementwise_mul(out, mask3)
        if cell_seq is not None:
            cell_seq = layers.elementwise_mul(cell_seq, mask3)
    if is_reverse:
        # last valid state of a full-length reversed scan is step 0
        last_h = layers.squeeze(
            layers.slice(out, axes=[1], starts=[0], ends=[1]), axes=[1])
        last_c = None if cell_seq is None else layers.squeeze(
            layers.slice(cell_seq, axes=[1], starts=[0], ends=[1]),
            axes=[1])
    elif sequence_length is not None:
        # covers the length-aware reverse too: the scan ran forward over
        # the prefix-reversed input, so its len-1 step IS the reverse
        # direction's final state
        last_h = layers.squeeze(_gather_steps(
            out, _len_minus_one(sequence_length)), axes=[1])
        last_c = None if cell_seq is None else layers.squeeze(
            _gather_steps(cell_seq, _len_minus_one(sequence_length)),
            axes=[1])
    else:
        t = x.shape[1]
        last_h = layers.squeeze(
            layers.slice(out, axes=[1], starts=[t - 1], ends=[t]),
            axes=[1])
        last_c = None if cell_seq is None else layers.squeeze(
            layers.slice(cell_seq, axes=[1], starts=[t - 1], ends=[t]),
            axes=[1])
    if length_aware_reverse:
        # put per-step outputs back in original time order
        out = sequence_reverse(out, lengths=sequence_length)
        if cell_seq is not None:
            cell_seq = sequence_reverse(cell_seq, lengths=sequence_length)
    return out, last_h, last_c


def _len_minus_one(sequence_length):
    lengths = layers.cast(sequence_length, "int64")
    return layers.elementwise_sub(
        lengths, layers.fill_constant([1], "int64", 1))


def _basic_rnn(cell_type, input, init_hidden, init_cell, hidden_size,
               num_layers, sequence_length, dropout_prob, bidirectional,
               batch_first, param_attr, bias_attr, dtype):
    if not batch_first:
        input = layers.transpose(input, perm=[1, 0, 2])
    batch = input.shape[0]
    dirs = 2 if bidirectional else 1
    x = input
    last_hs, last_cs = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            ih = _slice_init(init_hidden, idx, batch, hidden_size)
            ic = _slice_init(init_cell, idx, batch, hidden_size)
            out, lh, lc = _one_direction(
                x, ih, ic, hidden_size, is_reverse=(d == 1),
                cell_type=cell_type, param_attr=param_attr,
                bias_attr=bias_attr, dtype=dtype,
                sequence_length=sequence_length)
            outs.append(out)
            last_hs.append(lh)
            if lc is not None:
                last_cs.append(lc)
        x = outs[0] if dirs == 1 else layers.concat(outs, axis=2)
        if dropout_prob > 0.0 and layer < num_layers - 1:
            x = layers.dropout(x, dropout_prob=dropout_prob)
    rnn_out = x if batch_first else layers.transpose(x, perm=[1, 0, 2])
    last_hidden = layers.stack(last_hs, axis=0)
    last_cell = layers.stack(last_cs, axis=0) if last_cs else None
    return rnn_out, last_hidden, last_cell


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype='float32',
              name='basic_gru'):
    """Multi-layer (bi)directional GRU (ref rnn_impl.py:139) ->
    (rnn_out, last_hidden)."""
    out, last_h, _ = _basic_rnn(
        "gru", input, init_hidden, None, hidden_size, num_layers,
        sequence_length, dropout_prob, bidirectional, batch_first,
        param_attr, bias_attr, dtype)
    return out, last_h


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0, bidirectional=False,
               batch_first=True, param_attr=None, bias_attr=None,
               gate_activation=None, activation=None, forget_bias=1.0,
               dtype='float32', name='basic_lstm'):
    """Multi-layer (bi)directional LSTM (ref rnn_impl.py:358) ->
    (rnn_out, last_hidden, last_cell)."""
    out, last_h, last_c = _basic_rnn(
        "lstm", input, init_hidden, init_cell, hidden_size, num_layers,
        sequence_length, dropout_prob, bidirectional, batch_first,
        param_attr, bias_attr, dtype)
    return out, last_h, last_c
