"""High-level Inferencer API (ref python/paddle/fluid/contrib/inferencer.py).

Wraps a saved-params directory + an inference-program builder into a
callable: ``Inferencer(infer_func, param_path).infer({name: array})``.
The jit compile cache inside Executor makes repeated infer() calls
cheap, which is the reference's AnalysisPredictor-lite behavior.
"""
import os

import numpy as np

from ..framework.program import Program, program_guard
from ..framework.scope import Scope, scope_guard
from ..framework.executor import Executor
from .. import io as io_mod

__all__ = ['Inferencer']


class Inferencer(object):
    """infer_func() builds the inference graph and returns its output
    var(s); params load from ``param_path`` (a save_params /
    save_persistables directory) (ref :31)."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.param_path = param_path
        self.scope = Scope()
        self.startup_program = Program()
        self.inference_program = Program()
        with program_guard(self.inference_program, self.startup_program):
            outs = infer_func()
            self.predict_vars = list(outs) if isinstance(
                outs, (list, tuple)) else [outs]
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            if param_path and os.path.isdir(param_path):
                io_mod.load_persistables(self.exe, param_path,
                                         self.inference_program)
            elif param_path:
                raise ValueError(
                    "param_path %s is not a directory of saved params" %
                    param_path)

    def infer(self, inputs, return_numpy=True):
        """inputs: {feed_name: ndarray} -> list of outputs (ref :80)."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with scope_guard(self.scope):
            results = self.exe.run(self.inference_program, feed=inputs,
                                   fetch_list=self.predict_vars,
                                   return_numpy=False)
        if return_numpy:
            results = [np.asarray(r) for r in results]
        return results
