"""Extended optimizers: gradient merge (accumulation) + pipeline.

Reference parity: fluid optimizer.py GradientMergeOptimizer /
PipelineOptimizer (+ contrib/extend_optimizer). TPU-native notes:
- GradientMerge: accumulate grads in persistable buffers, apply every k
  steps via an on-device where-select on a step counter (no host branch —
  everything stays inside the single jitted step).
- Pipeline: on TPU, pipeline parallelism is expressed as a mesh "pp" axis
  with stage-sharded weights; this wrapper annotates stage shardings. The
  microbatched GPipe / 1F1B schedules live in distributed/pipeline.py.
"""
from ..framework.program import default_main_program
from ..framework import unique_name
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper
from .. import layers


class GradientMergeOptimizer(object):
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = k_steps
        self.avg = avg

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        inner = self.inner_optimizer
        params_grads = inner.backward(loss, startup_program,
                                      parameter_list, no_grad_set)
        if self.k_steps == 1:
            inner.apply_gradients(params_grads)
            return [], params_grads

        helper = LayerHelper("gradient_merge")
        step = layers.autoincreased_step_counter(
            counter_name="@GRAD_MERGE_STEP@", begin=1)
        stepf = layers.cast(step, "float32")
        k = layers.fill_constant([1], "float32", float(self.k_steps))
        rem = layers.elementwise_sub(
            stepf,
            layers.elementwise_mul(
                layers.floor(layers.elementwise_div(stepf, k)), k))
        is_apply = layers.equal(rem, 0.0)

        merged = []
        for p, g in params_grads:
            acc = helper.create_global_variable(
                name=unique_name.generate(p.name + ".grad_acc"),
                dtype="float32", shape=p.shape, persistable=True)
            helper.set_variable_initializer(acc, ConstantInitializer(0.0))
            acc_new = layers.elementwise_add(acc, g)
            scale = 1.0 / self.k_steps if self.avg else 1.0
            apply_grad = layers.scale(acc_new, scale=scale)
            # zero the buffer on apply steps, keep accumulating otherwise
            from ..layers import tensor as T
            T.assign(layers.where(is_apply, layers.zeros_like(acc_new),
                                  acc_new), acc)
            merged.append((p, apply_grad, acc_new))

        # gate the actual update: on non-apply steps feed zero grads
        gated = []
        for p, apply_grad, _ in merged:
            gated.append((p, layers.where(
                is_apply, apply_grad, layers.zeros_like(apply_grad))))
        inner.apply_gradients(gated)
        return [], [(p, g) for p, g, _ in merged]


class PipelineOptimizer(object):
    def __init__(self, inner_optimizer, num_stages=2, num_microbatches=1,
                 stage_axis="pp"):
        self.inner_optimizer = inner_optimizer
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.stage_axis = stage_axis

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        params = program.all_parameters()
        # annotate contiguous parameter groups to pipeline stages; XLA's
        # SPMD partitioner places each stage's weights on its pp slice
        per_stage = max(1, len(params) // self.num_stages)
        for i, p in enumerate(params):
            stage = min(i // per_stage, self.num_stages - 1)
            p.pipeline_stage = stage
        return self.inner_optimizer.minimize(loss, startup_program,
                                             parameter_list, no_grad_set)
