"""High-level Trainer API (ref python/paddle/fluid/contrib/trainer.py).

The reference's deprecated-but-still-shipped book API: a Trainer wraps
program construction (train_func returns loss), optimizer creation,
the epoch/step event loop, and checkpointing; an Inferencer (see
inferencer.py) wraps a saved model.  Faithful surface on top of
Executor/Scope — the event objects and handler contract match the book
chapters, so those scripts port unchanged.
"""
import os

import numpy as np

from ..framework.program import Program, program_guard
from ..framework.scope import Scope, scope_guard
from ..framework.executor import Executor
from .. import io as io_mod
from ..data_feeder import DataFeeder

__all__ = ['BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
           'EndStepEvent', 'CheckpointConfig', 'Trainer']


class BeginEpochEvent(object):
    """Fires at each epoch start (ref :40)."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    """Fires at each epoch end (ref :52)."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    """Fires before each step (ref :64); set fetch_metrics=False to
    skip metric fetching for speed."""

    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent(object):
    """Fires after each step with the fetched metrics (ref :83)."""

    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig(object):
    """Periodic checkpoint policy (ref :100)."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            ".", "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(int(epoch_interval), 1)
        self.step_interval = max(int(step_interval), 1)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None


class Trainer(object):
    """Build-and-train driver (ref :169).

    train_func() must return [loss] (or [loss, *metrics]);
    optimizer_func() returns an Optimizer.  Feeds come from a fluid
    reader (batches of per-slot tuples) through DataFeeder using
    ``feed_order`` names.
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self._place = place
        self._parallel = parallel
        self._checkpoint_cfg = checkpoint_config
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        with program_guard(self.train_program, self.startup_program):
            outs = train_func()
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            self.train_func_outputs = list(outs)
            self.loss = outs[0]
            optimizer = optimizer_func()
            optimizer.minimize(self.loss)
        # evaluation must not run the appended optimizer update ops —
        # test() uses the pruned inference clone of the same graph
        self.test_program = self.train_program.clone(for_test=True)
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            if param_path and os.path.isdir(param_path):
                io_mod.load_persistables(self.exe, param_path,
                                         self.train_program)
            cfg = self._checkpoint_cfg
            if cfg and os.path.exists(os.path.join(cfg.checkpoint_dir,
                                                   "latest")):
                # crash-resume: restore the newest checkpoint's state
                cfg.load_serial = io_mod.load_checkpoint(
                    self.exe, cfg.checkpoint_dir, self.train_program)

    def stop(self):
        self.__stop = True

    def train(self, num_epochs, event_handler, reader=None,
              feed_order=None):
        """The reference event loop (ref :379): BeginEpoch ->
        (BeginStep -> run -> EndStep)* -> EndEpoch, checkpointing per
        CheckpointConfig; event_handler may call trainer.stop()."""
        self.__stop = False
        feeder = self._make_feeder(feed_order)
        with scope_guard(self.scope):
            for epoch_id in range(num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self.__stop:
                        return
                    begin_event = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin_event)
                    fetch = self.train_func_outputs \
                        if begin_event.fetch_metrics else []
                    metrics = self.exe.run(
                        self.train_program,
                        feed=feeder.feed(data) if feeder else data,
                        fetch_list=fetch)
                    event_handler(EndStepEvent(epoch_id, step_id,
                                               metrics))
                    if self._checkpoint_cfg and \
                            (step_id + 1) % \
                            self._checkpoint_cfg.step_interval == 0:
                        self._save_checkpoint(epoch_id, step_id)
                event_handler(EndEpochEvent(epoch_id))
                if self._checkpoint_cfg and \
                        (epoch_id + 1) % \
                        self._checkpoint_cfg.epoch_interval == 0:
                    self._save_checkpoint(epoch_id, -1)

    def _make_feeder(self, feed_order):
        if not feed_order:
            return None
        blk = self.train_program.global_block()
        feed_vars = [blk.var(n) if isinstance(n, str) else n
                     for n in feed_order]
        return DataFeeder(feed_list=feed_vars, program=self.train_program)

    def test(self, reader, feed_order):
        """Mean metrics over a test reader (ref :407) — on the for_test
        clone, so no optimizer update ops run on test data."""
        feeder = self._make_feeder(feed_order)
        totals = None
        count = 0
        with scope_guard(self.scope):
            for data in reader():
                outs = self.exe.run(self.test_program,
                                    feed=feeder.feed(data),
                                    fetch_list=self.train_func_outputs)
                vals = [float(np.asarray(o).reshape(-1)[0]) for o in outs]
                totals = vals if totals is None else \
                    [t + v for t, v in zip(totals, vals)]
                count += 1
        return [t / max(count, 1) for t in (totals or [])]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            io_mod.save_persistables(self.exe, param_path,
                                     self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        with scope_guard(self.scope):
            io_mod.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                self.exe, main_program=self.train_program)

    def _save_checkpoint(self, epoch_id, step_id):
        cfg = self._checkpoint_cfg
        io_mod.save_checkpoint(
            self.exe, cfg.checkpoint_dir, self.train_program,
            step=epoch_id * 1000000 + max(step_id, 0),
            keep_last=cfg.max_num_checkpoints)
