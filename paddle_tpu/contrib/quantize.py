"""Post-training quantization (simulated int8).

Reference parity: fluid/contrib/quantize + slim quantization passes —
the subset that matters for inference: per-tensor abs-max int8 weight
quantization with dequant-at-load, keeping XLA as the int8->bf16 engine.
"""
import numpy as np


def quantize_weights_abs_max(arrays, bits=8):
    """arrays: {name: np.ndarray fp32} -> ({name: int8 array},
    {name: scale}). Symmetric per-tensor abs-max."""
    qmax = 2 ** (bits - 1) - 1
    q, scales = {}, {}
    for name, arr in arrays.items():
        a = np.asarray(arr, np.float32)
        s = float(np.max(np.abs(a))) / qmax if a.size else 1.0
        s = s if s > 0 else 1.0
        q[name] = np.clip(np.round(a / s), -qmax - 1, qmax).astype(np.int8)
        scales[name] = s
    return q, scales


def dequantize_weights(q, scales):
    return {name: q[name].astype(np.float32) * scales[name] for name in q}


def save_quantized_inference_model(dirname, feeded_var_names, target_vars,
                                   executor, main_program=None, bits=8):
    """save_inference_model variant storing int8 weights + scales."""
    import os
    import json
    from ..io import (save_inference_model, _collect, _atomic_savez,
                      PARAMS_FILE)
    from ..framework.scope import global_scope
    from ..framework.program import Parameter, default_main_program
    program = main_program or default_main_program()
    save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=program, program_only=True)
    arrays = _collect(program, global_scope(),
                      lambda v: isinstance(v, Parameter))
    others = _collect(program, global_scope(),
                      lambda v: v.persistable and
                      not isinstance(v, Parameter))
    q, scales = quantize_weights_abs_max(arrays, bits)
    blob = dict(others)
    for name in q:
        blob[name + ".int8"] = q[name]
    _atomic_savez(dirname, PARAMS_FILE, blob)
    with open(os.path.join(dirname, "quant_scales.json"), "w") as f:
        json.dump(scales, f)


def load_quantized_inference_model(dirname, executor):
    import os
    import json
    import jax.numpy as jnp
    from ..io import _load_arrays, MODEL_FILE
    from ..framework.program import Program
    from ..framework.scope import global_scope
    with open(os.path.join(dirname, MODEL_FILE)) as f:
        meta = json.load(f)
    with open(os.path.join(dirname, "quant_scales.json")) as f:
        scales = json.load(f)
    arrays = _load_arrays(dirname, None)
    scope = global_scope()
    for name, arr in arrays.items():
        if name.endswith(".int8"):
            base = name[:-5]
            scope.set_var(base, jnp.asarray(
                arr.astype(np.float32) * scales[base]))
        else:
            scope.set_var(name, jnp.asarray(arr))
    program = Program.from_dict(meta["program"])
    return program, meta["feed_var_names"], meta["fetch_var_names"]
