"""Lookup-table checkpoint conversion (ref contrib/utils/
lookup_table_utils.py): the reference converted pserver-distributed
lookup-table checkpoints into inference programs. TPU sparse tables are
row-sharded mesh arrays checkpointed by io.save_checkpoint, so the
conversion collapses to ordinary save/load — these entry points keep
the names and point at the working path."""

__all__ = ["convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference"]

_GUIDANCE = (
    "pserver lookup-table checkpoints do not exist on paddle_tpu: "
    "distributed embeddings are row-sharded mesh arrays "
    "(distributed/sharded_embedding.py) saved by io.save_checkpoint / "
    "io.save_persistables; load them with io.load_checkpoint / "
    "io.load_persistables and export with io.save_inference_model")


def convert_dist_to_sparse_program(program):
    """The dense->sparse program rewrite is unnecessary here: embedding
    with is_distributed=True already row-shards over the mesh."""
    return program


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var=None,
                                    lookup_table_var_path=None):
    from ... import io
    io.load_persistables(executor, dirname, main_program=program)


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name=None):
    from ... import io
    io.load_persistables(executor, dirname, main_program=program)
