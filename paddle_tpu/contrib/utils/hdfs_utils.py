"""fluid.contrib.utils.hdfs_utils (ref contrib/utils/hdfs_utils.py:35).

The reference shells out to a Hadoop CLI for distributed-FS staging.
Zero-egress TPU pods stage checkpoints/data via mounted storage (any
POSIX-visible path works with save/load as-is — see PORTING.md
"Capability substitutions"), so these raise with that guidance rather
than half-working.
"""

__all__ = ["HDFSClient", "multi_download", "multi_upload"]

_MSG = ("HDFS staging is N/A in paddle_tpu: TPU pods mount storage as a "
        "POSIX path — point save/load/Dataset APIs at that path directly "
        "(PORTING.md 'Capability substitutions').")


class HDFSClient(object):
    def __init__(self, hadoop_home=None, configs=None):
        raise NotImplementedError(_MSG)


def multi_download(*args, **kwargs):
    raise NotImplementedError(_MSG)


def multi_upload(*args, **kwargs):
    raise NotImplementedError(_MSG)
