"""fluid.contrib.utils parity (ref contrib/utils/: hdfs_utils +
lookup_table_utils)."""
from . import hdfs_utils  # noqa: F401
from .hdfs_utils import HDFSClient, multi_download, multi_upload  # noqa: F401

__all__ = ["HDFSClient", "multi_download", "multi_upload"]
