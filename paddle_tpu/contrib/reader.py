"""Multi-process reader decoration
(ref python/paddle/fluid/contrib/reader/distributed_reader.py).

Round-robin batch sharding for data-parallel trainers driven by the
PADDLE_TRAINER env contract (distributed/launch.py sets it): trainer i
of n consumes every n-th batch.  On TPU this pairs with the host-local
feed path (CompiledProgram assembles global arrays from per-process
shards), giving each host distinct data without a central dispatcher.
"""
import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    """Shard a batch reader across PADDLE_TRAINERS_NUM processes
    (ref :21): trainer ``i`` yields batches ``i, i+n, i+2n, ...``."""
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    assert trainer_id < trainers_num, \
        "PADDLE_TRAINER_ID %d out of range for %d trainers" % (
            trainer_id, trainers_num)

    def decorated():
        for batch_id, data in enumerate(batch_reader()):
            if batch_id % trainers_num == trainer_id:
                yield data

    return decorated
