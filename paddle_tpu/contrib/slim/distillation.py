"""Module-path alias for slim.distillation (ref
contrib/slim/distillation/); kernels live in distill.py."""
from .distill import *  # noqa: F401,F403
from . import distill as _d

__all__ = list(getattr(_d, "__all__", []))
