"""Token-search controllers (ref contrib/slim/searcher/controller.py:
SAController drives LightNAS by simulated annealing over an integer
token list). Deterministic here: a seeded Generator instead of global
numpy randomness, so searches replay."""
import math

import numpy as np

__all__ = ["EvolutionaryController", "SAController"]


class EvolutionaryController(object):
    """Base controller: propose tokens, learn from rewards."""

    def reset(self, range_table, init_tokens, constrain_func=None):
        raise NotImplementedError()

    def update(self, tokens, reward):
        raise NotImplementedError()

    def next_tokens(self, control_token=None):
        raise NotImplementedError()


class SAController(EvolutionaryController):
    """Simulated annealing: accept a worse candidate with probability
    exp(dreward / T), T decaying by reduce_rate each update — the
    reference's acceptance rule exactly."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_try_number=300, seed=0):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_try_number = max_try_number
        self._rng = np.random.RandomState(seed)
        self._constrain_func = None
        self._reward = -float("inf")
        self._tokens = None
        self._max_reward = -float("inf")
        self._best_tokens = None
        self._iter = 0

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0
        # a fresh search must not inherit the previous objective's state
        self._reward = -float("inf")
        self._max_reward = -float("inf")
        self._best_tokens = None

    def update(self, tokens, reward):
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        if reward > self._reward or self._rng.random_sample() <= math.exp(
                min((reward - self._reward) / max(temperature, 1e-12), 0)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self, control_token=None):
        tokens = list(control_token) if control_token else self._tokens
        # only positions with >1 option can mutate (a range of 1 pins a
        # fixed choice; mutating it would be randint(0) -> crash)
        mutable = [i for i, r in enumerate(self._range_table) if r > 1]
        if not mutable:
            return list(tokens)
        new_tokens = list(tokens)
        index = mutable[self._rng.randint(len(mutable))]
        new_tokens[index] = (
            new_tokens[index] +
            self._rng.randint(self._range_table[index] - 1) + 1) % \
            self._range_table[index]
        if self._constrain_func is None:
            return new_tokens
        for _ in range(self._max_try_number):
            if self._constrain_func(new_tokens):
                return new_tokens
            index = mutable[self._rng.randint(len(mutable))]
            new_tokens = list(tokens)
            new_tokens[index] = self._rng.randint(
                self._range_table[index])
        raise RuntimeError(
            "SAController: no constraint-satisfying candidate found in "
            "%d tries — the constrain_func may be infeasible around the "
            "current tokens %r" % (self._max_try_number, tokens))
