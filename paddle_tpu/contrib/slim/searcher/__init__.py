"""slim.searcher (ref contrib/slim/searcher/): evolutionary token
search controllers."""
from .controller import EvolutionaryController, SAController  # noqa: F401

__all__ = ["EvolutionaryController", "SAController"]
