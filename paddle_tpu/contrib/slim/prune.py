"""Model pruning — mask-based magnitude pruning + sensitivity analysis.

Reference parity: fluid/contrib/slim/prune/{pruner.py,prune_strategy.py}.
The reference physically shrinks tensors via graph surgery; on TPU static
shapes are king, so the native design is persistent 0/1 masks applied to
parameters in the Scope — XLA folds the multiplies, and sparsity-aware
hardware (or a later export) can exploit the zeros. Masks survive optimizer
updates by re-application (`apply_masks` after each step, or the
PruneHelper attached to an Executor run loop).
"""
import numpy as np

import jax.numpy as jnp

from ...framework.scope import global_scope


class Pruner(object):
    """Base pruner (reference slim/prune/pruner.py Pruner)."""

    def prune(self, param):
        raise NotImplementedError


class MagnitudePruner(Pruner):
    """Unstructured abs-magnitude pruning: zero the smallest `ratio`
    fraction of weights."""

    def __init__(self, ratio):
        self.ratio = float(ratio)

    def mask(self, value):
        v = np.asarray(value)
        k = int(v.size * self.ratio)
        if k <= 0:
            return np.ones_like(v, np.float32)
        # rank-based: prune exactly k elements — a threshold compare would
        # wipe out every tied value (e.g. the whole zero-init bias)
        mask = np.ones(v.size, np.float32)
        mask[np.argsort(np.abs(v).ravel(), kind="stable")[:k]] = 0.0
        return mask.reshape(v.shape)


class StructurePruner(Pruner):
    """Whole-slice (channel/neuron) pruning along `axis` ranked by the
    given criterion (reference StructurePruner l1_norm)."""

    def __init__(self, ratio, axis=0, criterion="l1_norm"):
        self.ratio = float(ratio)
        self.axis = int(axis)
        if criterion != "l1_norm":
            raise ValueError("unsupported criterion %r" % criterion)

    def mask(self, value):
        v = np.asarray(value)
        red = tuple(i for i in range(v.ndim) if i != self.axis)
        norms = np.abs(v).sum(axis=red)
        n_prune = int(norms.size * self.ratio)
        keep = np.ones(norms.size, np.float32)
        if n_prune > 0:
            keep[np.argsort(norms)[:n_prune]] = 0.0
        shape = [1] * v.ndim
        shape[self.axis] = -1
        return np.broadcast_to(keep.reshape(shape), v.shape).astype(
            np.float32).copy()


class PruneHelper(object):
    """Computes, applies, and re-applies pruning masks over Scope params."""

    def __init__(self, program, ratios, pruner_cls=MagnitudePruner,
                 scope=None, **pruner_kwargs):
        """ratios: {param_name: ratio} or a single float for all params."""
        self.program = program
        self.scope = scope or global_scope()
        params = [p.name for p in program.all_parameters()]
        if not isinstance(ratios, dict):
            ratios = {name: ratios for name in params}
        self.pruners = {name: pruner_cls(ratio, **pruner_kwargs)
                        for name, ratio in ratios.items()}
        self.masks = {}

    def compute_masks(self):
        for name, pruner in self.pruners.items():
            value = self.scope.find_var(name)
            if value is None:
                raise KeyError("parameter %r not in scope" % name)
            self.masks[name] = jnp.asarray(pruner.mask(value))
        return self.masks

    def apply_masks(self):
        """Zero pruned weights (idempotent; call after optimizer steps)."""
        if not self.masks:
            self.compute_masks()
        for name, mask in self.masks.items():
            self.scope.set_var(name, self.scope.find_var(name) * mask)

    def sparsity(self):
        total = live = 0
        for name, mask in self.masks.items():
            m = np.asarray(mask)
            total += m.size
            live += int(m.sum())
        return 1.0 - live / max(total, 1)


def sensitivity(program, executor, feed, fetch_loss, param_names=None,
                ratios=(0.1, 0.3, 0.5, 0.7, 0.9), pruner_cls=MagnitudePruner,
                scope=None):
    """Per-parameter pruning sensitivity sweep (reference
    slim/prune/auto_prune_strategy sensitivity analysis): for each param and
    ratio, prune ONLY that param and measure the loss delta. Weights are
    restored after every probe."""
    scope = scope or global_scope()
    if param_names is None:
        param_names = [p.name for p in program.all_parameters()]
    base = float(np.asarray(
        executor.run(program, feed=feed, fetch_list=[fetch_loss])[0]).mean())
    report = {}
    for name in param_names:
        orig = scope.find_var(name)
        report[name] = {}
        for ratio in ratios:
            mask = jnp.asarray(pruner_cls(ratio).mask(orig))
            scope.set_var(name, orig * mask)
            loss = float(np.asarray(executor.run(
                program, feed=feed, fetch_list=[fetch_loss])[0]).mean())
            report[name][ratio] = loss - base
            scope.set_var(name, orig)
    return base, report
