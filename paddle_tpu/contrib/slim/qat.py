"""Quantization-aware training program pass.

Reference parity: fluid/contrib/slim/quantization/quantization_pass.py
(QuantizationTransformPass / QuantizationFreezePass). Rewrites a Program in
place: every input of a quantizable op (conv2d / mul / matmul) is routed
through a simulated quantize-dequantize op — per-channel abs-max for
weights, moving-average abs-max (EMA state persisted in the Scope, updated
in-place each step like optimizer state) for activations — with
straight-through gradients, so training sees int8 rounding noise while XLA
still runs fp matmuls on the MXU.
"""
import numpy as np

from ...framework.program import Parameter
from ...framework.scope import global_scope

QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")

_W_SLOTS = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
            "mul": "Y", "matmul": "Y"}


__all__ = ["quant_aware", "convert", "QUANTIZABLE"]


def quant_aware(program, weight_bits=8, activation_bits=8,
                quantizable_op_types=QUANTIZABLE, moving_rate=0.9,
                skip_pattern="skip_quant", scope=None):
    """Insert fake-quant ops before every quantizable op's inputs.
    Activation EMA state vars are initialized directly in `scope`.
    Returns the number of rewritten ops (mutates `program`)."""
    import jax.numpy as jnp
    scope = scope or global_scope()
    block = program.global_block()
    rewritten = 0
    qdq_cache = {}      # (var name, is_weight) -> quantized replacement
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type not in quantizable_op_types or \
                skip_pattern in str(op.attrs.get("op_namescope", "")):
            i += 1
            continue
        w_slot = _W_SLOTS.get(op.type)
        inserted = 0
        for slot, names in list(op.inputs.items()):
            new_names = []
            for name in names:
                var = block.var(name)
                is_weight = isinstance(var, Parameter) and slot == w_slot
                # cache per quantization MODE: a tied param reaching both a
                # weight slot and an activation slot gets both variants
                key = (name, is_weight)
                if key in qdq_cache:
                    new_names.append(qdq_cache[key])
                    continue
                q_name = name + (".quantized" if is_weight
                                 else ".quantized.act")
                block.create_var(name=q_name, shape=var.shape,
                                 dtype=var.dtype)
                scale_var = block.create_var(
                    name=q_name + ".scale", stop_gradient=True)
                if is_weight:
                    # per-output-channel for conv (axis 0 of OIHW), per
                    # input-feature column for matmul/mul weights (axis 1)
                    axis = 0 if "conv" in op.type else 1
                    block._insert_op(
                        i, "fake_channel_wise_quantize_dequantize_abs_max",
                        inputs={"X": [name]},
                        outputs={"Out": [q_name],
                                 "OutScale": [scale_var.name]},
                        attrs={"bit_length": weight_bits,
                               "quant_axis": axis})
                else:
                    # EMA scale state lives in the scope and is updated
                    # in-place every step, exactly like optimizer moments
                    state = block.create_var(
                        name=q_name + ".state", shape=(1,),
                        persistable=True, stop_gradient=True)
                    accum = block.create_var(
                        name=q_name + ".accum", shape=(1,),
                        persistable=True, stop_gradient=True)
                    if scope.find_var(state.name) is None:
                        scope.set_var(state.name, jnp.ones((1,)))
                        scope.set_var(accum.name, jnp.zeros((1,)))
                    block._insert_op(
                        i,
                        "fake_quantize_dequantize_moving_average_abs_max",
                        inputs={"X": [name], "InState": [state.name],
                                "InAccum": [accum.name]},
                        outputs={"Out": [q_name],
                                 "OutScale": [scale_var.name],
                                 "OutState": [state.name],
                                 "OutAccum": [accum.name]},
                        attrs={"bit_length": activation_bits,
                               "moving_rate": moving_rate})
                qdq_cache[key] = q_name
                new_names.append(q_name)
                inserted += 1
                i += 1   # the target op shifted right
            op.inputs[slot] = new_names
        if inserted:
            rewritten += 1
        i += 1
    return rewritten


def convert(program, scope=None):
    """Freeze a quant-aware-trained program for int8 inference export:
    strips activation fake-quant ops (their EMA scales are returned as
    metadata) and computes PER-CHANNEL weight scales matching exactly what
    training simulated (reference QuantizationFreezePass, XLA-native form:
    weight qdq ops stay in the program so exported fp weights carry the
    rounding).

    Returns {"weights": {param: per-channel scale array},
             "activations": {var: float scale}}."""
    scope = scope or global_scope()
    block = program.global_block()
    # collect weight quant configs BEFORE stripping anything
    w_cfg = {}
    for op in block.ops:
        if op.type == "fake_channel_wise_quantize_dequantize_abs_max":
            w_cfg[op.inputs["X"][0]] = (int(op.attrs.get("quant_axis", 0)),
                                        int(op.attrs.get("bit_length", 8)))
    act_scales = {}
    idx = 0
    while idx < len(block.ops):
        op = block.ops[idx]
        if op.type == "fake_quantize_dequantize_moving_average_abs_max":
            src = op.inputs["X"][0]
            dst = op.outputs["Out"][0]
            accum = scope.find_var(op.inputs["InAccum"][0])
            state = scope.find_var(op.inputs["InState"][0])
            if accum is not None and state is not None:
                act_scales[src] = float(np.asarray(accum)[0] /
                                        max(float(np.asarray(state)[0]),
                                            1e-8))
            for later in block.ops[idx + 1:]:
                for slot, names in later.inputs.items():
                    later.inputs[slot] = [src if n == dst else n
                                          for n in names]
            block._remove_op(idx)
            continue
        idx += 1
    w_scales = {}
    for name, (axis, bits) in w_cfg.items():
        value = scope.find_var(name)
        if value is None:
            continue
        v = np.asarray(value)
        red = tuple(i for i in range(v.ndim) if i != axis)
        w_scales[name] = np.maximum(np.abs(v).max(axis=red), 1e-8)
    return {"weights": w_scales, "activations": act_scales}
