"""NAS controller server (ref slim/nas/controller_server.py): a tiny
TCP service wrapping an EvolutionaryController so distributed search
agents can request next-tokens / report rewards over the network."""
import json
import socket
import threading

__all__ = ["ControllerServer"]


class ControllerServer(object):
    """Serve a controller (e.g. searcher.controller.SAController).

    Protocol: one JSON line per request —
      {"cmd": "next_tokens"} -> {"tokens": [...]}
      {"cmd": "update", "tokens": [...], "reward": r} -> {"ok": true}
    """

    def __init__(self, controller, address=("", 0), max_client_num=100,
                 search_steps=None, key=None):
        self._controller = controller
        self._address = address
        self._max_client_num = max_client_num
        self._search_steps = search_steps
        self._sock = None
        self._thread = None
        self._closed = threading.Event()

    def start(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(self._address)
        self._sock.listen(self._max_client_num)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self.ip(), self.port()

    def ip(self):
        host = self._sock.getsockname()[0]
        if host in ("", "0.0.0.0", "::"):
            # wildcard binds are unreachable from other hosts — hand
            # agents this machine's routable address instead
            host = socket.gethostbyname(socket.gethostname())
        return host

    def port(self):
        return self._sock.getsockname()[1]

    def close(self):
        self._closed.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass

    def _serve(self):
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with conn:
                # one dead/half-open client must not stall or kill the
                # serve loop for every other agent
                try:
                    conn.settimeout(30)
                    req = json.loads(conn.makefile("r").readline())
                    resp = self._handle(req)
                    conn.sendall((json.dumps(resp) + "\n").encode())
                except Exception:      # malformed request / client gone
                    try:
                        conn.sendall(b'{"error": "bad request"}\n')
                    except OSError:
                        pass

    def _handle(self, req):
        cmd = req.get("cmd")
        if cmd == "next_tokens":
            return {"tokens": list(self._controller.next_tokens())}
        if cmd == "update":
            self._controller.update(req["tokens"], float(req["reward"]))
            return {"ok": True}
        return {"error": "unknown cmd %r" % (cmd,)}
