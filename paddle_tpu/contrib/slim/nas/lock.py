"""File locks for NAS checkpoint coordination (ref slim/nas/lock.py)."""
import os

__all__ = ["lock", "unlock"]

if os.name == "posix":
    import fcntl

    def lock(file_handle):
        """Block until an exclusive lock on the open file is held."""
        fcntl.flock(file_handle, fcntl.LOCK_EX)

    def unlock(file_handle):
        fcntl.flock(file_handle, fcntl.LOCK_UN)
else:  # pragma: no cover - windows parity stub
    def lock(file_handle):
        raise NotImplementedError("file locks require posix")

    def unlock(file_handle):
        raise NotImplementedError("file locks require posix")
