"""ref contrib/slim/nas/light_nas_strategy.py, reduced to its core: a
simulated-annealing search loop over a SearchSpace, driven by a reward
callback (the reference wires this into the Compressor event loop and a
controller server; evaluation is the caller's concern here)."""
from ..searcher.controller import SAController

__all__ = ["LightNASStrategy"]


class LightNASStrategy(object):
    def __init__(self, search_space, reduce_rate=0.85,
                 init_temperature=1024, search_steps=100, seed=0):
        self._space = search_space
        self._controller = SAController(
            reduce_rate=reduce_rate, init_temperature=init_temperature,
            seed=seed)
        self._search_steps = search_steps

    def search(self, reward_func, constrain_func=None):
        """Run the SA loop: reward_func(tokens) -> float. Returns
        (best_tokens, best_reward)."""
        self._controller.reset(self._space.range_table(),
                               self._space.init_tokens(), constrain_func)
        tokens = list(self._space.init_tokens())
        self._controller.update(tokens, reward_func(tokens))
        for _ in range(self._search_steps):
            tokens = self._controller.next_tokens()
            self._controller.update(tokens, reward_func(tokens))
        return self._controller.best_tokens, self._controller.max_reward
