"""ref contrib/slim/nas/search_space.py: user-subclassed definition of
the token space."""

__all__ = ["SearchSpace"]


class SearchSpace(object):
    def init_tokens(self):
        """Initial token list."""
        raise NotImplementedError()

    def range_table(self):
        """Per-position exclusive upper bounds."""
        raise NotImplementedError()

    def create_net(self, tokens=None):
        """Build (train_program, eval_program, ...) for the tokens."""
        raise NotImplementedError()
