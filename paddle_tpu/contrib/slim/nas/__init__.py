"""slim.nas (ref contrib/slim/nas/): LightNAS search loop. The
reference splits controller-server/search-agent across sockets for
cluster search; here the loop runs in-process (a pod evaluates
candidates under its own mesh — no socket tier needed)."""
from .search_space import SearchSpace  # noqa: F401
from .light_nas_strategy import LightNASStrategy  # noqa: F401

__all__ = ["SearchSpace", "LightNASStrategy"]
