"""NAS search agent (ref slim/nas/search_agent.py): the client side of
ControllerServer — ask for tokens, report rewards."""
import json
import socket

__all__ = ["SearchAgent"]


class SearchAgent(object):
    def __init__(self, server_ip, server_port, key=None):
        self._server_ip = server_ip or "127.0.0.1"
        self._server_port = int(server_port)

    def _request(self, payload):
        with socket.create_connection(
                (self._server_ip, self._server_port), timeout=60) as s:
            s.sendall((json.dumps(payload) + "\n").encode())
            resp = json.loads(s.makefile("r").readline())
        if "error" in resp:
            raise RuntimeError("controller server: %s" % resp["error"])
        return resp

    def next_tokens(self):
        return self._request({"cmd": "next_tokens"})["tokens"]

    def update(self, tokens, reward):
        return self._request({"cmd": "update", "tokens": list(tokens),
                              "reward": float(reward)})