"""Compression pipeline driver
(ref python/paddle/fluid/contrib/slim/core/compressor.py Compressor).

The reference Compressor reads a YAML config and drives pruning /
distillation / quantization strategies across training epochs with
periodic eval and checkpointing.  This build keeps the same run-loop
contract programmatically: strategies are objects exposing any of
``on_compression_begin/on_epoch_begin/on_epoch_end/
on_compression_end(context)``; the Context carries the executor, the
train/eval programs and readers, and an eval-history the strategies
(and eval_converged) can consult.  The package's strategy
implementations live in prune.py / distill.py / qat.py.
"""
import numpy as np

__all__ = ["Context", "Compressor"]


class Context(object):
    """Run-loop state handed to every strategy hook (ref :77)."""

    def __init__(self, place=None, scope=None, train_graph=None,
                 eval_graph=None, executor=None):
        self.place = place
        self.scope = scope
        self.train_graph = train_graph
        self.eval_graph = eval_graph
        self.executor = executor
        self.epoch_id = 0
        self.eval_results = {}

    def eval_converged(self, metric_name, delta=0.001):
        """True when the last two evals of ``metric_name`` moved by less
        than ``delta`` (ref :153)."""
        hist = self.eval_results.get(metric_name, [])
        if len(hist) < 2:
            return False
        return abs(hist[-1] - hist[-2]) < delta


class Compressor(object):
    """Drive train/eval epochs through a list of strategies (ref :238).

    train_fn(exe) runs one training epoch; eval_fn(exe) returns
    {metric_name: value}.  Both run under the caller's scope.
    """

    def __init__(self, place, scope, train_program, train_reader=None,
                 train_feed_list=None, train_fetch_list=None,
                 eval_program=None, eval_reader=None, eval_feed_list=None,
                 eval_fetch_list=None, epoch=1, strategies=None,
                 train_fn=None, eval_fn=None, checkpoint_path=None):
        from ...framework.executor import Executor
        self.place = place
        self.scope = scope
        self.train_program = train_program
        self.eval_program = eval_program or train_program
        self.epoch = int(epoch)
        self.strategies = list(strategies or [])
        self.checkpoint_path = checkpoint_path
        self._exe = Executor(place)
        self._train_reader = train_reader
        self._train_feeds = train_feed_list or []
        self._train_fetch = train_fetch_list or []
        self._eval_reader = eval_reader
        self._eval_feeds = eval_feed_list or []
        self._eval_fetch = eval_fetch_list or []
        self._train_fn = train_fn
        self._eval_fn = eval_fn

    def _dispatch(self, hook, context):
        for s in self.strategies:
            fn = getattr(s, hook, None)
            if fn is not None:
                fn(context)

    def _default_train_epoch(self):
        for data in self._train_reader():
            feed = dict(zip([getattr(v, "name", v)
                             for v in self._train_feeds],
                            map(np.asarray, zip(*data)))) \
                if self._train_feeds else data
            self._exe.run(self.train_program, feed=feed,
                          fetch_list=self._train_fetch)

    def _default_eval(self):
        totals, count = None, 0
        for data in self._eval_reader():
            feed = dict(zip([getattr(v, "name", v)
                             for v in self._eval_feeds],
                            map(np.asarray, zip(*data)))) \
                if self._eval_feeds else data
            outs = self._exe.run(self.eval_program, feed=feed,
                                 fetch_list=self._eval_fetch)
            vals = [float(np.asarray(o).reshape(-1)[0]) for o in outs]
            totals = vals if totals is None else \
                [t + v for t, v in zip(totals, vals)]
            count += 1
        names = [getattr(v, "name", str(i))
                 for i, v in enumerate(self._eval_fetch)]
        return {n: t / max(count, 1)
                for n, t in zip(names, totals or [])}

    def run(self):
        """The reference run loop (ref :520): compression_begin ->
        per-epoch (epoch_begin, train, eval, epoch_end) ->
        compression_end; returns the context for inspection."""
        from ...framework.scope import scope_guard
        context = Context(place=self.place, scope=self.scope,
                          train_graph=self.train_program,
                          eval_graph=self.eval_program,
                          executor=self._exe)
        with scope_guard(self.scope):
            self._dispatch("on_compression_begin", context)
            for epoch_id in range(self.epoch):
                context.epoch_id = epoch_id
                self._dispatch("on_epoch_begin", context)
                if self._train_fn is not None:
                    self._train_fn(self._exe)
                elif self._train_reader is not None:
                    self._default_train_epoch()
                results = self._eval_fn(self._exe) \
                    if self._eval_fn is not None else (
                    self._default_eval()
                    if self._eval_reader is not None else {})
                for k, v in (results or {}).items():
                    context.eval_results.setdefault(k, []).append(v)
                self._dispatch("on_epoch_end", context)
                if self.checkpoint_path:
                    from ... import io as io_mod
                    io_mod.save_checkpoint(
                        self._exe, self.checkpoint_path,
                        self.train_program, step=epoch_id)
            self._dispatch("on_compression_end", context)
        return context
