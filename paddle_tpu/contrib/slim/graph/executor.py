"""SlimGraphExecutor (ref slim/graph/executor.py): run a GraphWrapper's
underlying Program through the ordinary Executor."""
import numpy as np

__all__ = ["SlimGraphExecutor"]


class SlimGraphExecutor(object):
    def __init__(self, place=None):
        from .... import Executor
        self.exe = Executor(place)
        self.place = place

    def run(self, graph, scope=None, data=None):
        """Execute ``graph`` (a GraphWrapper or Program) and return its
        declared out_nodes' values."""
        program = getattr(graph, "program", graph)
        fetch_list = list(getattr(graph, "out_nodes", {}).values())
        feed = None
        if data is not None:
            in_nodes = getattr(graph, "in_nodes", {})
            if isinstance(data, dict):
                feed = data
            else:
                feed = {name: np.asarray(col) for name, col in
                        zip(in_nodes, map(list, zip(*data)))}
        return self.exe.run(program, feed=feed, scope=scope,
                            fetch_list=fetch_list)
