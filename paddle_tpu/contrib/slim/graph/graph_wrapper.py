"""ref contrib/slim/graph/graph_wrapper.py: the slim passes (prune/NAS/
quant) inspect models through this wrapper instead of raw IR."""

__all__ = ["GraphWrapper", "VarWrapper", "OpWrapper"]


class VarWrapper(object):
    def __init__(self, var, graph):
        self._var = var
        self._graph = graph

    def name(self):
        return self._var.name

    def shape(self):
        return self._var.shape

    def is_parameter(self):
        from ....framework.program import Parameter
        return isinstance(self._var, Parameter)

    def inputs(self):
        """Ops producing this var."""
        return [op for op in self._graph.ops()
                if self.name() in op._op.output_names()]

    def outputs(self):
        """Ops consuming this var."""
        return [op for op in self._graph.ops()
                if self.name() in op._op.input_names()]


class OpWrapper(object):
    def __init__(self, op, graph):
        self._op = op
        self._graph = graph

    def type(self):
        return self._op.type

    def attr(self, name):
        return self._op.attr(name)

    def all_inputs(self):
        return [self._graph.var(n) for n in self._op.input_names()
                if self._graph.has_var(n)]

    def all_outputs(self):
        return [self._graph.var(n) for n in self._op.output_names()
                if self._graph.has_var(n)]


class GraphWrapper(object):
    def __init__(self, program=None, in_nodes=None, out_nodes=None):
        from ....framework.program import default_main_program
        self.program = program or default_main_program()
        self.in_nodes = dict(in_nodes or {})
        self.out_nodes = dict(out_nodes or {})

    def has_var(self, name):
        return self.program.global_block()._find_var_recursive(name) \
            is not None

    def var(self, name):
        v = self.program.global_block()._find_var_recursive(name)
        if v is None:
            raise ValueError("variable %r not in graph" % name)
        return VarWrapper(v, self)

    def vars(self):
        return [VarWrapper(v, self) for v in self.program.list_vars()]

    def all_parameters(self):
        return [v for v in self.vars() if v.is_parameter()]

    def ops(self):
        # every block, not just block 0 — control-flow sub-block ops
        # must be visible to prune/quant passes
        return [OpWrapper(op, self)
                for blk in self.program.blocks for op in blk.ops]

    def numel_params(self):
        import numpy as np
        total = 0
        for p in self.all_parameters():
            shape = [d for d in (p.shape() or ()) if d not in (None, -1)]
            total += int(np.prod(shape)) if shape else 1
        return total
