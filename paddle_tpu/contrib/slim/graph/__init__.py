"""slim.graph (ref contrib/slim/graph/): program graph introspection."""
from .graph_wrapper import GraphWrapper, VarWrapper, OpWrapper  # noqa: F401

__all__ = ["GraphWrapper", "VarWrapper", "OpWrapper"]
