"""Module-path alias for slim.quantization (ref
contrib/slim/quantization/); QAT passes live in qat.py."""
from .qat import *  # noqa: F401,F403
from . import qat as _q

__all__ = list(getattr(_q, "__all__", []))
