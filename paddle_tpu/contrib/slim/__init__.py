"""Model compression toolkit (reference fluid/contrib/slim).

- prune: mask-based magnitude/structured pruning + sensitivity sweeps
- distill: soft-label / L2 / FSP distillation losses + teacher merge
  (module-path alias: slim.distillation)
- qat: quantization-aware training program pass (sim-quant with STE)
  (module-path alias: slim.quantization)
- graph: GraphWrapper program introspection for the passes
- searcher/nas: SAController simulated annealing + LightNASStrategy
  search loop (the reference's socketed controller-server tier is N/A:
  a pod evaluates candidates under its own mesh, in process)
- post-training int8 lives in paddle_tpu.contrib.quantize
"""
from .prune import (Pruner, MagnitudePruner, StructurePruner, PruneHelper,
                    sensitivity)
from .distill import (soft_label_loss, l2_distill_loss, fsp_matrix,
                      fsp_loss, merge)
from .qat import quant_aware, convert, QUANTIZABLE
from .core import Compressor  # noqa: F401
