"""Model compression toolkit (reference fluid/contrib/slim).

- prune: mask-based magnitude/structured pruning + sensitivity sweeps
- distill: soft-label / L2 / FSP distillation losses + teacher merge
- qat: quantization-aware training program pass (sim-quant with STE)
- post-training int8 lives in paddle_tpu.contrib.quantize

The reference's NAS (light_nas) searcher is a training-loop driver with no
TPU-specific kernel surface; it is intentionally out of scope here.
"""
from .prune import (Pruner, MagnitudePruner, StructurePruner, PruneHelper,
                    sensitivity)
from .distill import (soft_label_loss, l2_distill_loss, fsp_matrix,
                      fsp_loss, merge)
from .qat import quant_aware, convert, QUANTIZABLE
from .core import Compressor  # noqa: F401
