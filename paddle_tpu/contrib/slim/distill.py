"""Knowledge distillation losses + teacher/student program merging.

Reference parity: fluid/contrib/slim/distillation/distiller.py
(L2Distiller, FSPDistiller, SoftLabelDistiller) and the strategy's
program-merge step. Losses are plain layer compositions appended to the
current Program; `merge` clones a frozen teacher program into the student's
with a name prefix so one Executor step runs both.
"""
from ... import layers
from ...framework.program import Parameter, default_main_program


__all__ = ["soft_label_loss", "l2_distill_loss", "fsp_matrix",
           "fsp_loss", "merge"]


def soft_label_loss(student_logits, teacher_logits,
                    student_temperature=1.0, teacher_temperature=1.0):
    """Cross-entropy between temperature-softened distributions (reference
    SoftLabelDistiller): mean(-sum(softmax(t/Tt) * log_softmax(s/Ts)))."""
    s = layers.scale(student_logits, scale=1.0 / student_temperature)
    t = layers.scale(teacher_logits, scale=1.0 / teacher_temperature)
    t_prob = layers.softmax(t)
    t_prob.stop_gradient = True
    s_log = layers.log_softmax(s)
    ce = layers.reduce_sum(layers.elementwise_mul(t_prob, s_log), dim=-1)
    return layers.scale(layers.reduce_mean(ce), scale=-1.0)


def l2_distill_loss(student_feature, teacher_feature):
    """L2 feature-map distillation (reference L2Distiller)."""
    teacher_feature.stop_gradient = True
    diff = layers.elementwise_sub(student_feature, teacher_feature)
    return layers.reduce_mean(layers.square(diff))


def fsp_matrix(feature_a, feature_b):
    """Flow-of-solution-procedure matrix (reference FSPDistiller
    _fsp_matrix): (N, C1, H, W) x (N, C2, H, W) -> (N, C1, C2), the mean
    over H*W of per-position channel outer products."""
    c1 = feature_a.shape[1]
    c2 = feature_b.shape[1]
    h, w = feature_a.shape[2], feature_a.shape[3]
    a = layers.reshape(feature_a, shape=[0, c1, h * w])
    b = layers.reshape(feature_b, shape=[0, c2, h * w])
    prod = layers.matmul(a, layers.transpose(b, perm=[0, 2, 1]))
    return layers.scale(prod, scale=1.0 / (h * w))


def fsp_loss(student_a, student_b, teacher_a, teacher_b):
    """FSP distillation loss between a student layer pair and a teacher
    layer pair (reference FSPDistiller)."""
    sm = fsp_matrix(student_a, student_b)
    tm = fsp_matrix(teacher_a, teacher_b)
    tm.stop_gradient = True
    return layers.reduce_mean(layers.square(
        layers.elementwise_sub(sm, tm)))


def merge(teacher_program, student_program=None, name_prefix="teacher_",
          scope=None):
    """Clone the teacher graph into the student program under a prefix
    (reference slim distillation_strategy's merge): teacher vars/params are
    renamed `prefix+name`, marked stop_gradient, and its feed vars keep
    their ORIGINAL names so one feed dict drives both nets. Teacher
    parameter values already initialized in the scope are copied to their
    prefixed names.

    Returns {original_teacher_var_name: merged Variable} for wiring
    distillation losses.
    """
    from ...framework.scope import global_scope
    scope = scope or global_scope()
    student_program = student_program or default_main_program()
    if teacher_program.num_blocks > 1:
        raise NotImplementedError(
            "merge() supports single-block teacher programs; control-flow "
            "sub-blocks would need index remapping")
    t_block = teacher_program.global_block()
    s_block = student_program.global_block()

    def mapped(name):
        var = t_block.var(name)
        if getattr(var, "is_data", False):
            return name          # shared feeds
        return name_prefix + name

    var_map = {}
    for name, var in t_block.vars.items():
        new_name = mapped(name)
        if s_block.has_var(new_name):
            var_map[name] = s_block.var(new_name)
            continue
        kwargs = dict(name=new_name, shape=var.shape, dtype=var.dtype,
                      stop_gradient=True,
                      persistable=getattr(var, "persistable", False))
        if isinstance(var, Parameter):
            new = s_block.create_parameter(
                trainable=False, **kwargs)
            value = scope.find_var(name)
            if value is not None:
                # distinct buffer: the executor donates program params, and
                # an aliased array would be deleted under the old name
                import jax.numpy as jnp
                scope.set_var(new_name, jnp.array(value, copy=True))
        else:
            kwargs["is_data"] = getattr(var, "is_data", False)
            new = s_block.create_var(**kwargs)
        var_map[name] = new

    for op in t_block.ops:
        s_block.append_op(
            op.type,
            inputs={slot: [mapped(n) for n in names]
                    for slot, names in op.inputs.items()},
            outputs={slot: [mapped(n) for n in names]
                     for slot, names in op.outputs.items()},
            attrs=dict(op.attrs))
    return var_map
