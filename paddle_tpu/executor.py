"""Module-path alias for fluid.executor (ref
python/paddle/fluid/executor.py)."""
from .framework.executor import Executor  # noqa: F401
from .framework.scope import global_scope, scope_guard, Scope  # noqa: F401

__all__ = ["Executor", "global_scope", "scope_guard"]
