"""Weight-decay regularizers.

Reference parity: python/paddle/fluid/regularizer.py. Regularization is
appended as grad += coeff * f(param) ops, fused by XLA into the update.
"""
from .layer_helper import LayerHelper


class WeightDecayRegularizer(object):
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype,
                                                          param.shape)
        block.append_op("scale", inputs={"X": [param.name]},
                        outputs={"Out": [decay.name]},
                        attrs={"scale": self._coeff, "op_role": "optimize"})
        new_grad = helper.create_variable_for_type_inference(param.dtype,
                                                             param.shape)
        block.append_op("sum", inputs={"X": [grad.name, decay.name]},
                        outputs={"Out": [new_grad.name]},
                        attrs={"op_role": "optimize"})
        return new_grad


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype,
                                                         param.shape)
        block.append_op("sign", inputs={"X": [param.name]},
                        outputs={"Out": [sign.name]},
                        attrs={"op_role": "optimize"})
        decay = helper.create_variable_for_type_inference(param.dtype,
                                                          param.shape)
        block.append_op("scale", inputs={"X": [sign.name]},
                        outputs={"Out": [decay.name]},
                        attrs={"scale": self._coeff, "op_role": "optimize"})
        new_grad = helper.create_variable_for_type_inference(param.dtype,
                                                             param.shape)
        block.append_op("sum", inputs={"X": [grad.name, decay.name]},
                        outputs={"Out": [new_grad.name]},
                        attrs={"op_role": "optimize"})
        return new_grad


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(parameters_and_grads, regularization=None):
    out = []
    for param, grad in parameters_and_grads:
        if grad is None:
            out.append((param, grad))
            continue
        regularizer = getattr(param, "regularizer", None) or regularization
        if regularizer is None:
            out.append((param, grad))
            continue
        new_grad = regularizer(param, grad, grad.block)
        out.append((param, new_grad))
    return out
