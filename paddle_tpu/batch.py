"""paddle.batch parity (ref python/paddle/batch.py).

The implementation lives in reader.decorator; this module re-exports it
under the reference's module path. Because `import paddle_tpu.batch`
rebinds the package attribute `paddle_tpu.batch` from the function to
this module (the same footgun the reference had), the module itself is
made callable and delegates to the function — both spellings work.
"""
import sys
import types

from .reader.decorator import batch

__all__ = ["batch"]


class _CallableModule(types.ModuleType):
    def __call__(self, *args, **kwargs):
        return batch(*args, **kwargs)


sys.modules[__name__].__class__ = _CallableModule
