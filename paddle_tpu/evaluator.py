"""Evaluator classes (ref python/paddle/fluid/evaluator.py).

The reference Evaluators stitch accumulator variables into the Program
and zero them via mini-programs.  On TPU the step should stay one fused
jit, so these evaluators keep their running state on the HOST (the
pattern of metrics.py) and consume per-batch op outputs (chunk_eval,
edit_distance, detection predictions) fetched from Executor.run —
numerically the same aggregates without graph-side bookkeeping.
"""
import numpy as np

__all__ = ['ChunkEvaluator', 'EditDistance', 'DetectionMAP']


class Evaluator(object):
    """Base: host-state accumulators with the reference's
    reset()/eval() surface (ref evaluator.py:45)."""

    def __init__(self, name=None, **kwargs):
        self.helper_name = name or self.__class__.__name__
        self.states = {}

    def reset(self, executor=None, reset_program=None):
        for k in self.states:
            self.states[k] = 0.0

    def eval(self, executor=None, eval_program=None):
        raise NotImplementedError()


class ChunkEvaluator(Evaluator):
    """Accumulate chunk_eval batch counts into corpus-level
    precision/recall/F1 (ref evaluator.py:127).  Feed it the three
    count outputs of ``layers.chunk_eval`` each batch via update()."""

    def __init__(self, input=None, label=None, chunk_scheme=None,
                 num_chunk_types=None, excluded_chunk_types=None):
        super(ChunkEvaluator, self).__init__()
        self.states = {"num_infer_chunks": 0.0, "num_label_chunks": 0.0,
                       "num_correct_chunks": 0.0}

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.states["num_infer_chunks"] += float(
            np.asarray(num_infer_chunks).reshape(-1)[0])
        self.states["num_label_chunks"] += float(
            np.asarray(num_label_chunks).reshape(-1)[0])
        self.states["num_correct_chunks"] += float(
            np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self, executor=None, eval_program=None):
        c = self.states["num_correct_chunks"]
        i = self.states["num_infer_chunks"]
        l = self.states["num_label_chunks"]
        precision = c / i if i else 0.0
        recall = c / l if l else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0
        return precision, recall, f1


class EditDistance(Evaluator):
    """Average edit distance + sequence error rate accumulator
    (ref evaluator.py:218): update() with the per-batch (distances,
    seq_num) from ``layers.edit_distance``."""

    def __init__(self, input=None, label=None, ignored_tokens=None):
        super(EditDistance, self).__init__()
        self.states = {"total_distance": 0.0, "seq_num": 0.0,
                       "instance_error": 0.0}

    def update(self, distances, seq_num=None):
        d = np.asarray(distances).reshape(-1)
        self.states["total_distance"] += float(d.sum())
        self.states["seq_num"] += float(len(d) if seq_num is None
                                        else np.asarray(seq_num)
                                        .reshape(-1)[0])
        self.states["instance_error"] += float((d > 0).sum())

    def eval(self, executor=None, eval_program=None):
        n = self.states["seq_num"]
        avg = self.states["total_distance"] / n if n else 0.0
        err = self.states["instance_error"] / n if n else 0.0
        return avg, err


def _voc_ap(rec, prec, use_11_point):
    if use_11_point:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = prec[rec >= t].max() if np.any(rec >= t) else 0.0
            ap += p / 11.0
        return min(ap, 1.0)  # guard float accumulation past 1.0
    # integral AP: area under the monotone precision envelope
    mrec = np.concatenate(([0.0], rec, [1.0]))
    mpre = np.concatenate(([0.0], prec, [0.0]))
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


class DetectionMAP(Evaluator):
    """VOC-style mean average precision accumulator
    (ref evaluator.py:299 + operators/detection_map_op).  update() per
    image with predictions [[label, score, x1, y1, x2, y2], ...] and
    ground truths [[label, x1, y1, x2, y2], ...] (+ optional difficult
    flags); eval() returns mAP over all updates."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version='integral'):
        super(DetectionMAP, self).__init__()
        if ap_version not in ('integral', '11point'):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.class_num = class_num
        self.background_label = background_label
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self._preds = {}   # class -> list of (score, image_id, box)
        self._gts = {}     # (image_id, class) -> [ [box, difficult, hit] ]
        self._img = 0

    def reset(self, executor=None, reset_program=None):
        self._preds, self._gts, self._img = {}, {}, 0

    def update(self, predictions, gt_boxes, gt_labels, difficult=None):
        img = self._img
        self._img += 1
        preds = np.asarray(predictions, np.float64).reshape(-1, 6)
        gt_boxes = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        gt_labels = np.asarray(gt_labels).reshape(-1)
        if difficult is None:
            difficult = np.zeros(len(gt_labels), bool)
        difficult = np.asarray(difficult).reshape(-1).astype(bool)
        for box, lab, diff in zip(gt_boxes, gt_labels, difficult):
            self._gts.setdefault((img, int(lab)), []).append(
                [box, bool(diff), False])
        for row in preds:
            lab = int(row[0])
            if lab == self.background_label or lab < 0:
                continue
            self._preds.setdefault(lab, []).append(
                (float(row[1]), img, row[2:6]))

    @staticmethod
    def _iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = (a[2] - a[0]) * (a[3] - a[1]) + \
            (b[2] - b[0]) * (b[3] - b[1]) - inter
        return inter / ua if ua > 0 else 0.0

    def eval(self, executor=None, eval_program=None):
        classes = set(self._preds) | {c for (_, c) in self._gts}
        classes.discard(self.background_label)
        aps = []
        for c in sorted(classes):
            npos = 0
            for (img, cc), entries in self._gts.items():
                if cc != c:
                    continue
                for e in entries:
                    e[2] = False  # reset hit marks
                    if self.evaluate_difficult or not e[1]:
                        npos += 1
            dets = sorted(self._preds.get(c, []), reverse=True,
                          key=lambda r: r[0])
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            for i, (score, img, box) in enumerate(dets):
                cands = self._gts.get((img, c), [])
                best, best_iou = None, self.overlap_threshold
                for e in cands:
                    iou = self._iou(box, e[0])
                    if iou >= best_iou:
                        best, best_iou = e, iou
                if best is None:
                    fp[i] = 1
                elif not self.evaluate_difficult and best[1]:
                    continue  # difficult gt: ignore the detection
                elif not best[2]:
                    tp[i] = 1
                    best[2] = True
                else:
                    fp[i] = 1  # duplicate detection of a matched gt
            if npos == 0:
                continue
            rec = np.cumsum(tp) / npos
            prec = np.cumsum(tp) / np.maximum(
                np.cumsum(tp) + np.cumsum(fp), 1e-12)
            aps.append(_voc_ap(rec, prec,
                               self.ap_version == '11point'))
        return float(np.mean(aps)) if aps else 0.0
