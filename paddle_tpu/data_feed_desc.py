"""fluid.data_feed_desc parity (ref
python/paddle/fluid/data_feed_desc.py).

The reference wraps a protobuf-text config for the C++ MultiSlotDataFeed.
Our engine takes the same information as plain Python (Dataset API /
native dataplane), so DataFeedDesc here is a light config holder with
the reference's setters, parsed from the same proto-text format (name/
type/is_dense/is_used fields of multi_slot_desc, batch_size) — enough
for scripts that build the desc then hand it to a Dataset.
"""
import re

__all__ = ["DataFeedDesc"]


class DataFeedDesc(object):
    def __init__(self, proto_file):
        with open(proto_file) as f:
            text = f.read()
        self._text = text
        self.batch_size = None
        m = re.search(r"batch_size\s*:\s*(\d+)", text)
        if m:
            self.batch_size = int(m.group(1))
        self._slots = []
        for blk in re.findall(r"slots\s*\{([^}]*)\}", text):
            slot = {}
            for key in ("name", "type"):
                m = re.search(r"%s\s*:\s*\"([^\"]+)\"" % key, blk)
                if m:
                    slot[key] = m.group(1)
            for key in ("is_dense", "is_used"):
                m = re.search(r"%s\s*:\s*(\w+)" % key, blk)
                slot[key] = (m.group(1).lower() == "true") if m else False
            self._slots.append(slot)
        self.__name_to_index = {s["name"]: i
                                for i, s in enumerate(self._slots)}

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        for name in dense_slots_name:
            self._slots[self.__name_to_index[name]]["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        for name in use_slots_name:
            self._slots[self.__name_to_index[name]]["is_used"] = True

    def slots(self):
        return [dict(s) for s in self._slots]

    def desc(self):
        """Re-serialize the (possibly mutated) config: the reference
        returns text_format.MessageToString of the LIVE proto, so
        setters must be visible to consumers of desc()."""
        text = self._text
        if self.batch_size is not None:
            text = re.sub(r"batch_size\s*:\s*\d+",
                          "batch_size: %d" % self.batch_size, text, count=1)

        slot_iter = iter(self._slots)

        def render(m):
            slot = next(slot_iter)
            blk = m.group(1)
            for key in ("is_dense", "is_used"):
                val = "true" if slot.get(key) else "false"
                blk, n = re.subn(r"%s\s*:\s*\w+" % key,
                                 "%s: %s" % (key, val), blk)
                if not n:
                    blk = blk.rstrip() + "\n        %s: %s\n    " \
                        % (key, val)
            return "slots {%s}" % blk

        return re.sub(r"slots\s*\{([^}]*)\}", render, text)
