#!/usr/bin/env python
"""Pallas kernel autotuner CLI.

Sweeps each kernel's block configs per (op, shape, dtype, topology,
backend) with bounded probes — pruned to the cost model's ``--top-k``
best-predicted candidates by default (the full space with
``--top-k 0``) — persists the winners (or the XLA-fallback verdict)
plus every measured candidate row in the versioned JSON cache
`CompiledProgram` loads at trace time via ``BuildStrategy.
pallas_tune_cache`` / ``kernel_policy="auto"``, and prints ONE JSON
summary line with predicted vs measured seconds per candidate.

Usage:
  python tools/autotune.py                       # all ops, chip shapes,
                                                 # cost-model top-3
  python tools/autotune.py --top-k 0             # exhaustive sweep
  python tools/autotune.py --cost-model-only     # zero probes: bank the
                                                 # predicted configs
  python tools/autotune.py --ops adam,layer_norm
  python tools/autotune.py --shape adam=1048576 \\
      --shape layer_norm=16384x768               # override sweep shapes
  python tools/autotune.py --cache /path/tune.json --probes 5
  python tools/autotune.py --dry-run             # tiny shapes, interpret
                                                 # mode, CPU — the tier-1
                                                 # smoke of the harness
  python tools/autotune.py --bank cpu-interpret  # refresh the committed
                                                 # tools/tuned/ cache
                                                 # (exhaustive, so the
                                                 # fit rows stay whole)

--dry-run never concludes "xla" (interpreter wall time says nothing
about Mosaic), defaults its cache to a throwaway file, and REFUSES to
write into tools/tuned/ — a CI smoke cannot poison the banked fleet
caches; only --bank (validated by tools/tunecheck.py afterwards) may
write there.
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _parse_shape(text):
    return tuple(int(d) for d in text.lower().split("x"))


def _under_tuned_dir(path, tuned_dir):
    try:
        return os.path.commonpath(
            [os.path.abspath(path), os.path.abspath(tuned_dir)]) == \
            os.path.abspath(tuned_dir)
    except ValueError:  # pragma: no cover - different drives (win)
        return False


def main(argv=None):
    from paddle_tpu.ops.pallas import autotune as at
    from paddle_tpu.ops import pallas_dispatch as pd

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ops", default=",".join(pd.PALLAS_OPS),
                    help="comma-separated op names to sweep")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="OP=DIMxDIM",
                    help="sweep shape override, e.g. layer_norm=4096x768"
                         " (repeatable)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--mesh-axes", default=None, metavar="AXIS=N,...",
                    help="mesh axes of the compile the cache will serve, "
                         "e.g. dp=8 — must match BuildStrategy.mesh_axes "
                         "or the trace-time lookup falls back to the "
                         "mesh-less key (default: no mesh in the key, "
                         "serving every topology)")
    ap.add_argument("--probes", type=int, default=3,
                    help="timed calls per candidate (best-of)")
    ap.add_argument("--top-k", type=int, default=3, metavar="K",
                    help="measure only the K best cost-model-predicted "
                         "candidates (0 = exhaustive sweep)")
    ap.add_argument("--cost-model-only", action="store_true",
                    help="measure NOTHING: bank the model's top "
                         "predicted config per key (zero probes)")
    ap.add_argument("--cache", default=None,
                    help="cache JSON path (default: %s or ~/.cache/"
                         "paddle_tpu/pallas_autotune.json)"
                         % at.DEFAULT_CACHE_ENV)
    ap.add_argument("--candidate-deadline-s", type=float, default=120.0,
                    help="wall budget per candidate incl. compile")
    ap.add_argument("--dry-run", action="store_true",
                    help="tiny shapes + interpret mode + 1 probe: "
                         "exercises the sweep harness itself on CPU")
    ap.add_argument("--bank", default=None, metavar="BACKEND",
                    help="refresh the committed tools/tuned/{BACKEND}"
                         ".json: exhaustive sweep over the banking grid "
                         "(cpu-interpret = interpret-mode multi-shape "
                         "grid; anything else = DEFAULT_SHAPES on the "
                         "attached backend)")
    args = ap.parse_args(argv)

    ops = [o.strip() for o in args.ops.split(",") if o.strip()]
    unknown = sorted(set(ops) - set(pd.PALLAS_OPS))
    if unknown:
        ap.error("unknown ops %r (available: %s)"
                 % (unknown, ",".join(pd.PALLAS_OPS)))
    if args.dry_run and args.bank:
        ap.error("--dry-run and --bank are mutually exclusive (a smoke "
                 "run must never write the committed caches)")
    if args.cost_model_only and args.bank:
        ap.error("--cost-model-only and --bank are mutually exclusive: "
                 "the committed caches hold MEASURED rows (the cost "
                 "model fits learn from them) — a zero-probe bank would "
                 "pass tunecheck's format/coverage gates while teaching "
                 "future fits nothing")
    mesh_axes = None
    if args.mesh_axes:
        try:
            mesh_axes = {a: int(n) for a, n in
                         (item.split("=") for item in
                          args.mesh_axes.split(","))}
        except ValueError:
            ap.error("bad --mesh-axes %r (want AXIS=N,...)"
                     % args.mesh_axes)

    bank_interpret = None
    per_op_shapes = None
    if args.bank:
        name = args.bank
        bank_interpret = name.endswith("-interpret")
        if bank_interpret:
            per_op_shapes = {op: list(at.BANK_SHAPES.get(op, ()))
                             for op in ops}
        else:
            per_op_shapes = {op: [at.DEFAULT_SHAPES[op]] for op in ops}
        cache_path = os.path.join(at.tuned_dir(), name + ".json")
    else:
        shapes = dict(at.DRY_SHAPES if args.dry_run
                      else at.DEFAULT_SHAPES)
        for item in args.shape:
            op, _, dims = item.partition("=")
            if op not in shapes or not dims:
                ap.error("bad --shape %r (want OP=DIMxDIM)" % item)
            shapes[op] = _parse_shape(dims)
        per_op_shapes = {op: [shapes[op]] for op in ops}
        cache_path = args.cache
        if cache_path is None and args.dry_run:
            fd, cache_path = tempfile.mkstemp(
                prefix="pallas_autotune_dry_", suffix=".json")
            os.close(fd)

    if args.dry_run and cache_path and \
            _under_tuned_dir(cache_path, at.tuned_dir()):
        ap.error("--dry-run refuses to write into tools/tuned/ (%s): "
                 "the committed banked caches are refreshed by --bank "
                 "only" % cache_path)

    meta = None
    if args.bank:
        meta = {"backend": args.bank,
                "interpret": bool(bank_interpret),
                "model_version": at.cm.MODEL_VERSION,
                "grid": {op: [list(s) for s in shp]
                         for op, shp in per_op_shapes.items()}}
    cache = at.AutotuneCache(cache_path, meta=meta)

    probes = 1 if args.dry_run else args.probes
    interpret = True if (args.dry_run or bank_interpret) else None
    # banking keeps the rows whole (the fit learns from ALL of them);
    # --top-k 0 is the explicit exhaustive switch elsewhere
    top_k = None if (args.bank or args.top_k <= 0 or
                     args.cost_model_only) else args.top_k
    candidates = None
    summaries = {}
    ok = True
    for op in ops:
        op_sums = []
        for shape in per_op_shapes.get(op, ()):
            if args.bank and bank_interpret:
                candidates = at.BANK_CANDIDATES.get(op)
            try:
                op_sums.append(at.autotune_op(
                    op, shape, dtype=args.dtype, probes=probes,
                    interpret=interpret, cache=cache,
                    candidates=candidates, mesh_axes=mesh_axes,
                    candidate_deadline_s=args.candidate_deadline_s,
                    top_k=top_k,
                    cost_model_only=args.cost_model_only))
            except Exception as e:  # one broken sweep must not eat the rest
                op_sums.append({"op": op, "shape": list(shape),
                                "error": "%s: %s"
                                % (type(e).__name__, e)})
                ok = False
        summaries[op] = op_sums[0] if len(op_sums) == 1 else op_sums
    print(json.dumps({
        "metric": "pallas_autotune",
        "dry_run": bool(args.dry_run),
        "bank": args.bank,
        "top_k": top_k,
        "cost_model_only": bool(args.cost_model_only),
        "cache": cache.path,
        "entries": len(cache),
        "ok": ok and all(
            "entry" in s
            for sums in summaries.values()
            for s in (sums if isinstance(sums, list) else [sums])),
        "sweeps": summaries,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
