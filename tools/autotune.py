#!/usr/bin/env python
"""Pallas kernel autotuner CLI.

Sweeps each kernel's block configs per (op, shape, dtype, topology,
backend) with bounded probes, persists the winners (or the XLA-fallback
verdict) in the JSON cache `CompiledProgram` loads at trace time via
``BuildStrategy.pallas_tune_cache``, and prints ONE JSON summary line.

Usage:
  python tools/autotune.py                       # all ops, chip shapes
  python tools/autotune.py --ops adam,layer_norm
  python tools/autotune.py --shape adam=1048576 \\
      --shape layer_norm=16384x768               # override sweep shapes
  python tools/autotune.py --cache /path/tune.json --probes 5
  python tools/autotune.py --dry-run             # tiny shapes, interpret
                                                 # mode, CPU — the tier-1
                                                 # smoke of the harness

--dry-run never concludes "xla" (interpreter wall time says nothing
about Mosaic) and defaults its cache to a throwaway file so a CI run
cannot poison the real fleet cache.
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _parse_shape(text):
    return tuple(int(d) for d in text.lower().split("x"))


def main(argv=None):
    from paddle_tpu.ops.pallas import autotune as at
    from paddle_tpu.ops import pallas_dispatch as pd

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ops", default=",".join(pd.PALLAS_OPS),
                    help="comma-separated op names to sweep")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="OP=DIMxDIM",
                    help="sweep shape override, e.g. layer_norm=4096x768"
                         " (repeatable)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--mesh-axes", default=None, metavar="AXIS=N,...",
                    help="mesh axes of the compile the cache will serve, "
                         "e.g. dp=8 — must match BuildStrategy.mesh_axes "
                         "or the trace-time lookup misses (default: no "
                         "mesh in the key)")
    ap.add_argument("--probes", type=int, default=3,
                    help="timed calls per candidate (best-of)")
    ap.add_argument("--cache", default=None,
                    help="cache JSON path (default: %s or ~/.cache/"
                         "paddle_tpu/pallas_autotune.json)"
                         % at.DEFAULT_CACHE_ENV)
    ap.add_argument("--candidate-deadline-s", type=float, default=120.0,
                    help="wall budget per candidate incl. compile")
    ap.add_argument("--dry-run", action="store_true",
                    help="tiny shapes + interpret mode + 1 probe: "
                         "exercises the sweep harness itself on CPU")
    args = ap.parse_args(argv)

    ops = [o.strip() for o in args.ops.split(",") if o.strip()]
    unknown = sorted(set(ops) - set(pd.PALLAS_OPS))
    if unknown:
        ap.error("unknown ops %r (available: %s)"
                 % (unknown, ",".join(pd.PALLAS_OPS)))
    mesh_axes = None
    if args.mesh_axes:
        try:
            mesh_axes = {a: int(n) for a, n in
                         (item.split("=") for item in
                          args.mesh_axes.split(","))}
        except ValueError:
            ap.error("bad --mesh-axes %r (want AXIS=N,...)"
                     % args.mesh_axes)
    shapes = dict(at.DRY_SHAPES if args.dry_run else at.DEFAULT_SHAPES)
    for item in args.shape:
        op, _, dims = item.partition("=")
        if op not in shapes or not dims:
            ap.error("bad --shape %r (want OP=DIMxDIM)" % item)
        shapes[op] = _parse_shape(dims)

    cache_path = args.cache
    if cache_path is None and args.dry_run:
        fd, cache_path = tempfile.mkstemp(prefix="pallas_autotune_dry_",
                                          suffix=".json")
        os.close(fd)
    cache = at.AutotuneCache(cache_path)

    probes = 1 if args.dry_run else args.probes
    interpret = True if args.dry_run else None
    summaries = {}
    ok = True
    for op in ops:
        try:
            summaries[op] = at.autotune_op(
                op, shapes[op], dtype=args.dtype, probes=probes,
                interpret=interpret, cache=cache, mesh_axes=mesh_axes,
                candidate_deadline_s=args.candidate_deadline_s)
        except Exception as e:  # one broken sweep must not eat the rest
            summaries[op] = {"op": op, "error": "%s: %s"
                             % (type(e).__name__, e)}
            ok = False
    print(json.dumps({
        "metric": "pallas_autotune",
        "dry_run": bool(args.dry_run),
        "cache": cache.path,
        "entries": len(cache),
        "ok": ok and all("entry" in s for s in summaries.values()),
        "sweeps": summaries,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
