#!/usr/bin/env python
"""Standalone pod rendezvous service — orchestrator glue.

Runs a :class:`paddle_tpu.framework.transport.CoordServer`: the
stdlib-TCP service holding the pod's coordination KV state (gather
rounds with sticky completion, tombstones, join announcements,
heartbeats). Deploy ONE per pod — as a sidecar on host 0, a k8s
Service, or anywhere every host can reach over TCP — and point each
host's ``SocketCoordinator(address, n_hosts, host_id)`` at it. No
shared filesystem is needed anywhere.

Liveness: with ``--hb-deadline-s`` armed (the default), any host whose
heartbeat goes stale past the deadline is tombstoned by the server's
monitor — survivors observe the tombstone on their next heartbeat or
gather poll and fire their loss hooks (mesh re-init), and the fenced
host must rejoin through the admission protocol, never resume.

The service holds no MODEL state, so losing it never loses training
progress — but it does hold the coordination state (in-flight rounds,
tombstones) in memory. Two distinct failure grades:

  * a dropped CONNECTION (network blip, proxy restart) is fully
    transparent: clients reconnect/retry through their RetryPolicy
    (~5-10s budget by default; pass `retry_policy=` for more) and
    re-send idempotently against the intact state;
  * a service RESTART starts from empty state: hosts blocked in a
    round surface CoordinationError and the job restarts from its
    checkpoints (the resilience layer's ordinary recovery) — state
    snapshot/replay for seamless restarts is a ROADMAP follow-on.

Run it under a supervisor either way.

Usage:
  python tools/coordsvc.py --n-hosts N|auto [--port P] [--host ADDR]
                           [--hb-deadline-s S]

``--n-hosts auto`` starts the service without a fixed pod size: the
size is learned from the FIRST hello that carries one (every
SocketCoordinator/CoordClient hello does) and is fixed for the
service's lifetime — later hellos must agree. This is how elastic
group sizes (e.g. the serving fleet) avoid templating N into two
places; until that first hello, every other op answers a loud
"pod size not learned yet" error.

Prints one JSON line ``{"address": "host:port", "n_hosts": N}`` once
listening (orchestrators parse it to template the worker env;
``n_hosts`` is null in auto mode), then serves until SIGTERM/SIGINT.
"""
import argparse
import json
import signal
import socket
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n-hosts", required=True,
                    help="pod size (host ids 0..N-1), or 'auto' to "
                         "learn it from the first hello")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP port (0 = ephemeral, printed on stdout)")
    ap.add_argument("--host", default="0.0.0.0",
                    help="bind address (default: all interfaces)")
    ap.add_argument("--advertise-host", default=None,
                    help="hostname/IP printed in the address workers "
                         "dial (default: the bind address, or this "
                         "machine's hostname when binding 0.0.0.0 — "
                         "a wildcard bind address is not dialable)")
    ap.add_argument("--hb-deadline-s", type=float, default=10.0,
                    help="heartbeat staleness deadline; a host silent "
                         "past it is tombstoned (<= 0 disables the "
                         "monitor — losses then need mark_lost or a "
                         "gather deadline)")
    args = ap.parse_args(argv)
    if args.n_hosts == "auto":
        n_hosts = None
    else:
        try:
            n_hosts = int(args.n_hosts)
        except ValueError:
            ap.error("--n-hosts must be an integer or 'auto', got %r"
                     % args.n_hosts)
    from paddle_tpu.framework.transport import CoordServer
    hb = args.hb_deadline_s if args.hb_deadline_s > 0 else None
    server = CoordServer(n_hosts, port=args.port, host=args.host,
                         hb_deadline_s=hb).start()
    # the printed address is what orchestrators template into every
    # worker's SocketCoordinator — it must be DIALABLE from remote
    # hosts, and a wildcard bind address is not
    bind_host, port = server.address.rsplit(":", 1)
    adv = args.advertise_host
    if adv is None:
        adv = socket.gethostname() \
            if bind_host in ("0.0.0.0", "::", "") else bind_host
    print(json.dumps({"address": "%s:%s" % (adv, port),
                      "bind": server.address,
                      "n_hosts": n_hosts,
                      "hb_deadline_s": hb}), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
