#!/usr/bin/env python
"""Standalone pod rendezvous service — orchestrator glue.

Runs a :class:`paddle_tpu.framework.transport.CoordServer`: the
stdlib-TCP service holding the pod's coordination KV state (gather
rounds with sticky completion, tombstones, join announcements,
heartbeats). Deploy ONE per pod — as a sidecar on host 0, a k8s
Service, or anywhere every host can reach over TCP — and point each
host's ``SocketCoordinator(address, n_hosts, host_id)`` at it. No
shared filesystem is needed anywhere.

Liveness: with ``--hb-deadline-s`` armed (the default), any host whose
heartbeat goes stale past the deadline is tombstoned by the server's
monitor — survivors observe the tombstone on their next heartbeat or
gather poll and fire their loss hooks (mesh re-init), and the fenced
host must rejoin through the admission protocol, never resume.

High availability — the service holds no MODEL state, but it does hold
the coordination state (in-flight rounds, tombstones). Three grades of
protection, composable:

  * a dropped CONNECTION (network blip, proxy restart) is fully
    transparent: clients reconnect/retry through their RetryPolicy
    (~5-10s budget by default) and re-send idempotently;
  * ``--snapshot-path`` persists periodic state snapshots and reloads
    them on start, so a SUPERVISED RESTART of a solo service resumes
    in-flight rounds instead of aborting them (liveness leases are
    refreshed on load — restart grace);
  * ``--peers`` wires this member into a TERM-replicated group: one
    primary plus warm standbys. The primary streams every mutating op
    to the standbys; on primary loss (judged by the same
    ``--hb-deadline-s`` staleness bound) the lowest-index live standby
    promotes with a bumped term, clients fail over inside their retry
    budget (pass every member's address to SocketCoordinator:
    "h:p0,h:p1"), and a stale ex-primary is fenced by term — rejected
    by clients AND demoted by its peers' replication stream.

Run each member under a supervisor either way.

Usage:
  python tools/coordsvc.py --n-hosts N|auto [--port P] [--host ADDR]
      [--hb-deadline-s S] [--snapshot-path F] [--snapshot-every-s S]
      [--peers a:p0,a:p1,... --repl-index I [--standby]]
  python tools/coordsvc.py --status ADDR[,ADDR...]

``--peers`` is the ordered endpoint list of the WHOLE group (own entry
included); ``--repl-index`` is this member's position in it — the
index order is the promotion priority. Boot exactly one member without
``--standby`` (the initial primary); a RESTARTED ex-primary relaunched
with its original flags probes its peers first and demotes itself to
standby when it finds a higher-term incumbent, so the same command
line is safe across the whole lifecycle.

``--status`` prints one JSON line per probed member (role, term,
stream position, replication lag) and exits 0 when a primary answered,
2 otherwise — the operator/orchestrator health probe.

``--n-hosts auto`` starts the service without a fixed pod size: the
size is learned from the FIRST hello that carries one (every
SocketCoordinator/CoordClient hello does) and is fixed for the
service's lifetime — later hellos must agree. This is how elastic
group sizes (e.g. the serving fleet) avoid templating N into two
places; until that first hello, every other op answers a loud
"pod size not learned yet" error.

Prints one JSON line ``{"address": "host:port", "n_hosts": N, ...}``
once listening (orchestrators parse it to template the worker env;
``n_hosts`` is null in auto mode), then serves until SIGTERM/SIGINT.
"""
import argparse
import json
import signal
import socket
import sys
import threading


def probe_status(addresses):
    """--status: probe each member; returns (exit_code, reports)."""
    from paddle_tpu.framework.transport import _probe_status
    reports = []
    saw_primary = False
    for addr in addresses:
        st = _probe_status(addr, timeout_s=2.0)
        if st is None:
            reports.append({"address": addr, "reachable": False})
            continue
        st["reachable"] = True
        st.setdefault("address", addr)
        saw_primary = saw_primary or st.get("role") == "primary"
        reports.append(st)
    return (0 if saw_primary else 2), reports


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n-hosts",
                    help="pod size (host ids 0..N-1), or 'auto' to "
                         "learn it from the first hello")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP port (0 = ephemeral, printed on stdout)")
    ap.add_argument("--host", default="0.0.0.0",
                    help="bind address (default: all interfaces)")
    ap.add_argument("--advertise-host", default=None,
                    help="hostname/IP printed in the address workers "
                         "dial (default: the bind address, or this "
                         "machine's hostname when binding 0.0.0.0 — "
                         "a wildcard bind address is not dialable)")
    ap.add_argument("--hb-deadline-s", type=float, default=10.0,
                    help="heartbeat staleness deadline; a host silent "
                         "past it is tombstoned, and a standby judges "
                         "the primary dead by the same bound (<= 0 "
                         "disables the monitor AND auto-promotion)")
    ap.add_argument("--snapshot-path", default=None,
                    help="persist periodic state snapshots here and "
                         "reload on start — a supervised restart "
                         "resumes in-flight rounds instead of "
                         "aborting them")
    ap.add_argument("--snapshot-every-s", type=float, default=5.0,
                    help="snapshot cadence (with --snapshot-path)")
    ap.add_argument("--peers", default=None,
                    help="ordered comma-joined endpoint list of the "
                         "WHOLE replication group (own entry "
                         "included); index order = promotion priority")
    ap.add_argument("--repl-index", type=int, default=0,
                    help="this member's position in --peers")
    ap.add_argument("--standby", action="store_true",
                    help="boot in standby role (wait for the "
                         "primary's replication stream)")
    ap.add_argument("--repl-sync-timeout-s", type=float, default=2.0,
                    help="bound on waiting for standby acks before "
                         "answering a round-mutating op (a dead "
                         "standby is dropped from the wait set)")
    ap.add_argument("--status", default=None, metavar="ADDR[,ADDR...]",
                    help="probe the given member(s) and print one "
                         "JSON status line each; exit 0 iff a "
                         "primary answered")
    args = ap.parse_args(argv)
    if args.status:
        code, reports = probe_status(
            [a.strip() for a in args.status.split(",") if a.strip()])
        for r in reports:
            print(json.dumps(r), flush=True)
        return code
    if args.n_hosts is None:
        ap.error("--n-hosts is required (or use --status)")
    if args.n_hosts == "auto":
        n_hosts = None
    else:
        try:
            n_hosts = int(args.n_hosts)
        except ValueError:
            ap.error("--n-hosts must be an integer or 'auto', got %r"
                     % args.n_hosts)
    from paddle_tpu.framework.transport import CoordServer
    hb = args.hb_deadline_s if args.hb_deadline_s > 0 else None
    server = CoordServer(n_hosts, port=args.port, host=args.host,
                         hb_deadline_s=hb,
                         snapshot_path=args.snapshot_path,
                         snapshot_every_s=args.snapshot_every_s)
    if args.peers:
        peers = [p.strip() for p in args.peers.split(",") if p.strip()]
        if not 0 <= args.repl_index < len(peers):
            ap.error("--repl-index %d out of range for %d peers"
                     % (args.repl_index, len(peers)))
        server.configure_replication(
            args.repl_index, peers, standby=args.standby,
            sync_timeout_s=args.repl_sync_timeout_s)
    server.start()
    # the printed address is what orchestrators template into every
    # worker's SocketCoordinator — it must be DIALABLE from remote
    # hosts, and a wildcard bind address is not
    bind_host, port = server.address.rsplit(":", 1)
    adv = args.advertise_host
    if adv is None:
        adv = socket.gethostname() \
            if bind_host in ("0.0.0.0", "::", "") else bind_host
    print(json.dumps({"address": "%s:%s" % (adv, port),
                      "bind": server.address,
                      "n_hosts": n_hosts,
                      "hb_deadline_s": hb,
                      "role": server.state.role,
                      "term": server.state.term,
                      "repl_index": args.repl_index if args.peers
                      else None,
                      "snapshot_path": args.snapshot_path}),
          flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
