#!/usr/bin/env python
"""Startup/readiness probe for a serving artifact — orchestrator glue.

Loads the StableHLO artifact under DIR in THIS process, optionally
warms every exported bucket, optionally fires one synthetic
zero-request at the smallest bucket, and prints the resulting
``ServingPredictor.health()`` as JSON. It validates the artifact and
the deserialize->compile->execute path end to end — a broken or
unloadable artifact exits 2 before a replica is ever routed traffic.
Because it is a fresh predictor, the counters reflect the PROBE's own
requests, not a live replica's history: to rotate on accumulated
degradation, run the probe requests with ``--strict --deadline-s`` so
a miss/degrade DURING the probe fails it, or export the live
replica's own ``health()`` via your serving endpoint.

Usage:
  python tools/serving_probe.py DIR [--warmup] [--no-request]
                                    [--deadline-s S] [--strict]
                                    [--metrics-url URL]

``--metrics-url`` additionally scrapes a ``resilience.serve_metrics``
pull endpoint (Prometheus text exposition) and folds the event totals
into the report under ``"metrics"`` — per-host labels included — so one
probe answers both "is the replica loadable" and "what has the
resilience layer been seeing". An unreachable/unparsable endpoint sets
``metrics_error`` and fails a ``--strict`` probe.

Exit codes:
  0  ready — every exported bucket warm, not saturated (with
     ``--strict``: additionally status == "ok", i.e. the probe request
     itself saw no deadline miss / degraded serve / error, and the
     --metrics-url scrape, when requested, succeeded)
  1  loaded but NOT ready (cold buckets / saturated; strict: degraded)
  2  artifact broken or unreadable — replace the replica
"""
import argparse
import json
import sys


def probe(dirname, warmup=False, request=True, deadline_s=None):
    """Load + exercise the artifact; returns the health() snapshot."""
    import numpy as np
    from paddle_tpu.serving import load_serving_artifact
    pred = load_serving_artifact(dirname, deadline_s=deadline_s)
    if warmup:
        pred.warmup()
    if request:
        # one synthetic request at the smallest bucket: proves the
        # deserialize->compile->execute path end to end (and warms that
        # bucket as a side effect)
        bucket = sorted(pred._fns)[0]
        spec = pred._meta["buckets"][str(bucket)]["feeds"]
        feeds = {f["name"]: np.zeros(f["shape"],
                                     dtype=np.dtype(f["dtype"]))
                 for f in spec}
        from paddle_tpu.framework import resilience
        try:
            pred.run(feeds)
        except resilience.DeadlineExceededError:
            # already counted in the predictor's stats: a slow-but-
            # loadable artifact is the cold/degraded exit-1 path, not
            # the broken exit-2 one
            pass
    return pred.health()


def scrape_metrics(url, timeout_s=5.0):
    """Scrape a resilience.serve_metrics endpoint; returns a summary
    dict {"url", "samples", "events_total": {kind[/host]: n}} — plus a
    "feed" section with the elastic-data-plane series
    (feed_rebalance_total, feed_epoch/feed_stream_lag per host), a
    "transport" section with the pod-transport series
    (transport_reconnects_total, transport_failovers_total,
    transport_heartbeat_lag per host, and the coordination-plane-HA
    series: transport_term per host + transport_replication_lag), a
    "router" section with the serving-fleet series
    (router_requests_total{outcome=}, router_queue_depth,
    router_replica_inflight per replica, the router_batch_size
    histogram samples), a "qos" section with every ``tenant=``-
    labelled router series (per-tenant requests/expired-deadline
    counters and queue-depth gauges, keyed ``.../tenant:<id>`` —
    kept apart from "router" so the aggregate keys never collide),
    an "obs" section with the tracing layer's
    series (the ``executor_step_seconds{kind=}`` step-phase histogram
    samples and ``trace_spans_dropped_total`` — nonzero means the
    span ring overflowed and any merged timeline is missing spans)
    a "bytes" section with the compressed-movement raw-vs-wire
    pairs (collective/stateship/ckpt _bytes_total{kind=}) when the
    replica exports any, a "buddy" section with the buddy-checkpoint
    tier's series (buddy_snapshot_bytes_total{kind=} raw/wire pairs,
    buddy_restore_total{outcome=}, the per-host buddy_generation and
    buddy_resident_bytes gauges plus the p2p-tier buddy_delta_ratio /
    buddy_p2p_fetch_ms gauges — ``--strict`` FAILS the probe when live
    hosts' generation gauges diverge by more than one window, because
    a lagging mailbox turns the next host loss into a full disk
    rewind, and when the COORDINATOR's resident-bytes gauge exceeds a
    metadata-sized bound, because snapshot payloads parked on the
    coordination plane re-impose the memory ceiling the p2p mailboxes
    removed), and a "faults"
    section with the fault-plane
    series (failpoint_hits_total{site=}, the faultinject_armed gauge
    and numeric_fault_total{policy=,culprit=}) — ``--strict`` FAILS
    the probe when the armed gauge is nonzero, because live failpoint
    schedules in a production replica mean requests will be failed on
    purpose — or raises (caller folds failures into the health
    report)."""
    import urllib.request
    from paddle_tpu.framework.resilience import (METRIC_PREFIX,
                                                 parse_metrics_text)
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        text = resp.read().decode("utf-8")
    samples = parse_metrics_text(text)
    events, feed, transport, router, bytes_sec = {}, {}, {}, {}, {}
    obs_sec, qos, faults, elastic, buddy = {}, {}, {}, {}, {}
    for name, labels, value in samples:
        if name.startswith(METRIC_PREFIX + "_pp_"):
            # the elastic pipeline-re-cut series (pp_recut_total,
            # pp_recut_ms, pp_slots, pp_live_hosts) fold under one
            # "elastic" group — --strict cross-checks pp_slots
            # against pp_live_hosts (see elastic_topology_flags)
            elastic[name[len(METRIC_PREFIX) + 1:]] = value
            continue
        if name.startswith(METRIC_PREFIX + "_buddy_"):
            # the buddy-checkpoint tier folds under one "buddy" group:
            # the snapshot raw/wire byte pairs, restore outcomes and
            # the per-host last-published-generation gauges. Claimed
            # BEFORE the generic *_bytes_total fold so the snapshot
            # byte pairs don't scatter into "bytes" — --strict
            # cross-checks the generation gauges across hosts (see
            # buddy_generation_flags)
            key = name[len(METRIC_PREFIX) + 1:]
            if "kind" in labels:
                key += "/" + labels["kind"]
            if "outcome" in labels:
                key += "/" + labels["outcome"]
            if "host" in labels:
                key += "/host" + labels["host"]
            buddy[key] = value
            continue
        if name.startswith(METRIC_PREFIX + "_failpoint_") \
                or name.startswith(METRIC_PREFIX + "_faultinject_") \
                or name.startswith(METRIC_PREFIX + "_numeric_fault_"):
            # the fault plane folds under one "faults" group: the
            # failpoint fired-hit counters by site, the armed gauge
            # (nonzero = live failpoints — production poison) and the
            # numeric-fault counters by (policy, culprit)
            key = name[len(METRIC_PREFIX) + 1:]
            if "site" in labels:
                key += "/site:" + labels["site"]
            if "policy" in labels:
                key += "/" + labels["policy"]
            if "culprit" in labels:
                key += "/" + labels["culprit"]
            faults[key] = value
            continue
        if name == METRIC_PREFIX + "_events_total":
            key = labels.get("kind", "?")
            if "host" in labels:
                key += "/host" + labels["host"]
            events[key] = value
        elif name.startswith(METRIC_PREFIX + "_executor_step_seconds") \
                or name.startswith(METRIC_PREFIX + "_trace_spans"):
            # the obs tentpole's series fold under one "obs" group
            key = name[len(METRIC_PREFIX) + 1:]
            if "kind" in labels:
                key += "/" + labels["kind"]
            if "le" in labels:
                key += "/le" + labels["le"]
            obs_sec[key] = value
        elif name.startswith(METRIC_PREFIX + "_router_") \
                and "tenant" in labels:
            # the tenant-labelled QoS series fold under their own
            # "qos" group BEFORE the router fold — a tenant-labelled
            # router_requests_total sample colliding into the
            # aggregate's key would silently overwrite it. The key
            # mirrors the router section's, ending "/tenant:<id>" so
            # qos_quota_flags can re-derive the aggregate key
            key = name[len(METRIC_PREFIX) + 1:]
            if "where" in labels:
                key += "/" + labels["where"]
            if "outcome" in labels:
                key += "/" + labels["outcome"]
            if "router" in labels:
                key += "/router" + labels["router"]
            key += "/tenant:" + labels["tenant"]
            qos[key] = value
        elif name.startswith(METRIC_PREFIX + "_router_") \
                or name.startswith(METRIC_PREFIX + "_fleet_"):
            # the router-TIER series (per-router queue/requests plus
            # the fleet_leader_term / fleet_target_replicas gauges)
            # all fold under the "router" group
            key = name[len(METRIC_PREFIX) + 1:]
            if "outcome" in labels:
                key += "/" + labels["outcome"]
            if "replica" in labels:
                key += "/replica" + labels["replica"]
            if "router" in labels:
                key += "/router" + labels["router"]
            if "le" in labels:
                key += "/le" + labels["le"]
            router[key] = value
        elif name.startswith(METRIC_PREFIX) \
                and name.endswith("_bytes_total"):
            key = name[len(METRIC_PREFIX) + 1:]
            key += "/" + labels.get("kind", "?")
            bytes_sec[key] = value
        elif name.startswith(METRIC_PREFIX + "_feed_") \
                or name.startswith(METRIC_PREFIX + "_transport_"):
            key = name[len(METRIC_PREFIX) + 1:]
            if "host" in labels:
                key += "/host" + labels["host"]
            section = feed if key.startswith("feed_") else transport
            section[key] = value
    out = {"url": url, "samples": len(samples), "events_total": events}
    if feed:
        out["feed"] = feed
    if transport:
        out["transport"] = transport
    if router:
        out["router"] = router
    if obs_sec:
        out["obs"] = obs_sec
    if qos:
        out["qos"] = qos
    if bytes_sec:
        out["bytes"] = bytes_sec
    if faults:
        out["faults"] = faults
    if elastic:
        out["elastic"] = elastic
    if buddy:
        out["buddy"] = buddy
    return out


def qos_quota_flags(summary):
    """Quota-accounting drift in a scrape summary (empty = healthy):
    per (outcome, router), the tenant-labelled
    ``router_requests_total`` series must sum EXACTLY to the
    aggregate series — both are bumped under the same lock on the
    same request, so any gap means an admission path recorded one
    side without the other (a shed that charged no tenant, a tenant
    series double-bump) and per-class SLO accounting cannot be
    trusted. ``--strict`` fails the probe on any drift."""
    flags = []
    qos = summary.get("qos", {})
    router = summary.get("router", {})
    sums = {}
    for k, v in qos.items():
        if not k.startswith("router_requests_total"):
            continue
        base = k.rpartition("/tenant:")[0]
        sums[base] = sums.get(base, 0) + v
    for base, total in sorted(sums.items()):
        agg = router.get(base)
        if agg is None or abs(agg - total) > 1e-9:
            flags.append("quota accounting drift on %s: tenant "
                         "series sum to %g, aggregate reads %s"
                         % (base, total, agg))
    return flags


def obs_overflow_flags(summary):
    """Span-ring overflow symptoms in a scrape summary (empty =
    healthy): a nonzero ``trace_spans_dropped_total`` means the
    tracing ring evicted spans, so any merged timeline pulled from
    this process is LYING by omission — ``--strict`` fails on it
    (raise PADDLE_TPU_TRACE_RING or pull /admin/trace more often)."""
    dropped = summary.get("obs", {}).get("trace_spans_dropped_total", 0)
    if dropped:
        return ["span ring overflowed: trace_spans_dropped_total=%g — "
                "merged timelines are missing spans" % dropped]
    return []


def term_regression_flags(summary):
    """Stale-primary symptoms in a scrape summary (empty = healthy):

      * any ``transport_stale_primary`` event — a client watched the
        replication term go BACKWARDS, i.e. an ex-primary woke up and
        answered from a stale term (the client refused it, but the
        zombie is still reachable and should be restarted/demoted);
      * per-host ``transport_term`` gauges disagreeing — some client
        is still pinned to a lower term than its peers observed, the
        split-brain smell term fencing exists to catch;
      * per-router ``fleet_leader_term`` gauges disagreeing — the
        router-tier twin of the transport check: a router pinned
        below its peers' admission-leader term is still trusting a
        stale ex-leader, and its enactments would be refused.

    ``--strict`` fails the probe on any of them."""
    flags = []
    stale = {k: v for k, v in summary.get("events_total", {}).items()
             if k.startswith("transport_stale_primary")}
    if stale:
        flags.append("stale-primary responses observed: %s"
                     % sorted(stale.items()))
    terms = {k: v for k, v in summary.get("transport", {}).items()
             if k.startswith("transport_term")}
    if len(set(terms.values())) > 1:
        flags.append("transport_term gauges disagree (a client is "
                     "pinned below the group term): %s"
                     % sorted(terms.items()))
    lterms = {k: v for k, v in summary.get("router", {}).items()
              if k.startswith("fleet_leader_term")}
    if len(set(lterms.values())) > 1:
        flags.append("fleet_leader_term gauges disagree (a router is "
                     "pinned below the admission-leader term): %s"
                     % sorted(lterms.items()))
    return flags


def elastic_topology_flags(summary):
    """Elastic pp-topology disagreement in a scrape summary (empty =
    healthy): after a pipeline re-cut the ``pp_slots`` gauge (slots
    the survivors' mesh carries) must never EXCEED ``pp_live_hosts``
    (the live-host count the same retarget event recorded) — more
    slots than surviving hosts means a torn re-cut left the pod
    planning stages onto capacity it no longer has. ``--strict``
    fails the probe on it."""
    el = summary.get("elastic", {})
    slots, live = el.get("pp_slots"), el.get("pp_live_hosts")
    if slots is not None and live is not None and slots > live:
        return ["pp re-cut topology disagreement: pp_slots=%g exceeds "
                "pp_live_hosts=%g — the surviving hosts cannot hold "
                "the mesh's slot count" % (slots, live)]
    return []


def buddy_generation_flags(summary):
    """Buddy-mailbox lag in a scrape summary (empty = healthy): the
    per-host ``buddy_generation`` gauges record the last window
    generation each live host streamed into its ring buddy's mailbox.
    Hosts legitimately straddle ONE window boundary (a scrape can land
    mid-round), but a spread beyond one window means some host's
    snapshots are not landing — its buddy's mailbox is going stale,
    and the next loss of that host becomes a full disk rewind
    (reason=buddy_stale) instead of the warm sub-window restore the
    tier exists for. ``--strict`` fails the probe on it."""
    gens = {k: v for k, v in summary.get("buddy", {}).items()
            if k.startswith("buddy_generation/")}
    if gens and max(gens.values()) - min(gens.values()) > 1:
        return ["buddy generation gauges diverge by more than one "
                "window (a stale mailbox rewinds to disk on the next "
                "host loss): %s" % sorted(gens.items())]
    return []


#: --strict ceiling for the coordinator's buddy_resident_bytes gauge.
#: The p2p tier keeps snapshot PAYLOADS in peer mailboxes and only a
#: {host: (gen, buddy, digest, nbytes)} metadata table (plus any
#: legacy-mode blobs) on the coordinator — metadata for even a large
#: pod fits well under 64 KiB, so anything above it means payload
#: bytes are parked on the coordination plane.
BUDDY_COORD_RESIDENT_BOUND = 64 * 1024


def buddy_resident_flags(summary, bound=BUDDY_COORD_RESIDENT_BOUND):
    """Coordinator memory-ceiling regression in a scrape summary
    (empty = healthy): the ``buddy_resident_bytes{host="coord"}``
    gauge records what the coordination plane itself holds for the
    buddy tier. Under the p2p-mailbox topology that must be METADATA
    sized — a value above ``bound`` means full snapshot payloads are
    resident on the coordinator (legacy ``put_blob`` traffic, or a
    regression in the ack-before-commit path), re-imposing the
    coordinator memory ceiling the tier was rebuilt to remove.
    ``--strict`` fails the probe on it."""
    resident = summary.get("buddy", {}).get(
        "buddy_resident_bytes/hostcoord")
    if resident is not None and resident > bound:
        return ["coordinator buddy residency is payload-sized: "
                "buddy_resident_bytes{host=coord}=%g exceeds the "
                "%d-byte metadata bound — snapshot payloads are "
                "parked on the coordination plane" % (resident, bound)]
    return []


def fault_plane_flags(summary):
    """Fault-plane poison in a scrape summary (empty = healthy): a
    nonzero ``faultinject_armed`` gauge means live failpoint schedules
    are armed in the scraped process — chaos-drill instrumentation
    that has NO business in a production replica (the next matching
    request will be failed on purpose). Fired-hit counters alone are
    only reported, not fatal: a drill that was since disarmed leaves
    its counters behind. ``--strict`` fails the probe on armed."""
    armed = summary.get("faults", {}).get("faultinject_armed", 0)
    if armed:
        return ["failpoints armed in the scraped process "
                "(faultinject_armed=%g): disarm the fault plane before "
                "serving production traffic" % armed]
    return []


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dirname", help="artifact dir (holds serving/)")
    ap.add_argument("--warmup", action="store_true",
                    help="compile every exported bucket before reporting")
    ap.add_argument("--no-request", dest="request", action="store_false",
                    help="skip the synthetic probe request")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="deadline for the probe request (seconds)")
    ap.add_argument("--strict", action="store_true",
                    help="also require status == 'ok': a deadline miss, "
                         "degraded serve or error during the probe "
                         "itself fails it — and, with --metrics-url, "
                         "any term regression (stale-primary symptom) "
                         "in the transport series, span-ring "
                         "overflow (trace_spans_dropped_total > 0) in "
                         "the obs series, tenant-vs-aggregate "
                         "quota-accounting drift in the qos series, "
                         "armed failpoints (faultinject_armed > 0) in "
                         "the faults series, a pp_slots-vs-"
                         "pp_live_hosts disagreement in the elastic "
                         "series, buddy_generation gauges diverging "
                         "by more than one window in the buddy series, "
                         "or a coordinator buddy_resident_bytes gauge "
                         "above the metadata-sized bound")
    ap.add_argument("--metrics-url", default=None,
                    help="scrape a resilience.serve_metrics endpoint and "
                         "fold the event totals into the report")
    args = ap.parse_args(argv)
    try:
        health = probe(args.dirname, warmup=args.warmup,
                       request=args.request, deadline_s=args.deadline_s)
    except Exception as e:
        print(json.dumps({"live": False, "ready": False,
                          "status": "broken", "error": str(e)}))
        return 2
    metrics_ok = True
    if args.metrics_url:
        try:
            health["metrics"] = scrape_metrics(args.metrics_url)
            flags = term_regression_flags(health["metrics"])
            if flags:
                # a term regression means a stale ex-primary is still
                # answering somewhere: serviceable today, split-brain
                # fuel tomorrow — loud always, fatal under --strict
                health["term_regression"] = flags
                metrics_ok = False
            oflags = obs_overflow_flags(health["metrics"])
            if oflags:
                # dropped spans mean the timeline is lying — loud
                # always, fatal under --strict
                health["obs_overflow"] = oflags
                metrics_ok = False
            qflags = qos_quota_flags(health["metrics"])
            if qflags:
                # tenant series out of step with the aggregate: the
                # per-class SLO numbers cannot be trusted — loud
                # always, fatal under --strict
                health["qos_drift"] = qflags
                metrics_ok = False
            fflags = fault_plane_flags(health["metrics"])
            if fflags:
                # armed failpoints in a production scrape: requests
                # WILL be failed on purpose — loud always, fatal
                # under --strict
                health["faults_armed"] = fflags
                metrics_ok = False
            eflags = elastic_topology_flags(health["metrics"])
            if eflags:
                # a re-cut mesh with more slots than live hosts is a
                # torn elastic transition — loud always, fatal under
                # --strict
                health["elastic_topology"] = eflags
                metrics_ok = False
            bflags = buddy_generation_flags(health["metrics"])
            if bflags:
                # a host whose buddy snapshots stopped landing is one
                # failure away from a disk rewind the tier was built
                # to avoid — loud always, fatal under --strict
                health["buddy_lag"] = bflags
                metrics_ok = False
            rflags = buddy_resident_flags(health["metrics"])
            if rflags:
                # payload-sized residency on the coordinator means the
                # memory ceiling the p2p mailboxes lifted is back —
                # loud always, fatal under --strict
                health["buddy_resident"] = rflags
                metrics_ok = False
        except Exception as e:
            # a loadable replica with a dead metrics endpoint is still
            # serviceable — degrade to exit 1 only under --strict
            health["metrics_error"] = str(e)
            metrics_ok = False
    print(json.dumps(health))
    ok = health["ready"] and (not args.strict or
                              (health["status"] == "ok" and metrics_ok))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
