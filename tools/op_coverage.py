#!/usr/bin/env python
"""Kernel-coverage audit: which registered ops does the test suite never
invoke? (VERDICT r4 next #9 — the distance between "every name resolves"
and "every kernel is oracle-checked".)

Usage:
  PADDLE_TPU_OP_COVERAGE=/tmp/opcov.txt python -m pytest tests/ -q
  python tools/op_coverage.py /tmp/opcov.txt
"""
import sys


def main(path):
    import paddle_tpu  # noqa: F401 - populate the registry
    from paddle_tpu.ops.registry import registered_ops
    exercised = set()
    try:
        with open(path) as f:
            exercised = {ln.strip() for ln in f if ln.strip()}
    except OSError:
        print("coverage file %s missing — run the suite with "
              "PADDLE_TPU_OP_COVERAGE=%s first" % (path, path))
        return 2
    registered = set(registered_ops())
    uncovered = sorted(registered - exercised)
    print("registered: %d  exercised: %d  uncovered: %d"
          % (len(registered), len(exercised), len(uncovered)))
    for n in uncovered:
        print("  " + n)
    return 0 if not uncovered else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/opcov.txt"))
