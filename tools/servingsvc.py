#!/usr/bin/env python
"""Serving-fleet daemons — orchestrator glue for paddle_tpu.serving_fleet.

Three subcommands, one process each:

  replica   one ServingPredictor replica: loads the StableHLO artifact,
            serves POST /infer over HTTP, and registers as a
            heartbeat-leased member of the fleet's coordination group
            (tools/coordsvc.py — run it with --hb-deadline-s armed;
            --n-hosts auto learns the group size from the first
            member). A RESTARTED replica finds itself fenced and
            re-admits through announce/admit/join automatically — just
            re-run the same command line. A replica SPAWNED by the
            autoscaler (a grown slot above the router range) passes
            --group-size with the post-resize size.
            --artifact-compress q8 requires the artifact's weights in
            the int8 block codec (export with weight_compress='q8')
            and refuses a full-precision export at load — the ship-
            bytes savings are asserted, never assumed.

  router    the fleet's front door — now a replicated TIER: run R of
            these (--router-id 0..R-1 --n-routers R), each serving
            /infer independently (clients take the whole endpoint
            list — `servingsvc.py client`, or FleetClient in code).
            Admission is enacted only by the term-stamped admission
            LEADER (lowest live router id); continuous micro-batching,
            least-loaded dispatch over fleet-wide shared in-flight
            counts, shed on a full queue, retry a dead replica's
            in-flight work on a sibling. POST /admin/deploy
            {"dir": ...} rolls a weight refresh across the fleet one
            replica at a time with zero dropped traffic.
            --autoscale arms the leader-gated replica autoscaler:
            queue-depth/shed-rate surges grow the fleet through the
            coordinator's dynamic `resize` op, and --spawn-template
            (placeholders {replica_id} {group_size} {coord}) is the
            command launched for each grown replica; a sustained idle
            window drains + removes grown replicas again. Spawned
            processes are SUPERVISED by this router process (announced
            as {"kind": "autoscale_spawn", "pid": ...} lines, reaped
            on shutdown) — production orchestrators should instead
            watch the fleet_autoscale events and actuate themselves.
            --tenant-classes arms multi-tenant QoS: per-tenant queues
            drained by weighted-fair queueing, token-bucket/in-flight
            quotas, and priority-classed brownout shedding under
            overload (see PORTING.md "Multi-tenant QoS"); requests
            carry x-tenant / x-deadline-ms / x-retry-budget headers.

  client    stdin/stdout failover client for a multi-router
            deployment: --routers URL[,URL...] (both tiers take
            endpoint LISTS — --coord for the coordination group,
            --routers for the router tier). Reads one JSON request per
            line ({"feeds": {name: rows}[, "deadline_s": S]}), rotates
            on connection error/5xx and replays idempotently by
            request token, writes one JSON line per result.

Each daemon prints ONE JSON line with its address once serving
(orchestrators parse it), then runs until SIGTERM/SIGINT.

Distributed tracing: launch any daemon with ``PADDLE_TPU_TRACE=1``
and it records obs spans (router queue/dispatch, replica serve,
coordination waits) with trace context propagated via the
``x-trace-id`` header; pull each process's spans from
``GET /admin/trace`` and merge them with ``tools/traceview.py`` into
one Perfetto timeline. See PORTING.md "Observability & tracing".

``--coord`` accepts a comma-joined endpoint LIST when the coordination
plane is a replicated coordsvc group (``--peers`` mode): members fail
over to the promoted standby transparently, so a coordinator SIGKILL
mid-deploy costs the fleet nothing.

Usage:
  python tools/servingsvc.py replica --coord HOST:PORT[,HOST:PORT...]
         --n-replicas N --replica-id I --artifact DIR [--port P]
         [--n-routers R] [--group-size G] [--no-warmup]
         [--max-in-flight M] [--deadline-s S]
  python tools/servingsvc.py router --coord HOST:PORT[,HOST:PORT...]
         --n-replicas N [--router-id I --n-routers R] [--port P]
         [--max-batch B] [--batch-deadline-s S] [--max-queue Q]
         [--request-deadline-s S] [--autoscale
          --spawn-template 'python tools/servingsvc.py replica
          --coord {coord} --n-replicas N --n-routers R
          --replica-id {replica_id} --group-size {group_size}
          --artifact DIR' [--autoscale-max M] ...]
  python tools/servingsvc.py client --routers URL[,URL...]
         [--deadline-s S]
"""
import argparse
import json
import signal
import sys
import threading


def _serve_until_signal(member, line, cleanup=None):
    print(json.dumps(line), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    if cleanup is not None:
        cleanup()
    member.close()
    return 0


def _template_spawner(template, coord):
    """Build the autoscaler's spawner from a command template with
    {replica_id}/{group_size}/{coord} placeholders. Spawned processes
    are tracked by replica id so ``spawn.stop`` (the autoscaler's
    stopper) can reap a drained, resized-away replica — without it a
    shrink leaves the process's HTTP listener and heartbeat thread
    running until router shutdown — and announced as one JSON line
    each so orchestrators/tests can adopt them."""
    import shlex
    import subprocess
    procs = []
    by_id = {}

    def spawn(replica_id, group_size):
        cmd = [a.format(replica_id=replica_id, group_size=group_size,
                        coord=coord) for a in shlex.split(template)]
        p = subprocess.Popen(cmd)
        procs.append(p)
        by_id[int(replica_id)] = p
        print(json.dumps({"kind": "autoscale_spawn", "pid": p.pid,
                          "replica_id": replica_id,
                          "group_size": group_size}), flush=True)
        return p

    def stop(replica_id):
        p = by_id.pop(int(replica_id), None)
        if p is None or p.poll() is not None:
            return
        p.terminate()
        try:
            p.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        print(json.dumps({"kind": "autoscale_stop", "pid": p.pid,
                          "replica_id": replica_id}), flush=True)

    spawn.procs = procs
    spawn.stop = stop
    return spawn


def _client_main(args):
    from paddle_tpu.serving_fleet import FleetClient
    client = FleetClient(args.routers,
                         request_deadline_s=args.deadline_s,
                         tenant=args.tenant,
                         retry_budget=args.retry_budget)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            out = client.infer(req["feeds"],
                               deadline_s=req.get("deadline_s"))
            out = dict(out, ok=True)
        except Exception as e:   # noqa: BLE001 - reported on the wire
            out = {"ok": False, "error": str(e),
                   "kind": type(e).__name__}
        print(json.dumps(out), flush=True)
    return 0


def _load_tenant_classes(spec):
    """--tenant-classes value -> config dict (inline JSON, or a JSON
    file via '@path'). Validation happens in parse_tenant_classes at
    router construction."""
    if not spec:
        return None
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            return json.load(f)
    return json.loads(spec)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser("replica", help="one serving replica")
    rp.add_argument("--coord", required=True,
                    help="coordsvc address (host:port), or a comma-"
                         "joined endpoint list for a replicated "
                         "coordsvc group (failover is transparent)")
    rp.add_argument("--n-replicas", type=int, required=True)
    rp.add_argument("--replica-id", type=int, required=True)
    rp.add_argument("--artifact", required=True,
                    help="artifact dir (holds serving/)")
    rp.add_argument("--port", type=int, default=0)
    rp.add_argument("--host", default="127.0.0.1")
    rp.add_argument("--n-routers", type=int, default=1,
                    help="router-tier size (group = replicas + "
                         "routers [+ grown slots])")
    rp.add_argument("--group-size", type=int, default=None,
                    help="the group's CURRENT total size — required "
                         "for a replica spawned into a GROWN slot "
                         "(id above the router range)")
    rp.add_argument("--no-warmup", dest="warmup", action="store_false")
    rp.add_argument("--max-in-flight", type=int, default=None)
    rp.add_argument("--deadline-s", type=float, default=None)
    rp.add_argument("--ctl-interval-s", type=float, default=0.1)
    rp.add_argument("--hb-interval-s", type=float, default=0.25)
    rp.add_argument("--join-timeout-s", type=float, default=30.0)
    rp.add_argument("--artifact-compress", default=None,
                    choices=("q8",),
                    help="require the artifact's weights in this "
                         "compressed form (q8 = the int8 block codec;"
                         " export with weight_compress='q8') — a "
                         "full-precision artifact is refused at load")

    ro = sub.add_parser("router", help="one fleet router (run "
                        "--n-routers of these for the HA tier)")
    ro.add_argument("--coord", required=True)
    ro.add_argument("--n-replicas", type=int, required=True)
    ro.add_argument("--router-id", type=int, default=0)
    ro.add_argument("--n-routers", type=int, default=1)
    ro.add_argument("--group-size", type=int, default=None)
    ro.add_argument("--port", type=int, default=0)
    ro.add_argument("--host", default="127.0.0.1")
    ro.add_argument("--max-batch", type=int, default=8)
    ro.add_argument("--batch-deadline-s", type=float, default=0.005)
    ro.add_argument("--max-queue", type=int, default=128)
    ro.add_argument("--request-deadline-s", type=float, default=10.0)
    ro.add_argument("--ctl-interval-s", type=float, default=0.1)
    ro.add_argument("--hb-interval-s", type=float, default=0.25)
    ro.add_argument("--join-timeout-s", type=float, default=30.0)
    ro.add_argument("--autoscale", action="store_true",
                    help="arm the leader-gated replica autoscaler")
    ro.add_argument("--spawn-template", default=None,
                    help="command template for grown replicas; "
                         "placeholders {replica_id} {group_size} "
                         "{coord}")
    ro.add_argument("--autoscale-min", type=int, default=None)
    ro.add_argument("--autoscale-max", type=int, default=None)
    ro.add_argument("--autoscale-interval-s", type=float, default=0.25)
    ro.add_argument("--autoscale-window", type=int, default=8)
    ro.add_argument("--autoscale-queue-depth", type=float, default=4.0)
    ro.add_argument("--autoscale-shed-rate", type=float, default=0.05)
    ro.add_argument("--autoscale-hysteresis", type=int, default=3)
    ro.add_argument("--autoscale-cooldown-s", type=float, default=5.0)
    ro.add_argument("--autoscale-high-queue-depth", type=float,
                    default=None,
                    help="grow when the HIGHEST-priority class queues"
                         " this deep (default: half the global "
                         "threshold) — needs --tenant-classes")
    ro.add_argument("--tenant-classes", default=None,
                    help="tenant QoS classes as JSON ('@file' reads a"
                         " file): {name: {weight, priority, rate, "
                         "burst, max_inflight, tenants}}; absent = "
                         "the classic single-FIFO router")
    ro.add_argument("--brownout-queue-depth", type=float, default=None,
                    help="queue depth that counts as a hot brownout "
                         "sample (default 0.75 * max-queue)")
    ro.add_argument("--brownout-shed-rate", type=float, default=0.5,
                    help="shed-rate delta that counts as a hot "
                         "brownout sample")
    ro.add_argument("--qos-interval-s", type=float, default=0.1,
                    help="brownout controller sampling interval")
    ro.add_argument("--qos-hysteresis", type=int, default=3,
                    help="consecutive hot/cool samples before the "
                         "brownout floor moves one class level")

    cl = sub.add_parser("client", help="stdin/stdout failover client")
    cl.add_argument("--routers", required=True,
                    help="comma-joined router endpoint list (URLs or "
                         "host:port)")
    cl.add_argument("--deadline-s", type=float, default=10.0)
    cl.add_argument("--tenant", default=None,
                    help="QoS identity sent as x-tenant on every "
                         "request (absent = the 'default' tenant)")
    cl.add_argument("--retry-budget", type=int, default=None,
                    help="max replica attempts a request may burn "
                         "across hops (x-retry-budget; absent = "
                         "retry until the deadline)")

    args = ap.parse_args(argv)
    if args.cmd == "client":
        return _client_main(args)
    if args.cmd == "replica":
        from paddle_tpu.serving_fleet import ReplicaMember
        member = ReplicaMember(
            args.artifact, args.coord, args.n_replicas,
            args.replica_id, port=args.port, host=args.host,
            warmup=args.warmup, max_in_flight=args.max_in_flight,
            deadline_s=args.deadline_s,
            ctl_interval_s=args.ctl_interval_s,
            hb_interval_s=args.hb_interval_s,
            join_timeout_s=args.join_timeout_s,
            n_routers=args.n_routers,
            group_size=args.group_size,
            artifact_compress=args.artifact_compress).start()
        return _serve_until_signal(
            member, {"kind": "replica", "replica_id": args.replica_id,
                     "addr": member.address,
                     "generation": member.generation})
    from paddle_tpu.serving_fleet import Autoscaler, FleetRouter
    router = FleetRouter(
        args.coord, args.n_replicas, port=args.port, host=args.host,
        max_batch=args.max_batch,
        batch_deadline_s=args.batch_deadline_s,
        max_queue=args.max_queue,
        request_deadline_s=args.request_deadline_s,
        ctl_interval_s=args.ctl_interval_s,
        hb_interval_s=args.hb_interval_s,
        join_timeout_s=args.join_timeout_s,
        router_id=args.router_id, n_routers=args.n_routers,
        group_size=args.group_size,
        tenant_classes=_load_tenant_classes(args.tenant_classes),
        brownout_queue_depth=args.brownout_queue_depth,
        brownout_shed_rate=args.brownout_shed_rate,
        qos_interval_s=args.qos_interval_s,
        qos_hysteresis=args.qos_hysteresis).start()
    auto, spawner = None, None
    if args.autoscale:
        if args.spawn_template:
            spawner = _template_spawner(args.spawn_template, args.coord)
        auto = Autoscaler(
            router, spawner=spawner,
            stopper=spawner.stop if spawner is not None else None,
            min_replicas=args.autoscale_min,
            max_replicas=args.autoscale_max,
            interval_s=args.autoscale_interval_s,
            window=args.autoscale_window,
            grow_queue_depth=args.autoscale_queue_depth,
            grow_shed_rate=args.autoscale_shed_rate,
            hysteresis=args.autoscale_hysteresis,
            cooldown_s=args.autoscale_cooldown_s,
            grow_high_queue_depth=args.autoscale_high_queue_depth
            ).start()

    def cleanup():
        if auto is not None:
            auto.close()
        for p in (spawner.procs if spawner is not None else ()):
            if p.poll() is None:
                p.terminate()

    return _serve_until_signal(
        router, {"kind": "router", "router_id": args.router_id,
                 "addr": router.address, "url": router.url},
        cleanup=cleanup)


if __name__ == "__main__":
    sys.exit(main())
