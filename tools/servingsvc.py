#!/usr/bin/env python
"""Serving-fleet daemons — orchestrator glue for paddle_tpu.serving_fleet.

Two subcommands, one process each:

  replica   one ServingPredictor replica: loads the StableHLO artifact,
            serves POST /infer over HTTP, and registers as a
            heartbeat-leased member of the fleet's coordination group
            (tools/coordsvc.py — run it with --hb-deadline-s armed;
            --n-hosts auto learns the group size from the first
            member). A RESTARTED replica finds itself fenced and
            re-admits through announce/admit/join automatically — just
            re-run the same command line.

  router    the fleet's front door: continuous micro-batching over the
            live replica set (coalesce up to --max-batch rows or
            --batch-deadline-s, least-loaded dispatch from the
            heartbeat/lost map, shed on a full queue, retry a dead
            replica's in-flight work on a sibling). POST
            /admin/deploy {"dir": ...} rolls a weight refresh across
            the fleet one replica at a time with zero dropped traffic.

Each prints ONE JSON line with its address once serving (orchestrators
parse it), then runs until SIGTERM/SIGINT.

``--coord`` accepts a comma-joined endpoint LIST when the coordination
plane is a replicated coordsvc group (``--peers`` mode): members fail
over to the promoted standby transparently, so a coordinator SIGKILL
mid-deploy costs the fleet nothing.

Usage:
  python tools/servingsvc.py replica --coord HOST:PORT[,HOST:PORT...]
         --n-replicas N --replica-id I --artifact DIR [--port P]
         [--no-warmup] [--max-in-flight M] [--deadline-s S]
  python tools/servingsvc.py router --coord HOST:PORT[,HOST:PORT...]
         --n-replicas N [--port P] [--max-batch B]
         [--batch-deadline-s S] [--max-queue Q]
         [--request-deadline-s S]
"""
import argparse
import json
import signal
import sys
import threading


def _serve_until_signal(member, line):
    print(json.dumps(line), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    member.close()
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser("replica", help="one serving replica")
    rp.add_argument("--coord", required=True,
                    help="coordsvc address (host:port), or a comma-"
                         "joined endpoint list for a replicated "
                         "coordsvc group (failover is transparent)")
    rp.add_argument("--n-replicas", type=int, required=True)
    rp.add_argument("--replica-id", type=int, required=True)
    rp.add_argument("--artifact", required=True,
                    help="artifact dir (holds serving/)")
    rp.add_argument("--port", type=int, default=0)
    rp.add_argument("--host", default="127.0.0.1")
    rp.add_argument("--no-warmup", dest="warmup", action="store_false")
    rp.add_argument("--max-in-flight", type=int, default=None)
    rp.add_argument("--deadline-s", type=float, default=None)
    rp.add_argument("--ctl-interval-s", type=float, default=0.1)
    rp.add_argument("--hb-interval-s", type=float, default=0.25)
    rp.add_argument("--join-timeout-s", type=float, default=30.0)

    ro = sub.add_parser("router", help="the fleet router")
    ro.add_argument("--coord", required=True)
    ro.add_argument("--n-replicas", type=int, required=True)
    ro.add_argument("--port", type=int, default=0)
    ro.add_argument("--host", default="127.0.0.1")
    ro.add_argument("--max-batch", type=int, default=8)
    ro.add_argument("--batch-deadline-s", type=float, default=0.005)
    ro.add_argument("--max-queue", type=int, default=128)
    ro.add_argument("--request-deadline-s", type=float, default=10.0)
    ro.add_argument("--ctl-interval-s", type=float, default=0.1)
    ro.add_argument("--hb-interval-s", type=float, default=0.25)
    ro.add_argument("--join-timeout-s", type=float, default=30.0)

    args = ap.parse_args(argv)
    if args.cmd == "replica":
        from paddle_tpu.serving_fleet import ReplicaMember
        member = ReplicaMember(
            args.artifact, args.coord, args.n_replicas,
            args.replica_id, port=args.port, host=args.host,
            warmup=args.warmup, max_in_flight=args.max_in_flight,
            deadline_s=args.deadline_s,
            ctl_interval_s=args.ctl_interval_s,
            hb_interval_s=args.hb_interval_s,
            join_timeout_s=args.join_timeout_s).start()
        return _serve_until_signal(
            member, {"kind": "replica", "replica_id": args.replica_id,
                     "addr": member.address,
                     "generation": member.generation})
    from paddle_tpu.serving_fleet import FleetRouter
    router = FleetRouter(
        args.coord, args.n_replicas, port=args.port, host=args.host,
        max_batch=args.max_batch,
        batch_deadline_s=args.batch_deadline_s,
        max_queue=args.max_queue,
        request_deadline_s=args.request_deadline_s,
        ctl_interval_s=args.ctl_interval_s,
        hb_interval_s=args.hb_interval_s,
        join_timeout_s=args.join_timeout_s).start()
    return _serve_until_signal(
        router, {"kind": "router", "addr": router.address,
                 "url": router.url})


if __name__ == "__main__":
    sys.exit(main())
