#!/usr/bin/env python
"""Banked tuned-cache validator — the tier-1 gate for ``tools/tuned/``.

The per-backend caches committed under tools/tuned/ are shared fleet
state: CI, bench rounds and serving replicas all trace against their
verdicts (``BuildStrategy.kernel_policy="auto"``). A torn, stale or
hand-mangled file there would silently mistune every consumer, so this
tool fails FAST instead. Per file it checks:

  1. **format**: parseable JSON, versioned envelope with
     ``format_version == autotune.FORMAT_VERSION``, ``backend`` meta
     matching the filename;
  2. **entries**: every key parses back into a known kernel family
     with integer shapes and the file's platform; impls are
     ``pallas|xla|pallas_q``; a winner config actually tiles its shape
     (the cost model's feature map — which mirrors the kernel size
     guards — accepts it);
  3. **coverage**: every (op, shape) of the backend's sweep grid is
     banked — the interpret banking grid (``autotune.BANK_SHAPES``)
     for cpu-interpret, ``autotune.DEFAULT_SHAPES`` (the ERNIE
     headline geometry) for real backends;
  4. **ranking quality**: a cost model fit on the file's own measured
     rows must place the measured-best config in its top-3 ranking on
     >= 80% (``--min-top3``) of the keys that banked enough rows to
     judge — the gate that keeps the top-k pruned sweeps honest.

Prints ONE JSON line; exit 0 only when every checked file passes.

Usage:
  python tools/tunecheck.py                  # every tools/tuned/*.json
  python tools/tunecheck.py --file tools/tuned/cpu-interpret.json
  python tools/tunecheck.py --min-top3 0.9
"""
import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))



def check_file(path, min_top3=0.8):
    """Validate one banked cache; returns the per-file report dict
    (``ok`` False plus a ``problems`` list on any failure)."""
    from paddle_tpu.ops.pallas import autotune as at
    from paddle_tpu.ops.pallas import costmodel as cm
    problems = []
    name = os.path.splitext(os.path.basename(path))[0]
    platform = "cpu" if name == "cpu-interpret" else name
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        return {"file": path, "ok": False,
                "problems": ["unreadable/torn JSON: %s" % e]}
    if not isinstance(raw, dict) or "format_version" not in raw:
        return {"file": path, "ok": False,
                "problems": ["not a versioned banked cache (no "
                             "format_version envelope)"]}
    entries, meta = at.AutotuneCache.parse_blob(raw)
    try:
        ver = int(raw["format_version"])
    except (TypeError, ValueError):
        ver = None
    if ver != at.FORMAT_VERSION:
        problems.append("format_version %r unsupported (this build "
                        "speaks %d)" % (raw["format_version"],
                                        at.FORMAT_VERSION))
    if meta.get("backend") != name:
        problems.append("backend meta %r does not match filename %r"
                        % (meta.get("backend"), name))
    interpret = bool(meta.get("interpret"))
    if (name == "cpu-interpret") != interpret:
        problems.append("interpret meta %r inconsistent with backend "
                        "%r" % (meta.get("interpret"), name))

    # -- entries ------------------------------------------------------
    banked = set()
    for key, entry in sorted(entries.items()):
        parsed = cm.parse_key(key)
        if parsed is None:
            problems.append("unparseable key %r" % key)
            continue
        op, shape, _dtype, _axes, backend = parsed
        if op not in at.CANDIDATES:
            problems.append("key %r names unknown kernel family %r"
                            % (key, op))
            continue
        if backend != platform:
            problems.append("key %r banked for backend %r in the %s "
                            "file" % (key, backend, name))
        if not isinstance(entry, dict) or entry.get("impl") not in (
                "pallas", "xla", "pallas_q"):
            problems.append("key %r has invalid impl %r"
                            % (key, entry.get("impl")
                               if isinstance(entry, dict) else entry))
            continue
        if interpret and entry.get("impl") == "xla":
            problems.append("key %r: an interpret sweep banked an "
                            "'xla' verdict (interpreter wall time says "
                            "nothing about Mosaic)" % key)
        config = entry.get("config")
        if config is not None and cm.features(
                op, shape, config, bool(entry.get("interpret",
                                                  interpret))) is None:
            problems.append("key %r winner config %r cannot tile its "
                            "shape" % (key, config))
        banked.add((op, shape))

    # -- coverage -----------------------------------------------------
    required = at.BANK_SHAPES if interpret else \
        {op: [at.DEFAULT_SHAPES[op]] for op in at.DEFAULT_SHAPES}
    missing = ["%s@%s" % (op, "x".join(map(str, shape)))
               for op, shapes in sorted(required.items())
               for shape in shapes if (op, tuple(shape)) not in banked]
    if missing:
        problems.append("grid coverage holes: %s" % ", ".join(missing))

    # -- ranking quality ----------------------------------------------
    model = cm.CostModel().fit_cache(entries)
    hits, judged = cm.measured_best_in_topk(entries, model=model)
    top3_rate = round(hits / judged, 4) if judged else None
    if judged and top3_rate < min_top3:
        problems.append("cost-model ranking too weak: measured-best in "
                        "model top-3 on only %.0f%% of %d keys (< %.0f%%)"
                        % (100 * top3_rate, judged, 100 * min_top3))

    return {"file": path, "ok": not problems, "backend": name,
            "entries": len(entries), "coverage_missing": len(missing),
            "rank_keys_judged": judged, "top3_rate": top3_rate,
            "model_rows": model.rows_total(),
            "problems": problems or None}


def main(argv=None):
    from paddle_tpu.ops.pallas import autotune as at
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--file", action="append", default=[],
                    help="banked cache file(s) to check (default: "
                         "every tools/tuned/*.json)")
    ap.add_argument("--min-top3", type=float, default=0.8,
                    help="minimum measured-best-in-model-top-3 rate")
    args = ap.parse_args(argv)
    files = args.file or sorted(glob.glob(
        os.path.join(at.tuned_dir(), "*.json")))
    reports = [check_file(p, min_top3=args.min_top3) for p in files]
    ok = bool(reports) and all(r["ok"] for r in reports)
    print(json.dumps({"metric": "tunecheck", "ok": ok,
                      "files": reports or
                      [{"problems": ["no banked caches found under %s"
                                     % at.tuned_dir()]}]}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
