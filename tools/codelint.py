#!/usr/bin/env python
"""Repo-specific AST lints for the bug classes the generic linters miss.

Rule 1 — **compile-cache-token completeness** (the PR 6
``quantize_min_size`` / PR 13 ``kernel_policy`` bug class): every
BuildStrategy knob that the lowering paths under
``framework/compiler.py`` / ``framework/trace.py`` READ must be folded
into ``CompiledProgram._cache_token`` (directly or via a helper the
token calls), or carry an explicit allowlist entry saying why it cannot
change the lowered executable. A knob that steers lowering but misses
the token means a stale jitted step silently keeps the old behavior
when the knob flips.

Rule 2 — **free-floating locks** (coordination-thread sanity): a
``threading.Lock()``/``RLock()``/``Condition()`` constructed directly
inside a ``with`` statement guards nothing — every caller gets a fresh
lock, which is exactly the interleaving bug the lock was meant to
prevent. The lock must be stored (module global, ``self._lock``, a
closure var shared with the threads) before it can serialize anything.

Rule 3 — **failpoint site catalog** (the fault-injection plane's typo
guard): every ``faultinject.hit("...")`` call site must name its site
as a string LITERAL that appears in ``framework/faultinject.py``'s
``SITES`` catalog. A typo'd or uncatalogued site string would parse,
arm, and then silently never fire — a chaos test that tests nothing.

All rules run as a tier-1 test (tests/test_codelint.py) so the bug
classes stay extinct. Exit 0 clean, 1 violations.

Usage:
  python tools/codelint.py            # lint the repo
  python tools/codelint.py --json
"""
import argparse
import ast
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMPILER_PY = os.path.join(REPO, "paddle_tpu", "framework", "compiler.py")
TRACE_PY = os.path.join(REPO, "paddle_tpu", "framework", "trace.py")

# knob -> why it is allowed to stay out of the compile-cache token.
# Every entry must argue "cannot change the lowered executable".
TOKEN_ALLOWLIST = {
    # diagnostics only: the verifier reads the program, never rewrites
    # it — strict/warn/off produce byte-identical lowerings (asserted
    # by tests/test_analysis.py's off-mode inertness test)
    "verify_program": "read-only program verification at compile time",
}

_LOCKY = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def _knob_reads(tree, knobs, aliases=("bs", "build_strategy", "strategy")):
    """{knob: [(qualname, lineno)]} of BuildStrategy attribute READS
    (ast.Load) and getattr(bs, "knob", ...) calls, per enclosing
    function. Recognizes the conventional aliases (``bs``,
    ``build_strategy``, ``strategy``), any ``<expr>._build_strategy``
    chain, AND locals bound from one (``cfg = self._build_strategy``)
    — a fresh binding must not hide a knob read from the lint."""
    reads = {}
    base_aliases = set(aliases)

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = []
            self.scopes = [set()]   # per-function local alias sets

        def _is_bs(self, node):
            if isinstance(node, ast.Name):
                return node.id in base_aliases or \
                    any(node.id in s for s in self.scopes)
            if isinstance(node, ast.Attribute):
                return node.attr == "_build_strategy"
            return False

        def _record(self, name, lineno):
            if name in knobs:
                qual = ".".join(self.stack) or "<module>"
                reads.setdefault(name, []).append((qual, lineno))

        def visit_ClassDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        def visit_FunctionDef(self, node):
            self.stack.append(node.name)
            self.scopes.append(set())
            self.generic_visit(node)
            self.scopes.pop()
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assign(self, node):
            if self._is_bs(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.scopes[-1].add(t.id)
            self.generic_visit(node)

        def visit_Attribute(self, node):
            if isinstance(node.ctx, ast.Load) and \
                    self._is_bs(node.value):
                self._record(node.attr, node.lineno)
            self.generic_visit(node)

        def visit_Call(self, node):
            if isinstance(node.func, ast.Name) and \
                    node.func.id == "getattr" and len(node.args) >= 2 \
                    and self._is_bs(node.args[0]) \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                self._record(node.args[1].value, node.lineno)
            self.generic_visit(node)

    V().visit(tree)
    return reads


def _build_strategy_knobs(tree):
    """Knob names: every `self.<name> = ...` in BuildStrategy.__init__."""
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef) and cls.name == "BuildStrategy":
            for fn in cls.body:
                if isinstance(fn, ast.FunctionDef) and \
                        fn.name == "__init__":
                    knobs = set()
                    for n in ast.walk(fn):
                        if isinstance(n, ast.Assign):
                            for t in n.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) \
                                        and t.value.id == "self":
                                    knobs.add(t.attr)
                    return knobs
    raise ValueError("BuildStrategy.__init__ not found")


def _token_closure_functions(tree, entry="_cache_token",
                             cls_name="CompiledProgram"):
    """Names of CompiledProgram methods reachable from `entry` via
    self.<method>() calls — the functions whose BuildStrategy reads
    count as 'in the token'."""
    methods = {}
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef) and cls.name == cls_name:
            for fn in cls.body:
                if isinstance(fn, ast.FunctionDef):
                    methods[fn.name] = fn
    if entry not in methods:
        raise ValueError("%s.%s not found" % (cls_name, entry))
    seen, todo = set(), [entry]
    while todo:
        name = todo.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        for n in ast.walk(methods[name]):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == "self":
                todo.append(n.func.attr)
    return {methods[m] for m in seen}


def lint_cache_token(compiler_src=None, trace_src=None,
                     allowlist=None):
    """Rule 1. Returns a list of violation strings (empty = clean)."""
    allowlist = TOKEN_ALLOWLIST if allowlist is None else allowlist
    if compiler_src is None:
        with open(COMPILER_PY) as f:
            compiler_src = f.read()
    if trace_src is None:
        with open(TRACE_PY) as f:
            trace_src = f.read()
    ctree = ast.parse(compiler_src)
    ttree = ast.parse(trace_src)
    knobs = _build_strategy_knobs(ctree)

    closure = _token_closure_functions(ctree)
    closure_spans = [(fn.lineno, max(n.lineno for n in ast.walk(fn)
                                     if hasattr(n, "lineno")))
                     for fn in closure]

    def in_token(lineno):
        return any(a <= lineno <= b for a, b in closure_spans)

    reads = _knob_reads(ctree, knobs)
    for knob, sites in _knob_reads(ttree, knobs).items():
        reads.setdefault(knob, []).extend(
            [(q + " [trace.py]", ln) for q, ln in sites])

    tokened = {k for k, sites in reads.items()
               if any(in_token(ln) for q, ln in sites
                      if not q.endswith("[trace.py]"))}
    violations = []
    for knob in sorted(reads):
        outside = [(q, ln) for q, ln in reads[knob]
                   if q.endswith("[trace.py]") or not in_token(ln)]
        if not outside:
            continue     # only read while building the token itself
        if knob in tokened or knob in allowlist:
            continue
        where = ", ".join("%s:%d" % (q, ln) for q, ln in outside[:4])
        violations.append(
            "BuildStrategy.%s is read on the lowering path (%s) but is "
            "NOT folded into CompiledProgram._cache_token and has no "
            "allowlist entry — flipping it would silently reuse a stale "
            "executable (the PR 6 quantize_min_size / PR 13 "
            "kernel_policy bug class)" % (knob, where))
    return violations


def lint_free_floating_locks(root=None, paths=None):
    """Rule 2. Flags `with threading.Lock():`-style inline lock
    construction anywhere under paddle_tpu/ (plus tools/)."""
    if paths is None:
        root = root or REPO
        paths = []
        for base in ("paddle_tpu", "tools"):
            for dirpath, _, files in os.walk(os.path.join(root, base)):
                paths.extend(os.path.join(dirpath, f) for f in files
                             if f.endswith(".py"))
    violations = []
    for path in sorted(paths):
        with open(path) as f:
            src = f.read()
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            violations.append("%s: unparseable: %s" % (path, e))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                call = item.context_expr
                if not isinstance(call, ast.Call):
                    continue
                fn = call.func
                name = fn.attr if isinstance(fn, ast.Attribute) \
                    else getattr(fn, "id", None)
                if name in _LOCKY:
                    violations.append(
                        "%s:%d: `with %s()` constructs a FRESH lock "
                        "per entry — it serializes nothing; store the "
                        "lock (module/self/closure) and `with` that"
                        % (path, node.lineno, name))
    return violations


FAULTINJECT_PY = os.path.join(REPO, "paddle_tpu", "framework",
                              "faultinject.py")
# module aliases a hit() call may hang off; anything else (a local
# helper also named hit, a mock) is not this plane's call
_FAULTINJECT_ALIASES = {"faultinject", "fi"}


def _site_catalog(src=None):
    """The SITES keys from faultinject.py — parsed from the AST so the
    lint never imports (and thereby arms) the plane it checks."""
    if src is None:
        with open(FAULTINJECT_PY) as f:
            src = f.read()
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Dict):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "SITES":
                    return {k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)}
    raise ValueError("SITES catalog not found in faultinject.py")


def lint_failpoint_sites(root=None, paths=None, catalog=None):
    """Rule 3. Returns a list of violation strings (empty = clean)."""
    catalog = _site_catalog() if catalog is None else set(catalog)
    if paths is None:
        root = root or REPO
        paths = []
        for base in ("paddle_tpu", "tools"):
            for dirpath, _, files in os.walk(os.path.join(root, base)):
                paths.extend(os.path.join(dirpath, f) for f in files
                             if f.endswith(".py"))
    violations = []
    for path in sorted(paths):
        with open(path) as f:
            src = f.read()
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            violations.append("%s: unparseable: %s" % (path, e))
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "hit"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in _FAULTINJECT_ALIASES):
                continue
            a0 = node.args[0] if node.args else None
            if not (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)):
                violations.append(
                    "%s:%d: faultinject.hit() site must be a string "
                    "literal from the SITES catalog — a computed site "
                    "name defeats the static typo guard"
                    % (path, node.lineno))
            elif a0.value not in catalog:
                violations.append(
                    "%s:%d: faultinject.hit(%r) names a site missing "
                    "from framework/faultinject.py's SITES catalog — "
                    "it would arm and then silently never fire"
                    % (path, node.lineno, a0.value))
    return violations


def run_all():
    return {"cache_token": lint_cache_token(),
            "free_floating_locks": lint_free_floating_locks(),
            "failpoint_sites": lint_failpoint_sites()}


def main(argv=None):
    ap = argparse.ArgumentParser(description="paddle_tpu repo lints")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    report = run_all()
    n = sum(len(v) for v in report.values())
    if args.json:
        print(json.dumps({"metric": "codelint", "violations": report,
                          "ok": n == 0}))
    else:
        for rule, vs in report.items():
            for v in vs:
                print("[%s] %s" % (rule, v))
        print("codelint: %d violation(s)" % n)
    return 0 if n == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
