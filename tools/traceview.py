#!/usr/bin/env python
"""Merge per-process span dumps into ONE Perfetto timeline.

The obs spans engine (paddle_tpu/framework/obs.py) records each
process's spans in a bounded ring; this tool merges any number of
per-process dumps — files written by ``obs.dump(path)`` and/or LIVE
pulls from fleet members' ``GET /admin/trace`` endpoints — into one
Chrome-trace-event JSON that chrome://tracing and
https://ui.perfetto.dev load directly. Because trace context
propagates across processes (the ``x-trace-id`` header), a single
client request shows up as ONE tree: ``client.infer`` ->
``router.serve`` (queue / coalesce / dispatch attempts) ->
``replica.serve`` -> executor phases — with each process on its own
named track and every event carrying its trace/span/parent ids in
``args`` (filter a timeline to one request by its trace id).

Reference parity: tools/timeline.py of the reference stack renders
profiler records into a chrome://tracing file; this is the same move
for the DISTRIBUTED layers the reference never had.

Usage:
  python tools/traceview.py -o trace.json dump1.json dump2.json ...
  python tools/traceview.py -o trace.json --from URL[,URL...] [files]
  python tools/traceview.py --stdout dump1.json

``--from`` takes fleet-member base URLs (router or replica,
``http://h:p`` or ``h:p``) and pulls each one's ``/admin/trace``.
Exit code 1 when any input failed to load (the merge of the rest is
still written); 2 when NO spans were collected at all.
"""
import argparse
import json
import sys


def pull_live(url, timeout_s=5.0):
    """Fetch one live member's span dump from GET /admin/trace."""
    import urllib.request
    base = url if "://" in url else "http://" + url
    with urllib.request.urlopen(base.rstrip("/") + "/admin/trace",
                                timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def load_dump(path):
    with open(path) as f:
        d = json.load(f)
    if not isinstance(d, dict) or "spans" not in d:
        raise ValueError("%s is not an obs span dump "
                         "(expected a dict with a 'spans' list)" % path)
    return d


def merge(dumps):
    """Merged Chrome trace dict from a list of dump blobs."""
    from paddle_tpu.framework import obs
    return obs.chrome_trace(list(dumps))


def summarize(dumps):
    """One human line per process + per-trace span counts (stderr)."""
    lines = []
    traces = {}
    for d in dumps:
        spans = d.get("spans", [])
        lines.append("  %-16s pid=%-7s spans=%-5d dropped=%s"
                     % (d.get("service"), d.get("pid"), len(spans),
                        d.get("dropped", 0)))
        for s in spans:
            traces[s["trace"]] = traces.get(s["trace"], 0) + 1
    multi = sorted(traces.items(), key=lambda kv: -kv[1])[:5]
    if multi:
        lines.append("  top traces: " + ", ".join(
            "%s (%d spans)" % kv for kv in multi))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dumps", nargs="*",
                    help="span dump files (obs.dump / /admin/trace "
                         "JSON)")
    ap.add_argument("--from", dest="live", default=None,
                    help="comma-joined fleet member base URLs to pull "
                         "/admin/trace from live")
    ap.add_argument("-o", "--out", default=None,
                    help="output Chrome trace JSON path")
    ap.add_argument("--stdout", action="store_true",
                    help="write the merged trace to stdout instead")
    args = ap.parse_args(argv)
    if not args.out and not args.stdout:
        ap.error("need -o OUT or --stdout")
    blobs, failed = [], 0
    for path in args.dumps:
        try:
            blobs.append(load_dump(path))
        except (OSError, ValueError) as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            failed += 1
    for url in (args.live.split(",") if args.live else []):
        url = url.strip()
        if not url:
            continue
        try:
            blobs.append(pull_live(url))
        except Exception as e:  # noqa: BLE001 - reported, not fatal
            print("live pull %s failed: %s" % (url, e),
                  file=sys.stderr)
            failed += 1
    total = sum(len(b.get("spans", [])) for b in blobs)
    if total == 0:
        print("no spans collected (is PADDLE_TPU_TRACE=1 set on the "
              "fleet?)", file=sys.stderr)
        return 2
    trace = merge(blobs)
    print("merged %d spans from %d process dump(s):\n%s"
          % (total, len(blobs), summarize(blobs)), file=sys.stderr)
    out = json.dumps(trace)
    if args.stdout:
        print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)
        print("wrote %s (load it at https://ui.perfetto.dev or "
              "chrome://tracing)" % args.out, file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
