#!/usr/bin/env python
"""Offline Program verifier — vet serialized/exported programs before
they serve.

Runs the framework/analysis.py pass framework (def-use/liveness,
shape/dtype inference, sharding + pipeline feasibility, dead-op report)
over serialized Program JSON WITHOUT tracing, a device, or the exporting
process — so a serving artifact can be vetted in CI or at a deploy gate
and a corrupt export fails the drain step, never the first live request
(ServingPredictor runs the same check at load).

Accepts, per path argument:
  * an inference-model directory (``__model__.json`` — io.py's
    save_inference_model layout; feeds/fetches come from the meta)
  * a ``__model__.json``-style meta file itself
  * a bare ``Program.to_json()`` dump (feeds/fetches unknown unless
    passed via --feed/--fetch)

Exit code = max severity over every checked program: 0 clean (infos
allowed), 1 warnings, 2 errors. ``--json`` prints one machine-readable
line instead of the per-diagnostic text.

Usage:
  python tools/progcheck.py model_dir/                  # exported model
  python tools/progcheck.py prog.json --fetch loss      # raw program
  python tools/progcheck.py model_dir/ --json
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MODEL_FILE = "__model__.json"


def check_path(path, feeds=None, fetches=None):
    """Verify one path; returns (AnalysisResult, display_name).

    The envelope contract (meta["program"] + feed/fetch lists, or a
    bare Program dump) lives in analysis.verify_model_meta — ONE
    implementation shared with the ServingPredictor load gate."""
    from paddle_tpu.framework import analysis
    if os.path.isdir(path):
        model = os.path.join(path, MODEL_FILE)
        if not os.path.exists(model):
            raise ValueError(
                "%s is a directory without %s — not an exported "
                "inference model" % (path, MODEL_FILE))
        path = model
    with open(path) as f:
        meta = json.load(f)
    result = analysis.verify_model_meta(meta, feeds=feeds,
                                        fetches=fetches)
    return result, path


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="verify serialized paddle_tpu programs "
                    "(exit code = max severity: 0 clean, 1 warnings, "
                    "2 errors)")
    ap.add_argument("paths", nargs="+",
                    help="inference-model dirs, __model__.json metas, "
                         "or Program.to_json() dumps")
    ap.add_argument("--feed", action="append", default=None,
                    help="feed var name (repeatable; overrides the "
                         "meta's feed list)")
    ap.add_argument("--fetch", action="append", default=None,
                    help="fetch var name (repeatable; overrides the "
                         "meta's fetch list; enables the dead-op "
                         "report)")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line")
    args = ap.parse_args(argv)

    from paddle_tpu.framework import analysis
    reports, exit_code = [], 0
    for path in args.paths:
        try:
            result, name = check_path(path, feeds=args.feed,
                                      fetches=args.fetch)
        except (OSError, ValueError, KeyError) as e:
            # an unreadable/corrupt envelope is as fatal as any error
            # diagnostic — the artifact cannot be vetted, refuse it
            reports.append({"path": path, "ok": False,
                            "load_error": "%s: %s"
                            % (type(e).__name__, e)})
            exit_code = max(exit_code, 2)
            if not args.json:
                print("%s: LOAD ERROR: %s" % (path, e))
            continue
        analysis.report(result, mode="progcheck", source="progcheck")
        exit_code = max(exit_code, result.exit_code())
        reports.append({"path": name,
                        "ok": result.exit_code() == 0,
                        **result.to_dict()})
        if not args.json:
            c = result.counts()
            print("%s: %d error(s), %d warning(s), %d info"
                  % (name, c["error"], c["warning"], c["info"]))
            for d in result:
                print("  " + str(d))
    if args.json:
        print(json.dumps({"metric": "progcheck", "exit_code": exit_code,
                          "programs": reports}))
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
