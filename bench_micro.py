#!/usr/bin/env python
"""CPU-measurable perf gates: the tier-1-safe microbench suite.

BENCH_r03-r05 postmortem: three bench rounds produced zero perf signal
because the TPU fabric hung at backend init. Perf must not be hostage to
one flaky chip attach — this suite measures the paddle_tpu host/compiler
surfaces that move on every PR, on JAX_PLATFORMS=cpu, in seconds:

  * trace_lower_s          — Program -> StableHLO trace+lower wall time
                             of a small train step (the compile-path
                             regression canary)
  * cache_hit_rate         — Executor step-cache hit rate over a steady
                             dispatch loop (a drop means a cache key
                             churn bug: every step recompiles)
  * exact_step_s /         — per-step wall time of a dp-sharded
    quant_step_s             CompiledProgram window, full-width vs
                             quantize_collectives
  * collective_wire_ratio  — wire/raw bytes of the quantized gradient
                             all-reduce (resilience bytes counters —
                             the EQuARX-style bandwidth win, asserted
                             not hand-waved)
  * feed_samples_per_s     — ShardedFeed draw+commit throughput
                             (the data-plane hot loop)

Output contract: ONE JSON line (dict with "metric": "bench_micro" and a
"metrics" sub-dict). tests/test_bench_micro.py re-runs the suite
in-process and checks every metric against the REGRESSION BUDGETS below,
so every PR gets a perf verdict even when bench.py's chip probe fails
(bench.py --micro falls back to this suite).

Budgets are deliberately loose upper bounds for shared-CI noise: they
catch order-of-magnitude regressions (a trace blowup, a cache-key bug, a
codec that stopped compressing), not single-digit-percent drift.
"""
import json
import os
import sys
import time


def _force_cpu():
    """Standalone entry: pin the CPU backend with 8 virtual devices
    BEFORE jax import (same shape as tests/conftest.py). A no-op when
    jax is already imported/configured (pytest in-process use)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - older jax
        pass


# metric -> ("max"|"min", budget). Checked by check_budgets(); loose on
# purpose (shared CI boxes) — they exist to catch step changes.
BUDGETS = {
    "trace_lower_s": ("max", 60.0),
    "cache_hit_rate": ("min", 0.85),
    "exact_step_s": ("max", 20.0),
    "quant_step_s": ("max", 20.0),
    "collective_wire_ratio": ("max", 0.30),
    "feed_samples_per_s": ("min", 1000.0),
}


def check_budgets(metrics):
    """Return a list of human-readable budget violations (empty = pass)."""
    bad = []
    for name, (kind, budget) in BUDGETS.items():
        if name not in metrics:
            bad.append("metric %r missing from the report" % name)
            continue
        v = metrics[name]
        if not isinstance(v, (int, float)):
            bad.append("metric %r is not numeric: %r" % (name, v))
        elif kind == "max" and v > budget:
            bad.append("%s=%.4g exceeds budget %.4g" % (name, v, budget))
        elif kind == "min" and v < budget:
            bad.append("%s=%.4g below budget %.4g" % (name, v, budget))
    return bad


def _build_train(hidden=128, in_dim=64, classes=8):
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [in_dim], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=hidden, act="relu")
        logits = layers.fc(h, size=classes)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _batch(rng, n=16, in_dim=64, classes=8):
    import numpy as np
    return {"x": rng.rand(n, in_dim).astype(np.float32),
            "y": rng.randint(0, classes, (n, 1)).astype(np.int64)}


def bench_trace_lower():
    """Program -> StableHLO wall time of the small train step."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework.scope import Scope, scope_guard
    with scope_guard(Scope()):
        main, startup, loss = _build_train()
        exe = pt.Executor()
        exe.run(startup)
        feed = _batch(np.random.RandomState(0))
        t0 = time.perf_counter()
        exe.dump_hlo(main, feed=feed, fetch_list=[loss],
                     include_compiled=False)
        dt = time.perf_counter() - t0
    return {"trace_lower_s": round(dt, 4)}


def bench_cache_hit(steps=12):
    """Step-cache hit rate of a steady single-program dispatch loop."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework.scope import Scope, scope_guard
    with scope_guard(Scope()):
        main, startup, loss = _build_train()
        exe = pt.Executor()
        exe.run(startup)
        feed = _batch(np.random.RandomState(0))
        for _ in range(steps):
            exe.run(main, feed=feed, fetch_list=[loss])
        total = exe.cache_hits + exe.cache_misses
        rate = exe.cache_hits / float(total) if total else 0.0
    return {"cache_hit_rate": round(rate, 4),
            "cache_compiles": exe.cache_misses}


def bench_quantized_step(steps=6):
    """dp-sharded CompiledProgram step wall time, exact vs quantized,
    plus the quantized path's wire/raw byte ratio."""
    import numpy as np
    import jax
    import paddle_tpu as pt
    from paddle_tpu.framework.compiler import CompiledProgram, \
        BuildStrategy
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.framework import resilience
    n_dev = min(8, len(jax.devices()))
    feed = _batch(np.random.RandomState(0), n=2 * n_dev)
    out = {}
    for tag, quant in (("exact", False), ("quant", True)):
        with scope_guard(Scope()):
            main, startup, loss = _build_train()
            exe = pt.Executor()
            exe.run(startup)
            bs = BuildStrategy()
            bs.mesh_axes = {"dp": n_dev}
            bs.quantize_collectives = quant
            comp = CompiledProgram(main, bs)
            if quant:
                resilience.clear_bytes()
            exe.run(comp, feed=feed, fetch_list=[loss])  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                vals = exe.run(comp, feed=feed, fetch_list=[loss])
            dt = (time.perf_counter() - t0) / steps
            assert np.isfinite(np.asarray(vals[0])).all()
            out["%s_step_s" % tag] = round(dt, 5)
            if quant:
                tot = resilience.bytes_totals().get(
                    "collective", {"raw": 0, "wire": 0})
                ratio = tot["wire"] / float(tot["raw"]) if tot["raw"] \
                    else 1.0
                out["collective_wire_ratio"] = round(ratio, 4)
                out["collective_raw_bytes"] = tot["raw"]
                out["collective_wire_bytes"] = tot["wire"]
    return out


def bench_feed(n_files=16, per_file=64, batches=200, batch_size=8):
    """ShardedFeed draw+commit throughput (samples/sec, one host)."""
    import numpy as np
    from paddle_tpu.reader.sharded_feed import ShardedFeed
    rng = np.random.RandomState(0)
    files = [[{"x": rng.rand(4).astype(np.float32)}
              for _ in range(per_file)] for _ in range(n_files)]
    feed = ShardedFeed(files, n_hosts=1, host_id=0, seed=3,
                       batch_size=batch_size)
    served = 0
    t0 = time.perf_counter()
    for _ in range(batches):
        b = feed.next_batch()
        if b is None:
            break
        served += len(b["x"])
        feed.commit()
    dt = time.perf_counter() - t0
    return {"feed_samples_per_s": round(served / dt, 1),
            "feed_batches": batches}


def run_all():
    """Run every section; returns the report dict (never raises — a
    broken section lands as an "error" entry so the JSON line and the
    other sections still ship)."""
    metrics, errors = {}, {}
    for name, fn in (("trace_lower", bench_trace_lower),
                     ("cache_hit", bench_cache_hit),
                     ("quantized_step", bench_quantized_step),
                     ("feed", bench_feed)):
        t0 = time.perf_counter()
        try:
            metrics.update(fn())
        except Exception as e:  # pragma: no cover - section crash
            errors[name] = "%s: %s" % (type(e).__name__, e)
        metrics["%s_section_s" % name] = round(
            time.perf_counter() - t0, 3)
    report = {"metric": "bench_micro", "unit": "mixed",
              "platform": _platform(), "metrics": metrics}
    violations = check_budgets(metrics)
    report["budgets_ok"] = not violations and not errors
    if violations:
        report["budget_violations"] = violations
    if errors:
        report["errors"] = errors
    return report


def _platform():
    import jax
    return sorted({d.platform for d in jax.devices()})


def main(argv=None):
    _force_cpu()
    report = run_all()
    print(json.dumps(report))
    return 0 if report["budgets_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
