#!/usr/bin/env python
"""CPU-measurable perf gates: the tier-1-safe microbench suite.

BENCH_r03-r05 postmortem: three bench rounds produced zero perf signal
because the TPU fabric hung at backend init. Perf must not be hostage to
one flaky chip attach — this suite measures the paddle_tpu host/compiler
surfaces that move on every PR, on JAX_PLATFORMS=cpu, in seconds:

  * trace_lower_s          — Program -> StableHLO trace+lower wall time
                             of a small train step (the compile-path
                             regression canary)
  * cache_hit_rate         — Executor step-cache hit rate over a steady
                             dispatch loop (a drop means a cache key
                             churn bug: every step recompiles)
  * exact_step_s /         — per-step wall time of a dp-sharded
    quant_step_s             CompiledProgram window, full-width vs
                             quantize_collectives
  * collective_wire_ratio  — wire/raw bytes of the quantized gradient
                             all-reduce (resilience bytes counters —
                             the EQuARX-style bandwidth win, asserted
                             not hand-waved)
  * feed_samples_per_s     — ShardedFeed draw+commit throughput
                             (the data-plane hot loop)
  * pallas_*               — the Pallas kernel library vs its XLA
                             references in interpret mode (blockwise
                             CE / fused MLM head, fused Adam, fused
                             LayerNorm): fwd+bwd step wall + max abs
                             error per kernel — the kernels' tier-1
                             perf-and-parity canary
  * costmodel_*            — the kernel-selection cost model (ISSUE
                             13): fit wall over the committed
                             tools/tuned/ cache, per-query ranking
                             cost (what a trace-time cache miss
                             pays — must be ≪ one sweep probe), and
                             the measured-best-in-top-3 rate on the
                             banked keys
  * transport_*            — coordination-plane latency over an
                             in-process CoordServer: single
                             request/response round trip, a 2-host
                             all_gather round (the per-window cost
                             every pod/fleet protocol pays), and the
                             HA failover round trip — kill the
                             replicated primary, time until a standby
                             answers a completed gather (promotion +
                             client failover, the outage a SIGKILLed
                             coordinator actually costs)
  * serving_*              — fleet router p50/p99 request latency +
                             shed rate under synthetic concurrent
                             load (2 in-process replicas, continuous
                             micro-batching) — the serving-path
                             regression canary
  * buddy_*                — the in-memory buddy-checkpoint tier:
                             per-window snapshot encode+send wall into
                             the ring buddy's mailbox, and the buddy
                             restore vs the disk restore it front-runs
                             (same state, real load_checkpoint path)
  * obs_*                  — tracing-overhead gate: the same dp step
                             and router request measured spans-off vs
                             spans-on (median ratio) plus the per-span
                             record cost — the obs layer must never
                             silently tax a hot path

Output contract: ONE JSON line (dict with "metric": "bench_micro" and a
"metrics" sub-dict). tests/test_bench_micro.py re-runs the suite
in-process and checks every metric against the REGRESSION BUDGETS below,
so every PR gets a perf verdict even when bench.py's chip probe fails
(bench.py --micro falls back to this suite).

Budgets are deliberately loose upper bounds for shared-CI noise: they
catch order-of-magnitude regressions (a trace blowup, a cache-key bug, a
codec that stopped compressing), not single-digit-percent drift.

Trend tracking (ROADMAP item 4, remaining slice): pass --rounds-dir (or
set PADDLE_TPU_MICRO_ROUNDS_DIR) to persist each run's report under the
rounds dir and to compare the current metrics against the median of the
previous rounds — DRIFT (a metric worsening by more than DRIFT_FACTOR
vs its own history) is flagged in the report even while it is still
inside the absolute budget. The flag now GATES: --fail-on-drift is
default-ON (a drift flag exits non-zero) once MIN_DRIFT_GATE_ROUNDS
prior rounds have calibrated the noise floor — thinner history stays
informational — and --no-fail-on-drift restores the informational mode
outright for noisy one-off boxes.
"""
import glob
import json
import os
import sys
import time


def _force_cpu():
    """Standalone entry: pin the CPU backend with 8 virtual devices
    BEFORE jax import (same shape as tests/conftest.py). A no-op when
    jax is already imported/configured (pytest in-process use)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - older jax
        pass


# metric -> ("max"|"min", budget). Checked by check_budgets(); loose on
# purpose (shared CI boxes) — they exist to catch step changes.
BUDGETS = {
    "trace_lower_s": ("max", 60.0),
    "cache_hit_rate": ("min", 0.85),
    "exact_step_s": ("max", 20.0),
    "quant_step_s": ("max", 20.0),
    "collective_wire_ratio": ("max", 0.30),
    "feed_samples_per_s": ("min", 1000.0),
    # Pallas kernels, interpret mode on tiny shapes: wall budgets catch
    # an interpreter-path blowup, error budgets catch a numerics break
    # (the oracle batteries assert tighter bounds; these gate the bench)
    "pallas_ce_step_s": ("max", 30.0),
    "pallas_adam_step_s": ("max", 15.0),
    "pallas_ln_step_s": ("max", 15.0),
    "pallas_ce_err": ("max", 1e-4),
    "pallas_adam_err": ("max", 1e-5),
    "pallas_ln_err": ("max", 1e-4),
    # kernel-selection cost model (ISSUE 13): fitting over the whole
    # committed banked cache and ranking a candidate space must stay
    # FAR below one sweep probe (~ms-to-minutes) — the model only pays
    # for itself while a query is nearly free. The top-3 rate gates
    # the committed cache's ranking quality at the same bar
    # tools/tunecheck.py enforces.
    "costmodel_fit_s": ("max", 2.0),
    "costmodel_rank_us": ("max", 20000.0),
    "costmodel_top3_rate": ("min", 0.8),
    # coordination-plane latency (in-process CoordServer over loopback
    # TCP): a round trip is ~100us healthy; a 2-host gather round adds
    # the poll cadence. Budgets catch a protocol/serialization blowup.
    "transport_roundtrip_ms": ("max", 25.0),
    "transport_gather_ms": ("max", 250.0),
    # HA failover round trip: SIGKILL the primary (in-process kill()),
    # wall until a 2-host gather completes on the promoted standby.
    # Dominated by the group's heartbeat deadline (0.5s here) + the
    # promotion probe + one client failover; the budget catches a
    # promotion/fencing stall, not scheduler jitter.
    "transport_failover_ms": ("max", 15000.0),
    # serving fleet under synthetic load (2 in-process replicas +
    # micro-batching router, tiny model): p50/p99 wall per request and
    # the shed rate. Sized for shared-CI noise — they catch a batching
    # stall or a dispatch-path regression, not single-digit drift.
    "serving_p50_ms": ("max", 250.0),
    "serving_p99_ms": ("max", 2000.0),
    "serving_shed_rate": ("max", 0.2),
    # p50/p99 are computed over SUCCESSFUL requests only — without an
    # error-rate gate a broken dispatch path (mass 502s) would leave
    # the latency numbers green on the few requests that survived
    "serving_error_rate": ("max", 0.05),
    # multi-tenant QoS (ISSUE 16): the same fleet re-run behind a
    # classed router (gold/silver/bronze under weighted-fair
    # queueing). Gold p99 gates the highest class's latency with the
    # WFQ cutter in the path; the fairness metric is Jain's index
    # over per-class success ratios — 1.0 when every class's requests
    # complete alike, collapsing toward 1/n when the scheduler starts
    # starving a class the quota/brownout config says it should not.
    "serving_gold_p99_ms": ("max", 2000.0),
    "serving_fairness": ("min", 0.6),
    # router-tier HA: kill one of two in-process routers mid-load,
    # wall until the FleetClient's first successful request on the
    # survivor (connection-refused rotation + idempotent token
    # replay). Dominated by the client's per-rotation backoff, not
    # the heartbeat deadline — leadership can lag, routing cannot.
    "router_failover_ms": ("max", 15000.0),
    # obs tracing overhead (the spans tentpole's tier-1 gate): the
    # SAME dp step / router request measured spans-off vs spans-on as
    # a median-of-N ratio, plus the absolute per-span record cost.
    # The layer must be ~free — a ratio creeping past the margin means
    # tracing started taxing the hot path (the budget is sized for
    # shared-CI noise on ~ms walls, not single-digit drift)
    "obs_step_overhead_ratio": ("max", 1.75),
    "obs_router_overhead_ratio": ("max", 1.75),
    "obs_span_record_us": ("max", 200.0),
    # pipeline-parallel CompiledProgram step on the pp=2 x dp=4 CPU
    # mesh (1F1B, M=4 microbatches): step wall catches a lowering
    # blowup; the MEASURED bubble fraction (per-tick cost fitted from
    # two microbatch counts at a fixed micro-batch size x 1F1B's
    # M + 2(K-1) tick model) is sanity-gated — near 1.0 would mean the
    # ring schedule stopped overlapping at all; the cache-hit-rate
    # gate catches a pp cache-key churn bug (every schedule-toggle
    # repeat recompiling)
    "pp_step_s": ("max", 30.0),
    "pp_bubble_frac": ("max", 0.95),
    "pp_cache_hit_rate": ("min", 0.4),
    # Elastic pp re-cut (ISSUE 18): the full outage of a host-loss
    # re-cut on the in-process pp=2 pod — decision commit through the
    # first completed post-re-cut step, which includes compiling the
    # re-cut executable. Sized like pp_step_s for shared-CI CPU boxes:
    # it catches the re-cut path growing a second re-lowering or a
    # full-state rewrite, not scheduler jitter.
    "pp_recut_ms": ("max", 30000.0),
    # In-memory buddy checkpointing (ISSUE 19): the per-window
    # snapshot tax (encode+zlib+mailbox put of the whole persistable
    # scope) must stay far below a training window, and the buddy
    # restore (verdict + fetch + decode + adopt) must stay disk-class
    # — the tier's pitch is "disk-or-better restore, one window of
    # lost work instead of a full rewind". The disk number gates the
    # load_checkpoint path it falls back to. Sized for shared-CI
    # boxes: they catch a codec/protocol blowup, not ms drift.
    "buddy_snapshot_ms": ("max", 5000.0),
    "buddy_restore_ms": ("max", 5000.0),
    "buddy_disk_restore_ms": ("max", 10000.0),
    # P2p buddy mailboxes + delta snapshots (ISSUE 20): one host-to-
    # host deposit (encode + own-mailbox + buddy-mailbox + metadata
    # commit) must stay in the same class as the legacy coordinator
    # put, and on the churn-skewed reference scope (one large static
    # embedding leaf + small churning leaves) the delta wire must move
    # UNDER HALF the full-scope wire — the tier's pitch is "replicate
    # every window without re-streaming the static majority".
    "buddy_p2p_send_ms": ("max", 5000.0),
    "buddy_delta_bytes_ratio": ("max", 0.5),
    # Program verifier (ISSUE 15): one strict walk over the BERT-base
    # pretrain program must stay interactive (it is pure Python, no
    # tracing), and on the shared small step it must cost well under
    # the trace+lower wall it fronts — "warn" by default stays free.
    # Zero error-severity diagnostics on the clean headline program is
    # the bench-side no-false-positive gate.
    "analysis_verify_s": ("max", 10.0),
    "analysis_overhead_ratio": ("max", 0.5),
    "analysis_bert_errors": ("max", 0),
    # numeric-fault plane (ISSUE 17): the in-graph finite mask
    # (BuildStrategy.numeric_policy) measured against the plain dp step
    # as a median of strictly interleaved pairwise on/off ratios, and
    # the wall of one poisoned-step skip recovery (failpoint-corrupted
    # batch -> localize culprit -> in-graph state revert). The healthy
    # mask cost is single-digit percent (the design target is <=5%);
    # the gate is sized for shared-CI noise on ~ms CPU walls, where the
    # same binary measures anywhere up to ~10% run-over-run — it
    # catches the mask growing a real extra pass over the state, not
    # scheduler jitter (drift tracking watches the slide below it).
    "numerics_overhead_frac": ("max", 0.25),
    "fault_recovery_ms": ("max", 2000.0),
}

# metric -> worsening factor vs the rounds-history median that counts as
# drift. Looser than 2x for wall times (shared CI boxes), tight for
# error metrics (numerics should be bit-stable across rounds).
DRIFT_FACTOR = 2.5

# drift flags GATE (exit non-zero) only once this many prior rounds
# calibrate the noise floor; thinner history keeps them informational —
# a 2-sample median is noise, not a baseline
MIN_DRIFT_GATE_ROUNDS = 5


def check_budgets(metrics):
    """Return a list of human-readable budget violations (empty = pass)."""
    bad = []
    for name, (kind, budget) in BUDGETS.items():
        if name not in metrics:
            bad.append("metric %r missing from the report" % name)
            continue
        v = metrics[name]
        if not isinstance(v, (int, float)):
            bad.append("metric %r is not numeric: %r" % (name, v))
        elif kind == "max" and v > budget:
            bad.append("%s=%.4g exceeds budget %.4g" % (name, v, budget))
        elif kind == "min" and v < budget:
            bad.append("%s=%.4g below budget %.4g" % (name, v, budget))
    return bad


def _build_train(hidden=128, in_dim=64, classes=8):
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [in_dim], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=hidden, act="relu")
        logits = layers.fc(h, size=classes)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _batch(rng, n=16, in_dim=64, classes=8):
    import numpy as np
    return {"x": rng.rand(n, in_dim).astype(np.float32),
            "y": rng.randint(0, classes, (n, 1)).astype(np.int64)}


def bench_trace_lower():
    """Program -> StableHLO wall time of the small train step."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework.scope import Scope, scope_guard
    with scope_guard(Scope()):
        main, startup, loss = _build_train()
        exe = pt.Executor()
        exe.run(startup)
        feed = _batch(np.random.RandomState(0))
        t0 = time.perf_counter()
        exe.dump_hlo(main, feed=feed, fetch_list=[loss],
                     include_compiled=False)
        dt = time.perf_counter() - t0
    return {"trace_lower_s": round(dt, 4)}


def bench_cache_hit(steps=12):
    """Step-cache hit rate of a steady single-program dispatch loop."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework.scope import Scope, scope_guard
    with scope_guard(Scope()):
        main, startup, loss = _build_train()
        exe = pt.Executor()
        exe.run(startup)
        feed = _batch(np.random.RandomState(0))
        for _ in range(steps):
            exe.run(main, feed=feed, fetch_list=[loss])
        total = exe.cache_hits + exe.cache_misses
        rate = exe.cache_hits / float(total) if total else 0.0
    return {"cache_hit_rate": round(rate, 4),
            "cache_compiles": exe.cache_misses}


def bench_quantized_step(steps=6):
    """dp-sharded CompiledProgram step wall time, exact vs quantized,
    plus the quantized path's wire/raw byte ratio."""
    import numpy as np
    import jax
    import paddle_tpu as pt
    from paddle_tpu.framework.compiler import CompiledProgram, \
        BuildStrategy
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.framework import resilience
    n_dev = min(8, len(jax.devices()))
    feed = _batch(np.random.RandomState(0), n=2 * n_dev)
    out = {}
    for tag, quant in (("exact", False), ("quant", True)):
        with scope_guard(Scope()):
            main, startup, loss = _build_train()
            exe = pt.Executor()
            exe.run(startup)
            bs = BuildStrategy()
            bs.mesh_axes = {"dp": n_dev}
            bs.quantize_collectives = quant
            comp = CompiledProgram(main, bs)
            if quant:
                resilience.clear_bytes()
            exe.run(comp, feed=feed, fetch_list=[loss])  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                vals = exe.run(comp, feed=feed, fetch_list=[loss])
            dt = (time.perf_counter() - t0) / steps
            assert np.isfinite(np.asarray(vals[0])).all()
            out["%s_step_s" % tag] = round(dt, 5)
            if quant:
                tot = resilience.bytes_totals().get(
                    "collective", {"raw": 0, "wire": 0})
                ratio = tot["wire"] / float(tot["raw"]) if tot["raw"] \
                    else 1.0
                out["collective_wire_ratio"] = round(ratio, 4)
                out["collective_raw_bytes"] = tot["raw"]
                out["collective_wire_bytes"] = tot["wire"]
    return out


def bench_feed(n_files=16, per_file=64, batches=200, batch_size=8):
    """ShardedFeed draw+commit throughput (samples/sec, one host)."""
    import numpy as np
    from paddle_tpu.reader.sharded_feed import ShardedFeed
    rng = np.random.RandomState(0)
    files = [[{"x": rng.rand(4).astype(np.float32)}
              for _ in range(per_file)] for _ in range(n_files)]
    feed = ShardedFeed(files, n_hosts=1, host_id=0, seed=3,
                       batch_size=batch_size)
    served = 0
    t0 = time.perf_counter()
    for _ in range(batches):
        b = feed.next_batch()
        if b is None:
            break
        served += len(b["x"])
        feed.commit()
    dt = time.perf_counter() - t0
    return {"feed_samples_per_s": round(served / dt, 1),
            "feed_batches": batches}


def bench_pallas(steps=2):
    """Pallas kernel library vs the XLA references, interpret mode on
    tiny shapes: per-kernel fwd+bwd step wall (jitted, best-of) + max
    abs error. The same kernels the use_pallas dispatch routes to —
    this is their always-on perf-and-parity canary."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.blockwise_ce import \
        blockwise_softmax_cross_entropy
    from paddle_tpu.ops.pallas.fused_adam import fused_adam
    from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm

    rng = np.random.RandomState(0)
    out = {}

    def best_of(fn):
        jax.block_until_ready(fn())      # compile + warm
        best = None
        for _ in range(steps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    # blockwise CE: fwd+bwd vs log_softmax reference, (32, 256)
    t, v = 32, 256
    logits = jnp.asarray(rng.randn(t, v).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, v, (t,)).astype(np.int32))

    def ce_ref(lg):
        logp = jax.nn.log_softmax(lg, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]

    def ce_pallas(lg):
        return blockwise_softmax_cross_entropy(
            lg, labels, block_t=8, block_v=64, interpret=True)

    g_p = jax.jit(jax.grad(lambda lg: jnp.sum(ce_pallas(lg))))
    g_r = jax.jit(jax.grad(lambda lg: jnp.sum(ce_ref(lg))))
    out["pallas_ce_step_s"] = round(best_of(lambda: g_p(logits)), 5)
    out["pallas_ce_err"] = float(max(
        jnp.max(jnp.abs(ce_pallas(logits) - ce_ref(logits))),
        jnp.max(jnp.abs(g_p(logits) - g_r(logits)))))

    # fused adam: one update vs the elementwise chain, 4096 elements
    n = 4096
    p = jnp.asarray(rng.randn(n).astype(np.float32))
    gr = jnp.asarray(rng.randn(n).astype(np.float32))
    m1 = jnp.zeros((n,), jnp.float32)
    m2 = jnp.zeros((n,), jnp.float32)
    lr_t = jnp.float32(0.01)

    def adam_pallas(p, gr, m1, m2):
        return fused_adam(p, gr, m1, m2, lr_t, block_rows=16,
                          interpret=True)

    def adam_ref(p, gr, m1, m2):
        m1n = 0.9 * m1 + 0.1 * gr
        m2n = 0.999 * m2 + 0.001 * gr * gr
        return p - lr_t * m1n / (jnp.sqrt(m2n) + 1e-8), m1n, m2n

    jp, jr = jax.jit(adam_pallas), jax.jit(adam_ref)
    out["pallas_adam_step_s"] = round(
        best_of(lambda: jp(p, gr, m1, m2)), 5)
    out["pallas_adam_err"] = float(max(
        jnp.max(jnp.abs(a - b))
        for a, b in zip(jp(p, gr, m1, m2), jr(p, gr, m1, m2))))

    # fused layernorm: fwd+bwd vs jnp reference, (32, 128)
    r, c = 32, 128
    x = jnp.asarray(rng.randn(r, c).astype(np.float32))
    sc = jnp.asarray(rng.randn(c).astype(np.float32))
    bi = jnp.asarray(rng.randn(c).astype(np.float32))

    def ln_ref(x, sc, bi):
        m = jnp.mean(x, -1, keepdims=True)
        vv = jnp.var(x, -1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(vv + 1e-5) * sc[None, :] + bi

    def ln_pallas(x, sc, bi):
        return fused_layer_norm(x, sc, bi, block_rows=8, interpret=True)

    lg_p = jax.jit(jax.grad(
        lambda *a: jnp.sum(ln_pallas(*a) ** 2), argnums=(0, 1, 2)))
    lg_r = jax.jit(jax.grad(
        lambda *a: jnp.sum(ln_ref(*a) ** 2), argnums=(0, 1, 2)))
    out["pallas_ln_step_s"] = round(best_of(lambda: lg_p(x, sc, bi)), 5)
    out["pallas_ln_err"] = float(max(
        [jnp.max(jnp.abs(ln_pallas(x, sc, bi) - ln_ref(x, sc, bi)))] +
        [jnp.max(jnp.abs(a - b))
         for a, b in zip(lg_p(x, sc, bi), lg_r(x, sc, bi))]))
    return out


def bench_costmodel(rank_queries=50):
    """Kernel-selection cost model overhead + quality (ISSUE 13): wall
    time to fit the model from the committed tools/tuned/ cache, the
    per-query ranking cost over the interpret candidate space (this is
    what every trace-time cache miss pays — it must be ≪ one probe),
    and the in-sample measured-best-in-top-3 rate on the banked keys
    (the tunecheck quality bar, gated here so a bench round always
    carries a model verdict too)."""
    from paddle_tpu.ops.pallas import autotune as at
    from paddle_tpu.ops.pallas import costmodel as cmod

    out = {}
    cache = at.AutotuneCache(at.banked_cache_path("cpu"))
    t0 = time.perf_counter()
    model = at.fit_cost_model(cache, interpret=True)
    # force the lazy per-segment fits so fit_s covers the regression —
    # backend="cpu" targets the segments the banked rows actually live
    # in (the same query trace-time dispatch issues); the default "-"
    # segment has no rows and would time the analytic path instead
    for op in at.CANDIDATES:
        model.rank(op, at.DRY_SHAPES[op], backend="cpu",
                   interpret=True)
    out["costmodel_fit_s"] = round(time.perf_counter() - t0, 5)
    out["costmodel_rows"] = model.rows_total()

    shapes = [("softmax_with_cross_entropy", (48, 320)),
              ("adam", (12345,)), ("layer_norm", (96, 192)),
              ("fused_mlm_head_loss", (40, 384))]
    t0 = time.perf_counter()
    for i in range(rank_queries):
        op, shape = shapes[i % len(shapes)]
        model.rank(op, shape, backend="cpu", interpret=True)
    out["costmodel_rank_us"] = round(
        (time.perf_counter() - t0) / rank_queries * 1e6, 2)

    hits, judged = cmod.measured_best_in_topk(cache, model=model)
    out["costmodel_top3_rate"] = round(hits / judged, 4) if judged \
        else 0.0
    out["costmodel_keys_judged"] = judged
    return out


def bench_transport(roundtrips=200, gathers=20):
    """Coordination-plane latency over an in-process CoordServer:
    mean single round trip (the heartbeat/poll cost) and mean 2-host
    all_gather round wall (put + sticky freeze + poll + ack — what a
    pod window or a fleet control round pays)."""
    import threading
    from paddle_tpu.framework.coordination import SocketCoordinator
    from paddle_tpu.framework.transport import CoordServer
    out = {}
    with CoordServer(2) as srv:
        srv.start()
        cos = [SocketCoordinator(srv.address, 2, h, mesh_reinit=False,
                                 heartbeat=False, poll_s=0.001)
               for h in range(2)]
        try:
            cos[0].lost_hosts()              # warm the connection
            t0 = time.perf_counter()
            for _ in range(roundtrips):
                cos[0].lost_hosts()
            dt = time.perf_counter() - t0
            out["transport_roundtrip_ms"] = round(
                dt / roundtrips * 1e3, 4)

            def party(h, r):
                cos[h].all_gather("bench_g%d" % r, h, h)

            t0 = time.perf_counter()
            for r in range(gathers):
                ts = [threading.Thread(target=party, args=(h, r))
                      for h in range(2)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
            dt = time.perf_counter() - t0
            out["transport_gather_ms"] = round(dt / gathers * 1e3, 4)
        finally:
            for co in cos:
                co.close()
    return out


def bench_failover(hb_deadline_s=0.5):
    """Coordination-plane HA: the outage a SIGKILLed primary costs.
    A 2-member replicated group (primary + warm standby) serves a
    2-host pod; after a warm gather the primary is killed abruptly
    (connections severed, no farewell) and the clock runs until BOTH
    hosts complete a fresh all_gather against the promoted standby —
    promotion wait + client failover + idempotent re-submission, end
    to end."""
    import threading
    from paddle_tpu.framework.coordination import SocketCoordinator
    from paddle_tpu.framework.transport import replicated_group
    servers = replicated_group(2, n_members=2,
                               hb_deadline_s=hb_deadline_s)
    addrs = [s.address for s in servers]
    cos = []
    try:
        cos = [SocketCoordinator(addrs, 2, h, mesh_reinit=False,
                                 heartbeat=False, poll_s=0.002,
                                 timeout_s=60.0)
               for h in range(2)]

        def party(h, r):
            cos[h].all_gather("fo_g%d" % r, h, h)

        for r in (1, 2):   # r1 warms, r2 measures the failover
            if r == 2:
                servers[0].kill()
                t0 = time.perf_counter()
            ts = [threading.Thread(target=party, args=(h, r))
                  for h in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        dt = time.perf_counter() - t0
        assert servers[1].state.role == "primary", \
            "standby never promoted"
        return {"transport_failover_ms": round(dt * 1e3, 2),
                "transport_failover_term": servers[1].state.term}
    finally:
        for co in cos:
            co.close()
        for s in servers:
            try:
                s.close()
            except Exception:  # already killed
                pass


def bench_serving(n_replicas=2, clients=4, requests_per_client=30):
    """Fleet router p50/p99 + shed rate under synthetic load: export a
    tiny artifact, run 2 in-process replicas + the micro-batching
    router on the coordination plane, and drive concurrent clients
    through POST /infer."""
    import shutil
    import tempfile
    import threading
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.framework.transport import CoordServer
    from paddle_tpu.serving_fleet import (FleetRouter, ReplicaMember,
                                          http_json)
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_bench_serving_")
    members = []
    try:
        with scope_guard(Scope()):
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [8], dtype="float32")
                y = layers.softmax(layers.fc(x, 4))
            exe = pt.Executor()
            exe.run(startup)
            pt.save_inference_model(tmp, ["x"], [y], exe,
                                    main_program=main,
                                    format="stablehlo",
                                    batch_sizes=(8,))
        srv = CoordServer(n_replicas + 1, hb_deadline_s=5.0).start()
        members.append(srv)
        # register each member the moment it starts: a later start()
        # raising must not leak the earlier ones past the finally
        for i in range(n_replicas):
            members.append(ReplicaMember(tmp, srv.address, n_replicas,
                                         i, ctl_interval_s=0.25,
                                         hb_interval_s=0.25).start())
        router = FleetRouter(srv.address, n_replicas, max_batch=8,
                             batch_deadline_s=0.002, ctl_interval_s=0.25,
                             hb_interval_s=0.25,
                             poll_interval_s=0.05).start()
        members.append(router)
        deadline = time.monotonic() + 10.0
        while len(router.routable()) < n_replicas \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        rng = np.random.RandomState(0)
        xv = rng.rand(2, 8).astype(np.float32).tolist()
        lat, shed, errs = [], [0], [0]
        lock = threading.Lock()

        def client():
            for _ in range(requests_per_client):
                t0 = time.perf_counter()
                try:
                    status, _ = http_json(
                        "POST", router.url + "/infer",
                        {"feeds": {"x": xv}}, timeout_s=10.0)
                except (OSError, ValueError):
                    status = -1
                dt = time.perf_counter() - t0
                with lock:
                    if status == 200:
                        lat.append(dt)
                    elif status == 503:
                        shed[0] += 1
                    else:
                        errs[0] += 1

        ts = [threading.Thread(target=client) for _ in range(clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        total = len(lat) + shed[0] + errs[0]
        lat.sort()
        # no successful request: a finite budget-busting sentinel, not
        # inf — json.dumps(inf) emits non-RFC "Infinity" and breaks
        # every non-Python consumer of the bench line, and a -1 would
        # silently PASS the "max" budgets
        fail_ms = 1e9
        p50 = lat[len(lat) // 2] * 1e3 if lat else fail_ms
        p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3 \
            if lat else fail_ms
        out = {"serving_p50_ms": round(p50, 3),
               "serving_p99_ms": round(p99, 3),
               "serving_shed_rate": round(shed[0] / float(total), 4)
               if total else 1.0,
               "serving_error_rate": round(errs[0] / float(total), 4)
               if total else 1.0,
               "serving_errors": errs[0],
               "serving_requests": total}

        # ---- multi-tenant QoS phase: the same replicas behind a
        # CLASSED router (fresh coordination group so both routers
        # never share a leader lease). One client per class; gold p99
        # and Jain's fairness index over per-class success ratios
        # x_c = ok_c / offered_c: J = (sum x)^2 / (n * sum x^2)
        srv2 = CoordServer(n_replicas + 1, hb_deadline_s=5.0).start()
        members.append(srv2)
        for i in range(n_replicas):
            members.append(ReplicaMember(tmp, srv2.address,
                                         n_replicas, i,
                                         ctl_interval_s=0.25,
                                         hb_interval_s=0.25).start())
        qrouter = FleetRouter(
            srv2.address, n_replicas, max_batch=8,
            batch_deadline_s=0.002, ctl_interval_s=0.25,
            hb_interval_s=0.25, poll_interval_s=0.05,
            tenant_classes={
                "gold": {"weight": 4, "priority": 2},
                "silver": {"weight": 2, "priority": 1},
                "bronze": {"weight": 1, "priority": 0}}).start()
        members.append(qrouter)
        deadline = time.monotonic() + 10.0
        while len(qrouter.routable()) < n_replicas \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        classes = ("gold", "silver", "bronze")
        qlat = {c: [] for c in classes}
        qok = {c: 0 for c in classes}

        def qclient(tenant):
            for _ in range(requests_per_client):
                t0 = time.perf_counter()
                try:
                    status, _ = http_json(
                        "POST", qrouter.url + "/infer",
                        {"feeds": {"x": xv}}, timeout_s=10.0,
                        headers={"x-tenant": tenant,
                                 "x-deadline-ms": "10000"})
                except (OSError, ValueError):
                    status = -1
                dt = time.perf_counter() - t0
                with lock:
                    if status == 200:
                        qok[tenant] += 1
                        qlat[tenant].append(dt)

        ts = [threading.Thread(target=qclient, args=(c,))
              for c in classes for _ in range(max(1, clients // 3))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        offered = requests_per_client * max(1, clients // 3)
        ratios = [qok[c] / float(offered) for c in classes]
        sq = sum(r * r for r in ratios)
        fairness = (sum(ratios) ** 2) / (len(ratios) * sq) \
            if sq else 0.0
        glat = sorted(qlat["gold"])
        gold_p99 = glat[min(len(glat) - 1,
                            int(len(glat) * 0.99))] * 1e3 \
            if glat else fail_ms
        out.update({"serving_gold_p99_ms": round(gold_p99, 3),
                    "serving_fairness": round(fairness, 4),
                    "serving_class_ok": dict(qok)})
        return out
    finally:
        for m in reversed(members):
            m.close()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_router_failover(hb_deadline_s=1.0):
    """Router-tier HA: the outage a killed router costs one client.
    1 replica + 2 routers (the PR 11 HA tier) on one coordination
    group; a FleetClient pinned to router 0 (victim-first endpoint
    order) serves through it, a background client keeps load flowing,
    then router 0 is severed ABRUPTLY (listener + coordinator client
    down, no graceful queue drain — the SIGKILL shape an in-process
    bench can produce) and the clock runs until the pinned client's
    first successful request on the survivor: connection-refused
    rotation + idempotent token replay, end to end."""
    import shutil
    import tempfile
    import threading
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.framework.transport import CoordServer
    from paddle_tpu.serving_fleet import (FleetClient, FleetRouter,
                                          ReplicaMember)
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_bench_rtrfo_")
    members = []
    try:
        with scope_guard(Scope()):
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [8], dtype="float32")
                y = layers.softmax(layers.fc(x, 4))
            exe = pt.Executor()
            exe.run(startup)
            pt.save_inference_model(tmp, ["x"], [y], exe,
                                    main_program=main,
                                    format="stablehlo",
                                    batch_sizes=(8,))
        srv = CoordServer(3, hb_deadline_s=hb_deadline_s).start()
        members.append(srv)
        members.append(ReplicaMember(tmp, srv.address, 1, 0,
                                     n_routers=2, ctl_interval_s=0.25,
                                     hb_interval_s=0.25).start())
        routers = []
        for rid in (0, 1):
            r = FleetRouter(srv.address, 1, router_id=rid,
                            n_routers=2, max_batch=8,
                            batch_deadline_s=0.002,
                            ctl_interval_s=0.25, hb_interval_s=0.25,
                            poll_interval_s=0.05).start()
            routers.append(r)
            members.append(r)
        deadline = time.monotonic() + 10.0
        while any(len(r.routable()) < 1 for r in routers) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        xv = [[0.5] * 8, [0.25] * 8]
        client = FleetClient([routers[0].url, routers[1].url],
                             request_deadline_s=15.0, backoff_s=0.02)
        for _ in range(3):    # warm: the client is serving via r0
            client.infer({"x": xv})
        stop = threading.Event()

        def load():           # keeps "mid-load" honest
            side = FleetClient([routers[0].url, routers[1].url],
                               request_deadline_s=15.0,
                               backoff_s=0.02)
            while not stop.is_set():
                try:
                    side.infer({"x": xv})
                except Exception:   # noqa: BLE001 - background load
                    pass
        lt = threading.Thread(target=load, daemon=True)
        lt.start()
        r0 = routers[0]
        t0 = time.perf_counter()
        r0._stop.set()
        r0._server.shutdown()
        r0._server.server_close()
        r0._co.close()
        client.infer({"x": xv})   # rotates + replays onto the survivor
        dt = time.perf_counter() - t0
        stop.set()
        lt.join(timeout=5.0)
        return {"router_failover_ms": round(dt * 1e3, 2)}
    finally:
        for m in reversed(members):
            try:
                m.close()
            except Exception:   # already severed
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def bench_pipeline(steps=4):
    """Pipeline-parallel CompiledProgram on the pp=2 x dp=4 CPU mesh:
    per-step wall of the 1F1B lowering, the measured bubble fraction
    vs the schedule's tick-model ideal (1F1B runs M + 2(K-1) ticks;
    the per-tick cost is fitted from two microbatch counts at a FIXED
    MICRO-BATCH SIZE, batch = mb x M, so every tick does identical
    work), and the executor cache hit rate across schedule toggles
    (1f1b <-> gpipe repeats must hit)."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    from paddle_tpu.distributed.pipeline_program import pp_stage_guard
    from paddle_tpu.framework.compiler import CompiledProgram, \
        BuildStrategy
    from paddle_tpu.framework.scope import Scope, scope_guard

    k, dm, mb = 2, 32, 4
    rng = np.random.RandomState(0)

    def build(batch):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("bp_x", [batch, dm], "float32",
                            append_batch_size=False)
            h = x
            for i in range(4):
                with pp_stage_guard(i // 2):
                    h = layers.fc(h, size=dm, act="tanh")
            y = layers.data("bp_y", [batch, dm], "float32",
                            append_batch_size=False)
            loss = layers.reduce_mean(layers.square(h - y))
            optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    def strat(schedule="1f1b", m=4):
        bs = BuildStrategy(pp_stages=k, pp_micro_batches=m,
                           pp_schedule=schedule)
        bs.mesh_axes = {"pp": k, "dp": 4}
        return bs

    out = {}
    exe = pt.Executor()

    def wall(m, schedule="1f1b", n=steps):
        # CONSTANT micro-batch size (batch = mb * M): every tick does
        # the same work regardless of M, so the per-tick cost fitted
        # across microbatch counts is a real quantity — at fixed total
        # batch the per-tick work would shrink as M grows and the fit
        # would mostly measure the confound
        batch = mb * m
        xv = rng.randn(batch, dm).astype(np.float32)
        yv = rng.randn(batch, dm).astype(np.float32)
        with scope_guard(Scope()):
            main, startup, loss = build(batch)
            exe.run(startup)
            comp = CompiledProgram(main, strat(schedule, m))
            exe.run(comp, feed={"bp_x": xv, "bp_y": yv},
                    fetch_list=[loss])        # compile + warm
            # BEST-of-n, not mean: the bubble fraction is fitted from
            # the difference of two walls, and one contention spike
            # (GC, a loaded CI box) in the mean inflates the fitted
            # per-tick cost enough to clamp the fraction at 1
            best = None
            for _ in range(n):
                t0 = time.perf_counter()
                vals = exe.run(comp, feed={"bp_x": xv, "bp_y": yv},
                               fetch_list=[loss])
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            assert np.isfinite(np.asarray(vals[0])).all()
            return best, xv, yv

    m_lo, m_hi = 2, 8
    w_main, xv4, yv4 = wall(4)
    w_lo = wall(m_lo)[0]
    w_hi = wall(m_hi)[0]
    out["pp_step_s"] = round(w_main, 5)
    # 1F1B runs M + 2(K-1) ticks of CONSTANT per-tick work; fit the
    # per-tick cost a from the two microbatch counts, then bubble =
    # the 2(K-1) fill/drain ticks' share of the benched (M=4) step.
    # Broken overlap inflates a and the fraction rises toward 1.
    ticks = lambda m: m + 2 * (k - 1)
    a = (w_hi - w_lo) / float(ticks(m_hi) - ticks(m_lo))
    bubble = a * 2 * (k - 1) / w_main if w_main > 0 else 1.0
    out["pp_bubble_frac"] = round(max(0.0, min(1.0, bubble)), 4)
    out["pp_bubble_frac_ideal"] = round(2.0 * (k - 1) / ticks(4), 4)
    # cache behaviour across schedule toggles on the M=4 program:
    # 1f1b re-used from the wall run above would need its scope — use
    # a fresh scope + fresh executor counters; the first 1f1b and
    # gpipe lower, every repeat hits
    with scope_guard(Scope()):
        main, startup, loss = build(mb * 4)
        exe2 = pt.Executor()
        exe2.run(startup)
        feed = {"bp_x": xv4, "bp_y": yv4}
        for schedule in ("1f1b", "gpipe", "1f1b", "gpipe"):
            comp = CompiledProgram(main, strat(schedule, 4))
            exe2.run(comp, feed=feed, fetch_list=[loss])
        total = exe2.cache_hits + exe2.cache_misses
        out["pp_cache_hit_rate"] = round(
            exe2.cache_hits / float(total), 4) if total else 0.0
        out["pp_cache_compiles"] = exe2.cache_misses
    return out


def bench_pp_recut(n_steps=8):
    """Elastic pp re-cut wall (ISSUE-18): an in-process 3-host
    pp=2 x dp=4 pod loses one host mid-run, the survivors re-stack both
    stages onto one slot, and pp_recut_ms is the wall from the re-cut
    decision committing (the start of the re-lowering) to the FIRST
    completed post-re-cut training step — i.e. re-lower + state
    re-placement + the re-cut executable's compile, the whole outage
    the elastic path trades against a consensus rewind."""
    import tempfile

    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    from paddle_tpu.distributed.pipeline_program import pp_stage_guard
    from paddle_tpu.framework import resilience
    from paddle_tpu.framework.compiler import CompiledProgram, \
        BuildStrategy
    from paddle_tpu.framework.coordination import (ElasticTrainer,
                                                   LocalCoordinator)
    from paddle_tpu.framework.resilience import (ResilientTrainer,
                                                 RetryPolicy)
    from paddle_tpu.framework.scope import Scope, scope_guard

    dm, batch = 16, 16
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("br_x", [batch, dm], "float32",
                        append_batch_size=False)
        h = x
        for i in range(4):
            with pp_stage_guard(i // 2):
                h = layers.fc(h, size=dm, act="tanh")
        y = layers.data("br_y", [batch, dm], "float32",
                        append_batch_size=False)
        loss = layers.reduce_mean(layers.square(h - y))
        optimizer.SGD(0.2).minimize(loss)
    rng = np.random.RandomState(3)
    feeds = [{"br_x": rng.randn(batch, dm).astype(np.float32),
              "br_y": rng.randn(batch, dm).astype(np.float32)}
             for _ in range(n_steps)]
    root = tempfile.mkdtemp(prefix="bench_pp_recut_")
    resilience.install(None)
    resilience.clear_events()
    trainers, walls = [], []
    for hid in range(3):
        sc, exe = Scope(), pt.Executor()
        with scope_guard(sc):
            exe.run(startup)
        bs = BuildStrategy(pp_stages=2, pp_micro_batches=4)
        bs.mesh_axes = {"pp": 2, "dp": 4}
        t = ResilientTrainer(
            exe, CompiledProgram(main, bs),
            os.path.join(root, "h%d" % hid), fetch_list=[loss],
            checkpoint_every=2, scope=sc,
            retry_policy=RetryPolicy(base_delay_s=0.0, jitter=0.0,
                                     sleep=lambda s: None))
        def timed(*a, _orig=t._dispatch_batches, **kw):
            out = _orig(*a, **kw)
            walls.append(time.time())
            return out

        t._dispatch_batches = timed
        trainers.append(t)
    pod = ElasticTrainer(trainers, LocalCoordinator(3, timeout_s=300.0),
                         rejoin=False)
    with resilience.inject("step:die@%d" % (n_steps + 2)):
        pod.run(feeds)
    recuts = resilience.events("elastic_pp_recut")
    out = {}
    if recuts:
        # decision commit = event stamp minus the re-lowering latency
        # it reports; first post-re-cut step = first dispatch wall
        # after the LAST survivor finished re-cutting
        t_start = min(e["time"] - e["latency_s"] for e in recuts)
        t_done = max(e["time"] for e in recuts)
        post = [w for w in walls if w > t_done]
        if post:
            out["pp_recut_ms"] = round((min(post) - t_start) * 1e3, 3)
            out["pp_recut_resharded"] = int(recuts[0]["resharded"])
    resilience.clear_events()
    return out


def bench_buddy(windows=5):
    """Buddy-checkpoint tier walls (ISSUE 19): the per-window tax —
    encode(+zlib)+put of one host's scope snapshot into the ring
    buddy's coordinator mailbox — and the two recovery paths head to
    head: buddy restore (metadata verdict + mailbox fetch + decode +
    adopt, at most ONE window of lost work) vs the disk rewind it
    front-runs (a real load_checkpoint of the same state). The disk
    number here is I/O only — a rewind ALSO re-executes every window
    since the last disk commit, which this section does not count, so
    the buddy win is understated on purpose."""
    import shutil
    import statistics
    import tempfile

    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.io as io_mod
    from paddle_tpu.framework import buddy, resilience
    from paddle_tpu.framework.coordination import LocalCoordinator
    from paddle_tpu.framework.scope import Scope, scope_guard

    main, startup, _loss = _build_train(hidden=256)
    sc, exe = Scope(), pt.Executor()
    with scope_guard(sc):
        exe.run(startup)
    # the payload is the program's persistable state — exactly what the
    # pod tier snapshots at every committed window boundary
    arrays = io_mod._collect(
        main, sc, lambda v: v.persistable and not v.name.startswith("@"))
    co, members = LocalCoordinator(2, timeout_s=60.0), [0, 1]
    walls = []
    for gen in range(1, windows + 1):
        t0 = time.perf_counter()
        for h in members:
            assert buddy.send_snapshot(co, h, members, gen, arrays)
        walls.append((time.perf_counter() - t0) / len(members) * 1e3)
    out = {"buddy_snapshot_ms": round(statistics.median(walls), 3)}

    class _Dst(object):   # bare find_var/set_var adoption target
        def __init__(self):
            self.d = {}

        def find_var(self, n):
            return self.d.get(n)

        def set_var(self, n, v):
            self.d[n] = v

    # buddy restore: host 1 just died, survivor host 0 re-adopts its
    # own gen-N mailbox copy — verdict (metadata only; the agreement
    # gather's cost is transport_gather_ms) + fetch + decode + adopt
    dst = _Dst()
    t0 = time.perf_counter()
    verdict = buddy.plan_restore(co, [0], [1], members, windows)
    assert verdict is None, verdict
    got_arrays, _fs = buddy.fetch_and_decode(co, 0, windows)
    buddy.adopt_arrays(dst, got_arrays)
    out["buddy_restore_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    for name, ref in arrays.items():   # zlib mailbox restores bitwise
        np.testing.assert_array_equal(np.asarray(dst.d[name]), ref)
    # the disk rewind it replaces: the same state through the real
    # checkpoint path — save once (untimed), restore into a cold scope
    root = tempfile.mkdtemp(prefix="bench_buddy_")
    try:
        with scope_guard(sc):
            io_mod.save_checkpoint(exe, root, main, step=windows,
                                   scope=sc)
        cold = Scope()
        t0 = time.perf_counter()
        got = io_mod.load_checkpoint(exe, root, main, scope=cold)
        out["buddy_disk_restore_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        assert got == windows
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # p2p + delta walls (ISSUE 20): the churn-skewed reference scope —
    # one large STATIC embedding-style leaf (the bulk of real scopes:
    # frozen or slowly-moving tables) plus small leaves that churn
    # every window. The delta path should skip the static leaf after
    # the first full send, so the per-window wire collapses to the
    # churning minority; buddy_delta_bytes_ratio is the median
    # delta-wire / last-full-wire across the timed windows.
    rng = np.random.RandomState(7)
    churn = {"emb/table": rng.randn(1024, 256).astype(np.float32)}
    for i in range(4):
        churn["head/w%d" % i] = rng.randn(64, 64).astype(np.float32)
    co2 = LocalCoordinator(2, timeout_s=60.0)
    tracker = buddy.DeltaTracker(rebase_every=windows + 2)
    assert buddy.send_snapshot(co2, 0, members, 0, churn,
                               tracker=tracker)   # seed full (untimed)
    p2p_walls, ratios = [], []
    for gen in range(1, windows + 1):
        for i in range(4):   # only the small heads churn
            churn["head/w%d" % i] = rng.randn(64, 64).astype(np.float32)
        t0 = time.perf_counter()
        assert buddy.send_snapshot(co2, 0, members, gen, churn,
                                   tracker=tracker)
        p2p_walls.append((time.perf_counter() - t0) * 1e3)
        ratios.append(resilience.buddy_delta_ratio())
    out["buddy_p2p_send_ms"] = round(statistics.median(p2p_walls), 3)
    out["buddy_delta_bytes_ratio"] = round(statistics.median(ratios), 6)
    # the chain restores bitwise through the delta links
    rec = co2.mailbox_of(1).reconstruct(0)
    got_arrays, step, _fs = io_mod.decode_state_blob(rec["blob"])
    assert step == windows
    for name, ref in churn.items():
        np.testing.assert_array_equal(got_arrays[name], ref)
    resilience.clear_buddy_gens()
    return out


def bench_obs(steps=11, requests=21):
    """Tracing-overhead gate (the obs spans tentpole): the exact same
    dp-sharded executor step and router /infer request measured
    spans-OFF then spans-ON — median walls and their ratio — plus the
    absolute cost of recording one span. The obs layer sits on every
    hot path (executor dispatch, router intake, coordination rounds),
    so this section is what keeps it from ever silently taxing them:
    the ratios are BUDGETS-gated in tier-1."""
    import numpy as np
    import jax
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework import obs
    from paddle_tpu.framework.compiler import CompiledProgram, \
        BuildStrategy
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.framework.transport import CoordServer
    from paddle_tpu.serving_fleet import (FleetRouter, ReplicaMember,
                                          http_json)
    import shutil
    import tempfile

    was_enabled = obs.enabled()
    out = {}

    def median(walls):
        walls = sorted(walls)
        return walls[len(walls) // 2]

    try:
        # -- executor leg: dp CompiledProgram step ----------------------
        n_dev = min(8, len(jax.devices()))
        feed = _batch(np.random.RandomState(0), n=2 * n_dev)
        with scope_guard(Scope()):
            main, startup, loss = _build_train()
            exe = pt.Executor()
            exe.run(startup)
            bs = BuildStrategy()
            bs.mesh_axes = {"dp": n_dev}
            comp = CompiledProgram(main, bs)
            exe.run(comp, feed=feed, fetch_list=[loss])   # compile+warm

            def step_walls():
                walls = []
                for _ in range(steps):
                    t0 = time.perf_counter()
                    exe.run(comp, feed=feed, fetch_list=[loss])
                    walls.append(time.perf_counter() - t0)
                return median(walls)

            obs.disable()
            off = step_walls()
            obs.enable()
            on = step_walls()
            obs.disable()
            obs.clear()
        out["obs_step_off_s"] = round(off, 5)
        out["obs_step_on_s"] = round(on, 5)
        out["obs_step_overhead_ratio"] = round(
            on / off if off > 0 else 1.0, 4)

        # -- span record microcost -------------------------------------
        obs.enable()
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            with obs.span("bench.noop", k=1):
                pass
        dt = time.perf_counter() - t0
        obs.disable()
        obs.clear()
        out["obs_span_record_us"] = round(dt / n * 1e6, 3)

        # -- router leg: one replica + router, sequential requests -----
        tmp = tempfile.mkdtemp(prefix="paddle_tpu_bench_obs_")
        members = []
        try:
            with scope_guard(Scope()):
                main, startup = pt.Program(), pt.Program()
                with pt.program_guard(main, startup):
                    x = layers.data("x", [8], dtype="float32")
                    y = layers.softmax(layers.fc(x, 4))
                exe = pt.Executor()
                exe.run(startup)
                pt.save_inference_model(tmp, ["x"], [y], exe,
                                        main_program=main,
                                        format="stablehlo",
                                        batch_sizes=(8,))
            srv = CoordServer(2, hb_deadline_s=5.0).start()
            members.append(srv)
            members.append(ReplicaMember(tmp, srv.address, 1, 0,
                                         ctl_interval_s=0.25,
                                         hb_interval_s=0.25).start())
            router = FleetRouter(srv.address, 1, max_batch=8,
                                 batch_deadline_s=0.001,
                                 ctl_interval_s=0.25,
                                 hb_interval_s=0.25,
                                 poll_interval_s=0.05).start()
            members.append(router)
            deadline = time.monotonic() + 10.0
            while not router.routable() \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            xv = np.ones((2, 8), np.float32).tolist()

            def request_walls():
                walls = []
                for _ in range(requests):
                    t0 = time.perf_counter()
                    status, _ = http_json("POST",
                                          router.url + "/infer",
                                          {"feeds": {"x": xv}},
                                          timeout_s=10.0)
                    walls.append(time.perf_counter() - t0)
                    assert status == 200, status
                return median(walls)

            request_walls()               # warm the serving path
            obs.disable()
            r_off = request_walls()
            obs.enable()
            r_on = request_walls()
            obs.disable()
            obs.clear()
        finally:
            for m in reversed(members):
                m.close()
            shutil.rmtree(tmp, ignore_errors=True)
        out["obs_router_off_ms"] = round(r_off * 1e3, 3)
        out["obs_router_on_ms"] = round(r_on * 1e3, 3)
        out["obs_router_overhead_ratio"] = round(
            r_on / r_off if r_off > 0 else 1.0, 4)
    finally:
        (obs.enable if was_enabled else obs.disable)()
    return out


def bench_analysis():
    """Program-verifier wall (ISSUE 15): the cost of keeping
    BuildStrategy.verify_program="warn" ON by default.

      analysis_verify_s        — one strict verifier walk over the
                                 ERNIE/BERT-base pretrain program (the
                                 headline graph: 12 layers, full op
                                 count — graph size is what the walk
                                 scales with, feed shapes are free)
      analysis_overhead_ratio  — verifier wall / trace+lower wall on
                                 the SAME small train step: the
                                 verifier must stay ≪ the compile work
                                 it fronts, or "warn by default" stops
                                 being free
      analysis_bert_errors     — error-severity diagnostics on the
                                 clean headline program (must be 0:
                                 the no-false-positive contract,
                                 gated here as well as in tests)
    """
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework import analysis
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.models import bert

    cfg = bert.bert_base()
    main, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch_size=8, seq_len=128)
    feed_names = [getattr(f, "name", f) for f in (
        feeds.values() if isinstance(feeds, dict) else feeds)]
    t0 = time.perf_counter()
    result = analysis.verify_program(main, feeds=feed_names,
                                     fetch_list=list(fetch.values()))
    verify_s = time.perf_counter() - t0

    with scope_guard(Scope()):
        small_main, small_startup, loss = _build_train()
        exe = pt.Executor()
        exe.run(small_startup)
        feed = _batch(np.random.RandomState(0))
        t0 = time.perf_counter()
        exe.dump_hlo(small_main, feed=feed, fetch_list=[loss],
                     include_compiled=False)
        lower_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        analysis.verify_program(
            small_main, feeds={k: np.shape(v) for k, v in feed.items()},
            fetch_list=[loss])
        small_verify_s = time.perf_counter() - t0
    return {"analysis_verify_s": round(verify_s, 4),
            "analysis_overhead_ratio": round(
                small_verify_s / max(lower_s, 1e-9), 4),
            "analysis_bert_errors": len(result.errors())}


def bench_numerics(pairs=25, steps_budget=3):
    """Numeric-fault plane costs (ISSUE 17).

      numerics_overhead_frac — the in-graph per-var finite mask
          (numeric_policy="raise") vs the plain dp step on the SAME
          warmed CompiledProgram pair. Measured as the median of
          strictly interleaved pairwise ratios (off_i then on_i,
          ratio_i = on_i/off_i): pairing adjacent walls cancels the
          slow frequency/load drift that makes sequential medians lie
          on shared boxes. Clamped at 0 — the mask cannot speed a step
          up; a negative frac is pure noise.
      fault_recovery_ms — wall of the ONE poisoned step under
          numeric_policy="skip": a failpoint corrupts the batch on the
          wire, the mask localizes the culprit var, the in-graph
          jnp.where revert discards the update. This is the unit of
          work every skip/rewind recovery pays per bad batch.
    """
    import numpy as np
    import jax
    import paddle_tpu as pt
    from paddle_tpu.framework import faultinject
    from paddle_tpu.framework.compiler import CompiledProgram, \
        BuildStrategy
    from paddle_tpu.framework.scope import Scope, scope_guard

    n_dev = min(8, len(jax.devices()))
    feed = _batch(np.random.RandomState(0), n=4 * n_dev)
    out = {}

    def setup(policy):
        sc = Scope()
        with scope_guard(sc):
            main, startup, loss = _build_train()
            exe = pt.Executor()
            exe.run(startup)
            bs = BuildStrategy()
            bs.mesh_axes = {"dp": n_dev}
            if policy is not None:
                bs.numeric_policy = policy
            comp = CompiledProgram(main, bs)
            for _ in range(steps_budget):          # compile + warm
                exe.run(comp, feed=feed, fetch_list=[loss])
        return sc, exe, comp, loss

    def one(leg):
        sc, exe, comp, loss = leg
        with scope_guard(sc):
            t0 = time.perf_counter()
            exe.run(comp, feed=feed, fetch_list=[loss])
            return time.perf_counter() - t0

    plain, masked = setup(None), setup("raise")
    ratios = []
    for _ in range(pairs):
        off = one(plain)
        on = one(masked)
        ratios.append(on / off if off > 0 else 1.0)
    ratios.sort()
    med = ratios[len(ratios) // 2]
    out["numerics_step_off_s"] = round(one(plain), 5)
    out["numerics_step_on_s"] = round(one(masked), 5)
    out["numerics_overhead_frac"] = round(max(0.0, med - 1.0), 4)

    # -- skip-path recovery: one poisoned step, wall to discard -------
    sc, exe, comp, loss = setup("skip")
    with scope_guard(sc):
        with faultinject.failpoints(["executor.step:corrupt=x@1"]):
            t0 = time.perf_counter()
            exe.run(comp, feed=feed, fetch_list=[loss])
            recovery = time.perf_counter() - t0
        exe.run(comp, feed=feed, fetch_list=[loss])   # budget resets
    out["fault_recovery_ms"] = round(recovery * 1e3, 3)
    return out


# ---------------------------------------------------------------------------
# round trend tracking
# ---------------------------------------------------------------------------

def _round_files(rounds_dir):
    return sorted(glob.glob(os.path.join(rounds_dir, "round_*.json")))


def save_round(report, rounds_dir):
    """Persist this run's report as the next round_NNNN.json."""
    os.makedirs(rounds_dir, exist_ok=True)
    existing = _round_files(rounds_dir)
    nxt = 1
    if existing:
        tail = os.path.basename(existing[-1])[len("round_"):-len(".json")]
        try:
            nxt = int(tail) + 1
        except ValueError:
            nxt = len(existing) + 1
    path = os.path.join(rounds_dir, "round_%04d.json" % nxt)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def check_drift(metrics, rounds_dir, window=8, factor=DRIFT_FACTOR):
    """Compare current metrics against the median of the last `window`
    persisted rounds; return human-readable drift flags (empty = ok).

    This catches the slide the absolute budgets are too loose to see: a
    metric can stay under its order-of-magnitude budget while quietly
    worsening round over round. "max" metrics drift when current >
    factor * median(history); "min" metrics when current < median /
    factor. Fewer than 2 historical rounds = nothing to compare."""
    history = {}
    for path in _round_files(rounds_dir)[-window:]:
        try:
            with open(path) as f:
                past = json.load(f).get("metrics", {})
        except (OSError, ValueError):
            continue
        for k, v in past.items():
            if isinstance(v, (int, float)):
                history.setdefault(k, []).append(float(v))
    flags = []
    for name, (kind, _budget) in BUDGETS.items():
        vals = history.get(name, [])
        cur = metrics.get(name)
        if len(vals) < 2 or not isinstance(cur, (int, float)):
            continue
        vals = sorted(vals)
        med = vals[len(vals) // 2]
        if kind == "max" and med > 0 and cur > factor * med:
            flags.append("%s=%.4g drifted above %.1fx its %d-round "
                         "median %.4g" % (name, cur, factor, len(vals),
                                          med))
        elif kind == "min" and med > 0 and cur < med / factor:
            flags.append("%s=%.4g drifted below 1/%.1fx its %d-round "
                         "median %.4g" % (name, cur, factor, len(vals),
                                          med))
    return flags


def run_all(rounds_dir=None):
    """Run every section; returns the report dict (never raises — a
    broken section lands as an "error" entry so the JSON line and the
    other sections still ship). With rounds_dir, the report is checked
    for drift against the persisted history and then saved as the next
    round."""
    metrics, errors = {}, {}
    for name, fn in (("trace_lower", bench_trace_lower),
                     ("cache_hit", bench_cache_hit),
                     ("quantized_step", bench_quantized_step),
                     ("feed", bench_feed),
                     ("pallas", bench_pallas),
                     ("costmodel", bench_costmodel),
                     ("pipeline", bench_pipeline),
                     ("pp_recut", bench_pp_recut),
                     ("buddy", bench_buddy),
                     ("transport", bench_transport),
                     ("failover", bench_failover),
                     ("serving", bench_serving),
                     ("router_failover", bench_router_failover),
                     ("obs", bench_obs),
                     ("analysis", bench_analysis),
                     ("numerics", bench_numerics)):
        t0 = time.perf_counter()
        try:
            metrics.update(fn())
        except Exception as e:  # pragma: no cover - section crash
            errors[name] = "%s: %s" % (type(e).__name__, e)
        metrics["%s_section_s" % name] = round(
            time.perf_counter() - t0, 3)
    report = {"metric": "bench_micro", "unit": "mixed",
              "platform": _platform(), "metrics": metrics}
    violations = check_budgets(metrics)
    report["budgets_ok"] = not violations and not errors
    if violations:
        report["budget_violations"] = violations
    if errors:
        report["errors"] = errors
    if rounds_dir:
        flags = check_drift(metrics, rounds_dir)
        report["drift_ok"] = not flags
        if flags:
            report["drift_flags"] = flags
        # the gate arms only with a calibrated noise floor (counted
        # BEFORE this round is saved: prior rounds only)
        report["drift_gating"] = \
            len(_round_files(rounds_dir)) >= MIN_DRIFT_GATE_ROUNDS
        report["round_file"] = save_round(report, rounds_dir)
    return report


def _platform():
    import jax
    return sorted({d.platform for d in jax.devices()})


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    rounds_dir = os.environ.get("PADDLE_TPU_MICRO_ROUNDS_DIR") or None
    # drift GATES by default (ROADMAP item 4, final slice) once the
    # rounds history is deep enough to trust — see drift_gating in
    # run_all; --fail-on-drift is kept as an accepted no-op for
    # existing CI invocations
    fail_on_drift = True
    i = 0
    while i < len(argv):
        if argv[i] == "--rounds-dir" and i + 1 < len(argv):
            rounds_dir = argv[i + 1]
            i += 2
        elif argv[i] == "--fail-on-drift":
            fail_on_drift = True
            i += 1
        elif argv[i] == "--no-fail-on-drift":
            fail_on_drift = False
            i += 1
        else:
            print("usage: bench_micro.py [--rounds-dir DIR] "
                  "[--fail-on-drift | --no-fail-on-drift]",
                  file=sys.stderr)
            return 2
    _force_cpu()
    report = run_all(rounds_dir=rounds_dir)
    print(json.dumps(report))
    # drift fails the run only when the gate is ARMED (enough history
    # to trust the median) and --no-fail-on-drift did not opt out
    drift_fails = fail_on_drift and not report.get("drift_ok", True) \
        and report.get("drift_gating", False)
    return 0 if report["budgets_ok"] and not drift_fails else 1


if __name__ == "__main__":
    sys.exit(main())
