"""Cell-based RNN API (ref python/paddle/fluid/layers/rnn.py):
RNNCell/GRUCell/LSTMCell + rnn()/lstm()/dynamic_lstmp()."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.framework.scope import Scope, scope_guard


def run(build, feed, fetches_fn, steps=1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        fetches = build()
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        for _ in range(steps):
            outs = exe.run(main, feed=feed, fetch_list=list(fetches))
    return [np.asarray(o) for o in outs]


def test_gru_cell_rnn_masking_and_finals():
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 6, 3).astype(np.float32)
    lens = np.array([6, 4], np.int64)

    def build():
        x = layers.data('x', [2, 6, 3], 'float32',
                        append_batch_size=False)
        l = layers.data('l', [2], 'int64', append_batch_size=False)
        cell = layers.GRUCell(hidden_size=4)
        out, final = layers.rnn(cell, x, sequence_length=l)
        return out, final

    o, f = run(build, {'x': xv, 'l': lens}, None)
    assert o.shape == (2, 6, 4) and f.shape == (2, 4)
    assert np.all(o[1, 4:] == 0)                 # padded steps zeroed
    np.testing.assert_allclose(f[1], o[1, 3], rtol=1e-5)  # last valid
    np.testing.assert_allclose(f[0], o[0, 5], rtol=1e-5)


def test_gru_cell_matches_manual_recurrence():
    """rnn(GRUCell) against a numpy replay of the same parameters."""
    rng = np.random.RandomState(1)
    xv = rng.randn(2, 4, 3).astype(np.float32)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data('x', [2, 4, 3], 'float32',
                        append_batch_size=False)
        cell = layers.GRUCell(hidden_size=5, name="oracle_gru")
        out, final = layers.rnn(cell, x)
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        o, = exe.run(main, feed={'x': xv}, fetch_list=[out])
        params = {n: np.asarray(scope.find_var(n))
                  for n in scope.keys() if n.startswith("oracle_gru")}
    o = np.asarray(o)
    gw = next(v for k, v in params.items() if k.endswith("_gate_w"))
    gb = next(v for k, v in params.items() if k.endswith("_gate_b"))
    cw = next(v for k, v in params.items() if k.endswith("_cand_w"))
    cb = next(v for k, v in params.items() if k.endswith("_cand_b"))
    h = np.zeros((2, 5), np.float32)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    for t in range(4):
        gates = sig(np.concatenate([xv[:, t], h], -1) @ gw + gb)
        u, r = gates[:, :5], gates[:, 5:]
        cand = np.tanh(np.concatenate([xv[:, t], r * h], -1) @ cw + cb)
        h = u * h + (1 - u) * cand
        np.testing.assert_allclose(o[:, t], h, rtol=1e-4, atol=1e-5)


def test_lstm_cell_rnn_and_reverse():
    rng = np.random.RandomState(2)
    xv = rng.randn(2, 5, 3).astype(np.float32)

    def build():
        x = layers.data('x', [2, 5, 3], 'float32',
                        append_batch_size=False)
        cell = layers.LSTMCell(hidden_size=4)
        out, (fh, fc) = layers.rnn(cell, x)
        rcell = layers.LSTMCell(hidden_size=4)
        rout, _ = layers.rnn(rcell, x, is_reverse=True)
        tm_out, _ = layers.rnn(layers.GRUCell(hidden_size=4),
                               layers.transpose(x, perm=[1, 0, 2]),
                               time_major=True)
        return out, fh, fc, rout, tm_out

    o, fh, fc, ro, tmo = run(build, {'x': xv}, None)
    assert o.shape == (2, 5, 4)
    assert fh.shape == (2, 4) and fc.shape == (2, 4)
    np.testing.assert_allclose(fh, o[:, -1], rtol=1e-5)
    assert ro.shape == (2, 5, 4)
    assert tmo.shape == (5, 2, 4)  # time-major in, time-major out


def test_rnn_trains():
    rng = np.random.RandomState(3)
    xv = rng.randn(4, 6, 3).astype(np.float32)
    yv = (xv.sum(axis=(1, 2), keepdims=False) > 0).astype(
        np.int64).reshape(-1, 1)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data('x', [4, 6, 3], 'float32',
                        append_batch_size=False)
        y = layers.data('y', [4, 1], 'int64', append_batch_size=False)
        out, final = layers.rnn(layers.GRUCell(hidden_size=8), x)
        logits = layers.fc(final, size=2)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, y))
        optimizer.Adam(1e-2).minimize(loss)
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        vals = [float(np.asarray(exe.run(main, feed={'x': xv, 'y': yv},
                                         fetch_list=[loss])[0])
                      .reshape(-1)[0]) for _ in range(30)]
    assert vals[-1] < vals[0] * 0.5


def test_lstm_wrapper_and_lstmp():
    rng = np.random.RandomState(4)
    xv = rng.randn(2, 6, 3).astype(np.float32)

    def build():
        x = layers.data('x', [2, 6, 3], 'float32',
                        append_batch_size=False)
        rout, lh, lc = layers.lstm(x, None, None, max_len=6,
                                   hidden_size=4, num_layers=2,
                                   is_bidirec=True)
        proj = layers.fc(x, size=16, num_flatten_dims=2,
                         bias_attr=False)
        p_out, c_out = layers.dynamic_lstmp(proj, size=16, proj_size=3)
        return rout, lh, lc, p_out, c_out

    rout, lh, lc, p_out, c_out = run(build, {'x': xv}, None)
    assert rout.shape == (2, 6, 8)          # bi => 2*hidden
    assert lh.shape == (4, 2, 4)            # layers*dirs, B, H
    assert p_out.shape == (2, 6, 3)         # projected
    assert c_out.shape == (2, 6, 4)         # cell stays hidden-sized
    assert np.isfinite(p_out).all() and np.isfinite(rout).all()


def test_grad_through_nondiff_shape_ref():
    """Regression (backward.py): a differentiable var feeding a
    declared-nondiff slot (fill_constant_batch_size_like's Input) must
    not register a dangling grad contribution."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data('x', [3, 4], 'float32', append_batch_size=False)
        h = layers.fc(x, size=4)
        zeros = layers.fill_constant_batch_size_like(
            h, shape=[-1, 4], dtype='float32', value=0.0)
        out = layers.elementwise_add(h, zeros)
        loss = layers.reduce_sum(layers.square(out))
        gx, = pt.gradients(loss, [x])
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        g, = exe.run(main, feed={'x': np.ones((3, 4), np.float32)},
                     fetch_list=[gx])
    assert np.isfinite(np.asarray(g)).all()


def test_dynamic_decode_beam_invariants():
    V, D, H, B = 11, 6, 8, 3
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        enc = layers.data('enc', [B, H], 'float32',
                          append_batch_size=False)
        cell = layers.GRUCell(hidden_size=H, name='dd_cell')

        def emb(ids):
            return layers.reshape(layers.embedding(
                ids, size=[V, D],
                param_attr=pt.ParamAttr(name='dd_emb')), [-1, D])

        def out_fn(h):
            return layers.fc(h, size=V,
                             param_attr=pt.ParamAttr(name='dd_fc_w'),
                             bias_attr=pt.ParamAttr(name='dd_fc_b'))

        bsd = layers.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                       beam_size=3, embedding_fn=emb,
                                       output_fn=out_fn)
        ids, final = layers.dynamic_decode(bsd, inits=enc,
                                           max_step_num=4)
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        rng = np.random.RandomState(5)
        iv, = exe.run(main,
                      feed={'enc': rng.randn(B, H).astype(np.float32)},
                      fetch_list=[ids])
    iv = np.asarray(iv)
    assert iv.shape == (B, 3, 4)
    assert iv.min() >= 0 and iv.max() < V
    for n in range(B):
        for bm in range(3):
            seen = False
            for t in range(4):
                if seen:
                    assert iv[n, bm, t] == 1
                if iv[n, bm, t] == 1:
                    seen = True
    assert len({tuple(iv[0, b]) for b in range(3)}) == 3


def test_beam_search_functional_step():
    """beam_search one step: highest candidates win; frozen rows only
    re-emit end_id at unchanged score."""
    B, b, K, END = 2, 2, 3, 9
    pre_ids = np.array([[3], [END], [4], [5]], np.int64)   # (B*b, 1)
    pre_scores = np.array([[0.0], [-1.0], [-0.5], [-2.0]], np.float32)
    cand_ids = np.tile(np.array([[5, 6, END]], np.int64), (B * b, 1))
    cand_scores = np.array([
        [-0.1, -2.0, -3.0],     # row 0 live: only -0.1 beats the
        [-9.0, -9.0, -9.0],     # frozen row 1 (pre=END, score -1.0)
        [-0.3, -0.9, -4.0],
        [-0.4, -0.5, -5.0]], np.float32)

    def build():
        pi = layers.data('pi', [B * b, 1], 'int64',
                         append_batch_size=False)
        ps = layers.data('ps', [B * b, 1], 'float32',
                         append_batch_size=False)
        ci = layers.data('ci', [B * b, K], 'int64',
                         append_batch_size=False)
        cs = layers.data('cs', [B * b, K], 'float32',
                         append_batch_size=False)
        si, ss, parent = layers.beam_search(
            pi, ps, ci, cs, beam_size=b, end_id=END,
            return_parent_idx=True)
        return si, ss, parent

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        fetches = build()
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        si, ss, parent = exe.run(
            main, feed={'pi': pre_ids, 'ps': pre_scores, 'ci': cand_ids,
                        'cs': cand_scores}, fetch_list=list(fetches))
    si = np.asarray(si).reshape(B, b)
    ss = np.asarray(ss).reshape(B, b)
    parent = np.asarray(parent).reshape(B, b)
    # batch row 0: best is live beam0's -0.1 (id 5); second is frozen
    # beam1 re-emitting END at its pre_score -1.0
    assert si[0, 0] == 5 and abs(ss[0, 0] + 0.1) < 1e-5
    assert si[0, 1] == END and abs(ss[0, 1] + 1.0) < 1e-5
    assert parent[0, 0] == 0 and parent[0, 1] == 1
    # batch row 1: -0.3 (beam0 id 5) then -0.4 (beam1 id 5)
    assert si[1, 0] == 5 and abs(ss[1, 0] + 0.3) < 1e-5
    assert si[1, 1] == 5 and abs(ss[1, 1] + 0.4) < 1e-5


def test_beam_search_decode_backtrace():
    """Two-step backtrace: step-2 winners descending from step-1 beam 1
    must carry beam 1's prefix."""
    b = 2
    step1_ids = np.array([[7], [8], [5], [6]], np.int64)
    step2_ids = np.array([[3], [4], [2], [1]], np.int64)
    # every step-2 winner in row 0 descends from beam 1; row 1 from 0
    step2_parents = np.array([1, 1, 0, 0], np.int64)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        i1 = layers.data('i1', [4, 1], 'int64', append_batch_size=False)
        i2 = layers.data('i2', [4, 1], 'int64', append_batch_size=False)
        p2 = layers.data('p2', [4], 'int64', append_batch_size=False)
        s1 = layers.data('s1', [4, 1], 'float32',
                         append_batch_size=False)
        seqs, fs = layers.beam_search_decode(
            [i1, i2], [None, p2], beam_size=b, end_id=1,
            scores=[s1, s1])
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        sv, fsv = exe.run(main, feed={'i1': step1_ids,
                                      'i2': step2_ids,
                                      'p2': step2_parents,
                                      's1': np.full((4, 1), -0.5,
                                                    np.float32)},
                          fetch_list=[seqs, fs])
    sv = np.asarray(sv)
    assert np.asarray(fsv).shape == (2, 2)
    assert sv.shape == (2, 2, 2)
    np.testing.assert_array_equal(sv[0], [[8, 3], [8, 4]])
    np.testing.assert_array_equal(sv[1], [[5, 2], [5, 1]])


def test_dynamic_decode_output_time_major():
    V, D, H, B = 7, 4, 6, 2
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        enc = layers.data('enc', [B, H], 'float32',
                          append_batch_size=False)
        cell = layers.GRUCell(hidden_size=H, name='tm_cell')

        def emb(ids):
            return layers.reshape(layers.embedding(
                ids, size=[V, D],
                param_attr=pt.ParamAttr(name='tm_emb')), [-1, D])

        bsd = layers.BeamSearchDecoder(
            cell, start_token=0, end_token=1, beam_size=2,
            embedding_fn=emb,
            output_fn=lambda h: layers.fc(
                h, size=V, param_attr=pt.ParamAttr(name='tm_fc')))
        ids_tm, _ = layers.dynamic_decode(bsd, inits=enc, max_step_num=3,
                                          output_time_major=True)
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        iv, = exe.run(main, feed={'enc': np.zeros((B, H), np.float32)},
                      fetch_list=[ids_tm])
    assert np.asarray(iv).shape == (3, B, 2)   # (T, batch, beam)
