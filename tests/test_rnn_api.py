"""Cell-based RNN API (ref python/paddle/fluid/layers/rnn.py):
RNNCell/GRUCell/LSTMCell + rnn()/lstm()/dynamic_lstmp()."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.framework.scope import Scope, scope_guard


def run(build, feed, fetches_fn, steps=1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        fetches = build()
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        for _ in range(steps):
            outs = exe.run(main, feed=feed, fetch_list=list(fetches))
    return [np.asarray(o) for o in outs]


def test_gru_cell_rnn_masking_and_finals():
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 6, 3).astype(np.float32)
    lens = np.array([6, 4], np.int64)

    def build():
        x = layers.data('x', [2, 6, 3], 'float32',
                        append_batch_size=False)
        l = layers.data('l', [2], 'int64', append_batch_size=False)
        cell = layers.GRUCell(hidden_size=4)
        out, final = layers.rnn(cell, x, sequence_length=l)
        return out, final

    o, f = run(build, {'x': xv, 'l': lens}, None)
    assert o.shape == (2, 6, 4) and f.shape == (2, 4)
    assert np.all(o[1, 4:] == 0)                 # padded steps zeroed
    np.testing.assert_allclose(f[1], o[1, 3], rtol=1e-5)  # last valid
    np.testing.assert_allclose(f[0], o[0, 5], rtol=1e-5)


def test_gru_cell_matches_manual_recurrence():
    """rnn(GRUCell) against a numpy replay of the same parameters."""
    rng = np.random.RandomState(1)
    xv = rng.randn(2, 4, 3).astype(np.float32)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data('x', [2, 4, 3], 'float32',
                        append_batch_size=False)
        cell = layers.GRUCell(hidden_size=5, name="oracle_gru")
        out, final = layers.rnn(cell, x)
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        o, = exe.run(main, feed={'x': xv}, fetch_list=[out])
        params = {n: np.asarray(scope.find_var(n))
                  for n in scope.keys() if n.startswith("oracle_gru")}
    o = np.asarray(o)
    gw = next(v for k, v in params.items() if k.endswith("_gate_w"))
    gb = next(v for k, v in params.items() if k.endswith("_gate_b"))
    cw = next(v for k, v in params.items() if k.endswith("_cand_w"))
    cb = next(v for k, v in params.items() if k.endswith("_cand_b"))
    h = np.zeros((2, 5), np.float32)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    for t in range(4):
        gates = sig(np.concatenate([xv[:, t], h], -1) @ gw + gb)
        u, r = gates[:, :5], gates[:, 5:]
        cand = np.tanh(np.concatenate([xv[:, t], r * h], -1) @ cw + cb)
        h = u * h + (1 - u) * cand
        np.testing.assert_allclose(o[:, t], h, rtol=1e-4, atol=1e-5)


def test_lstm_cell_rnn_and_reverse():
    rng = np.random.RandomState(2)
    xv = rng.randn(2, 5, 3).astype(np.float32)

    def build():
        x = layers.data('x', [2, 5, 3], 'float32',
                        append_batch_size=False)
        cell = layers.LSTMCell(hidden_size=4)
        out, (fh, fc) = layers.rnn(cell, x)
        rcell = layers.LSTMCell(hidden_size=4)
        rout, _ = layers.rnn(rcell, x, is_reverse=True)
        tm_out, _ = layers.rnn(layers.GRUCell(hidden_size=4),
                               layers.transpose(x, perm=[1, 0, 2]),
                               time_major=True)
        return out, fh, fc, rout, tm_out

    o, fh, fc, ro, tmo = run(build, {'x': xv}, None)
    assert o.shape == (2, 5, 4)
    assert fh.shape == (2, 4) and fc.shape == (2, 4)
    np.testing.assert_allclose(fh, o[:, -1], rtol=1e-5)
    assert ro.shape == (2, 5, 4)
    assert tmo.shape == (5, 2, 4)  # time-major in, time-major out


def test_rnn_trains():
    rng = np.random.RandomState(3)
    xv = rng.randn(4, 6, 3).astype(np.float32)
    yv = (xv.sum(axis=(1, 2), keepdims=False) > 0).astype(
        np.int64).reshape(-1, 1)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data('x', [4, 6, 3], 'float32',
                        append_batch_size=False)
        y = layers.data('y', [4, 1], 'int64', append_batch_size=False)
        out, final = layers.rnn(layers.GRUCell(hidden_size=8), x)
        logits = layers.fc(final, size=2)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, y))
        optimizer.Adam(1e-2).minimize(loss)
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        vals = [float(np.asarray(exe.run(main, feed={'x': xv, 'y': yv},
                                         fetch_list=[loss])[0])
                      .reshape(-1)[0]) for _ in range(30)]
    assert vals[-1] < vals[0] * 0.5


def test_lstm_wrapper_and_lstmp():
    rng = np.random.RandomState(4)
    xv = rng.randn(2, 6, 3).astype(np.float32)

    def build():
        x = layers.data('x', [2, 6, 3], 'float32',
                        append_batch_size=False)
        rout, lh, lc = layers.lstm(x, None, None, max_len=6,
                                   hidden_size=4, num_layers=2,
                                   is_bidirec=True)
        proj = layers.fc(x, size=16, num_flatten_dims=2,
                         bias_attr=False)
        p_out, c_out = layers.dynamic_lstmp(proj, size=16, proj_size=3)
        return rout, lh, lc, p_out, c_out

    rout, lh, lc, p_out, c_out = run(build, {'x': xv}, None)
    assert rout.shape == (2, 6, 8)          # bi => 2*hidden
    assert lh.shape == (4, 2, 4)            # layers*dirs, B, H
    assert p_out.shape == (2, 6, 3)         # projected
    assert c_out.shape == (2, 6, 4)         # cell stays hidden-sized
    assert np.isfinite(p_out).all() and np.isfinite(rout).all()


def test_grad_through_nondiff_shape_ref():
    """Regression (backward.py): a differentiable var feeding a
    declared-nondiff slot (fill_constant_batch_size_like's Input) must
    not register a dangling grad contribution."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data('x', [3, 4], 'float32', append_batch_size=False)
        h = layers.fc(x, size=4)
        zeros = layers.fill_constant_batch_size_like(
            h, shape=[-1, 4], dtype='float32', value=0.0)
        out = layers.elementwise_add(h, zeros)
        loss = layers.reduce_sum(layers.square(out))
        gx, = pt.gradients(loss, [x])
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        g, = exe.run(main, feed={'x': np.ones((3, 4), np.float32)},
                     fetch_list=[gx])
    assert np.isfinite(np.asarray(g)).all()
