"""Router HA tier battery (ISSUE-11): N concurrent FleetRouters with
leader-based admission, client failover + idempotent replay, replica
autoscaling over dynamic group resize — in-process units plus chaos
over REAL ``tools/servingsvc.py`` processes:

  * double-failure: SIGKILL the admission-leader router AND one
    replica in the same window under multi-client load — zero failed
    requests, the surviving router inherits admission (term bumped),
    the killed replica re-admits after restart;
  * acceptance headline: 2 routers + 3 replicas as real processes
    under 4-thread client load; leader SIGKILL costs zero requests,
    the restarted router rejoins as FOLLOWER (sticky incumbency), and
    a queue-depth surge drives one ``fleet_autoscale`` grow that adds
    a serving replica through the coordinator's ``resize`` op.
"""
import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import resilience
from paddle_tpu.framework.coordination import CoordinationError
from paddle_tpu.framework.transport import CoordServer
from paddle_tpu.serving_fleet import (Autoscaler, FleetClient,
                                      FleetRouter, ReplicaMember,
                                      http_json)

pytestmark = [pytest.mark.faultinject, pytest.mark.fleet]

WAIT_S = 25.0
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(ROOT, "tools", "servingsvc.py")


@pytest.fixture(autouse=True)
def _clean_resilience():
    resilience.install(None)
    resilience.clear_events()
    resilience.clear_router()
    yield
    resilience.install(None)
    resilience.clear_events()
    resilience.clear_router()


def _export_artifact(dirname, features=6, classes=3,
                     batch_sizes=(1, 8)):
    from paddle_tpu.framework.scope import Scope, scope_guard
    with scope_guard(Scope()):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [features], dtype="float32")
            y = layers.softmax(layers.fc(x, classes))
        exe = pt.Executor()
        exe.run(startup)
        pt.save_inference_model(str(dirname), ["x"], [y], exe,
                                main_program=main, format="stablehlo",
                                batch_sizes=batch_sizes)
    return str(dirname)


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    return _export_artifact(tmp_path_factory.mktemp("ha_artifact"))


def _wait(cond, what, timeout_s=WAIT_S):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError("timed out waiting for %s" % what)


def _ha_fleet(stack, artifact, n_replicas=1, n_routers=2,
              hb_deadline_s=1.0, router_kw=None):
    """In-process HA fleet: n replicas + R routers, fast cadences,
    torn down by the ExitStack."""
    srv = CoordServer(n_replicas + n_routers,
                      hb_deadline_s=hb_deadline_s).start()
    stack.callback(srv.close)
    reps = []
    for i in range(n_replicas):
        rep = ReplicaMember(artifact, srv.address, n_replicas, i,
                            n_routers=n_routers, ctl_interval_s=0.05,
                            hb_interval_s=0.1,
                            join_timeout_s=WAIT_S).start()
        stack.callback(rep.close)
        reps.append(rep)
    rkw = dict(max_batch=8, batch_deadline_s=0.01, ctl_interval_s=0.05,
               hb_interval_s=0.1, poll_interval_s=0.03,
               join_timeout_s=WAIT_S)
    rkw.update(router_kw or {})
    routers = []
    for rid in range(n_routers):
        r = FleetRouter(srv.address, n_replicas, router_id=rid,
                        n_routers=n_routers, **rkw).start()
        stack.callback(r.close)
        routers.append(r)
    for r in routers:
        _wait(lambda r=r: len(r.routable()) == n_replicas,
              "router %d routable" % r.router_id)
    return srv, reps, routers


def _sever(router):
    """Abrupt in-process kill: listener + coordinator client down, no
    graceful queue drain — the closest a thread can come to SIGKILL."""
    router._stop.set()
    router._server.shutdown()
    router._server.server_close()
    router._co.close()


# ---------------------------------------------------------------------------
# in-process units
# ---------------------------------------------------------------------------

def test_lowest_live_router_id_is_the_admission_leader(artifact):
    with contextlib.ExitStack() as stack:
        _, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                  n_routers=2)
        _wait(lambda: routers[0].is_leader(), "router 0 leads")
        assert not routers[1].is_leader()
        assert routers[0].leader_term >= 1
        h = routers[0].health()
        assert h["leader"] and h["router_id"] == 0
        assert h["n_routers"] == 2


def test_leader_failover_bumps_term_and_restart_rejoins_as_follower(
        artifact):
    """Kill the leader: the survivor takes over with a HIGHER term
    (the stale ex-leader's claim is fenced); the restarted router
    re-admits through announce/admit/join and stays a FOLLOWER
    (sticky incumbency), its term gauge converging with the leader's."""
    with contextlib.ExitStack() as stack:
        srv, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                    n_routers=2)
        _wait(lambda: routers[0].is_leader(), "router 0 leads")
        t0 = routers[0].leader_term
        _sever(routers[0])
        _wait(lambda: routers[1].is_leader(), "router 1 takes over",
              timeout_s=WAIT_S)
        assert routers[1].leader_term > t0     # takeover fences claims
        kinds = [e for e in resilience.events("fleet_leader_elect")
                 if e.get("router") == routers[1]._host_id]
        assert kinds, "takeover did not record an election event"
        # restart = a fresh object with the same router_id; it finds
        # itself fenced, rejoins, and DOES NOT reclaim the lease
        r0b = FleetRouter(srv.address, 1, router_id=0, n_routers=2,
                          max_batch=8, batch_deadline_s=0.01,
                          ctl_interval_s=0.05, hb_interval_s=0.1,
                          poll_interval_s=0.03,
                          join_timeout_s=WAIT_S).start()
        stack.callback(r0b.close)
        _wait(lambda: len(r0b.routable()) == 1, "restarted routable")
        time.sleep(0.3)                        # a few leadership polls
        assert routers[1].is_leader()
        assert not r0b.is_leader()
        _wait(lambda: r0b.leader_term == routers[1].leader_term,
              "terms converge")
        # the serving path never broke: both routers answer /infer
        xv = np.ones((1, 6), np.float32).tolist()
        for r in (routers[1], r0b):
            status, resp = http_json("POST", r.url + "/infer",
                                     {"feeds": {"x": xv}},
                                     timeout_s=15.0)
            assert status == 200, resp


def test_router_metrics_are_per_router_series(artifact):
    """Satellite: N concurrent routers in one process must not
    overwrite each other's gauges — every router_* series carries a
    ``router=`` label and the per-router snapshots stay distinct."""
    with contextlib.ExitStack() as stack:
        _, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                  n_routers=2)
        xv = np.ones((2, 6), np.float32).tolist()
        for r in routers:
            for _ in range(3):
                status, _ = http_json("POST", r.url + "/infer",
                                      {"feeds": {"x": xv}},
                                      timeout_s=15.0)
                assert status == 200
        by = resilience.router_totals(by_router=True)
        keys = {k for k in by if k is not None}
        assert {str(r._host_id) for r in routers} <= keys
        for r in routers:
            assert by[str(r._host_id)]["requests"].get("ok") == 3
        # the aggregate (legacy single-router shape) still adds up
        assert resilience.router_totals()["requests"]["ok"] == 6
        gauges = resilience.metrics()["gauges"]
        qd_labels = [g["labels"] for g in gauges
                     if g["name"].endswith("_router_queue_depth")]
        routers_seen = {lbl.get("router") for lbl in qd_labels}
        assert {str(r._host_id) for r in routers} <= routers_seen
        # and the text exposition round-trips the label
        assert 'router="' in resilience.metrics_text()


def test_probe_strict_flags_router_term_disagreement():
    """Satellite: ``serving_probe --strict`` fails on DISAGREEING
    per-router ``fleet_leader_term`` gauges (a router pinned below the
    admission-leader term), mirroring the transport term check; the
    ``fleet_*`` gauges fold under the scrape's "router" group."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import serving_probe
    finally:
        sys.path.pop(0)
    resilience.record_event("fleet_leader_term", router=3, term=2)
    resilience.record_event("fleet_leader_term", router=4, term=2)
    resilience.record_event("fleet_autoscale", action="grow", target=4)
    with resilience.serve_metrics(port=0) as server:
        got = serving_probe.scrape_metrics(server.url)
    assert got["router"]["fleet_leader_term/router3"] == 2.0
    assert got["router"]["fleet_leader_term/router4"] == 2.0
    assert got["router"]["fleet_target_replicas"] == 4.0
    assert serving_probe.term_regression_flags(got) == []
    # one router pinned below the group's admission term: flagged
    resilience.record_event("fleet_leader_term", router=4, term=1)
    with resilience.serve_metrics(port=0) as server:
        got = serving_probe.scrape_metrics(server.url)
    flags = serving_probe.term_regression_flags(got)
    assert flags and "fleet_leader_term" in flags[0]


def test_submit_token_replay_is_idempotent(artifact):
    """A replayed token rides the original request instead of
    enqueueing a duplicate: same result, one replica execution,
    outcome counted as ``replay``."""
    with contextlib.ExitStack() as stack:
        _, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                  n_routers=1)
        router = routers[0]
        xv = np.random.RandomState(3).rand(2, 6)
        body = {"feeds": {"x": xv.tolist()}, "token": "tok-1"}
        status1, r1 = http_json("POST", router.url + "/infer", body,
                                timeout_s=15.0)
        status2, r2 = http_json("POST", router.url + "/infer", body,
                                timeout_s=15.0)
        assert status1 == status2 == 200
        assert r1["outputs"] == r2["outputs"]
        tot = resilience.router_totals(by_router=True)[
            str(router._host_id)]
        assert tot["requests"].get("ok") == 1
        assert tot["requests"].get("replay") == 1


def test_fleet_client_rotates_past_dead_endpoints(artifact):
    with contextlib.ExitStack() as stack:
        _, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                  n_routers=1)
        client = FleetClient(["127.0.0.1:9", routers[0].url],
                             request_deadline_s=15.0, backoff_s=0.01)
        xv = np.ones((1, 6), np.float32).tolist()
        out = client.infer({"x": xv})
        assert out["replica"] == 0
        # malformed requests are NOT retried: deterministic 400
        with pytest.raises(ValueError):
            client.infer({"nope": xv})


def test_autoscaler_grows_on_shed_surge_and_shrinks_when_idle(
        artifact):
    """The full in-process autoscale loop: a shed surge grows the
    group one slot (dynamic resize + spawner, the new replica joins
    through announce/admit/join and serves), a sustained idle window
    drains the grown replica and resizes it away again — with
    ``fleet_autoscale`` events and the ``fleet_target_replicas``
    gauge on both edges."""
    with contextlib.ExitStack() as stack:
        srv, _, routers = _ha_fleet(
            stack, artifact, n_replicas=1, n_routers=1,
            router_kw=dict(max_queue=4, max_batch=1,
                           batch_deadline_s=0.001))
        router = routers[0]
        _wait(lambda: router.is_leader(), "leader")
        grown = []

        def spawner(new_id, new_group):
            rep = ReplicaMember(artifact, srv.address, 1, new_id,
                                n_routers=1, group_size=new_group,
                                ctl_interval_s=0.05, hb_interval_s=0.1,
                                join_timeout_s=WAIT_S).start()
            stack.callback(rep.close)
            grown.append(rep)

        stopped = []
        auto = Autoscaler(router, spawner=spawner,
                          stopper=stopped.append, min_replicas=1,
                          max_replicas=2, interval_s=0.03, window=8,
                          grow_queue_depth=3.0, grow_shed_rate=0.05,
                          hysteresis=2, cooldown_s=0.5,
                          drain_timeout_s=WAIT_S).start()
        stack.callback(auto.close)
        # SUSTAINED shed surge (hysteresis deliberately ignores a
        # sub-interval blip): looping senders keep the 4-deep queue
        # full and the shed counter climbing across samples
        xv = np.ones((1, 6), np.float32).tolist()
        surge_stop = threading.Event()

        def pound():
            while not surge_stop.is_set():
                try:
                    http_json("POST", router.url + "/infer",
                              {"feeds": {"x": xv}}, timeout_s=15.0)
                except (OSError, ValueError):
                    pass

        ts = [threading.Thread(target=pound, daemon=True)
              for _ in range(12)]
        for t in ts:
            t.start()
        try:
            _wait(lambda: any(
                e.get("action") == "grow"
                for e in resilience.events("fleet_autoscale")),
                "autoscale grow", timeout_s=WAIT_S)
        finally:
            surge_stop.set()
            for t in ts:
                t.join(timeout=5)
        grow, = [e for e in resilience.events("fleet_autoscale")
                 if e.get("action") == "grow"]
        assert grow["member"] == 2 and grow["group"] == 3
        # the event lands when the resize commits; the spawner then
        # runs on the autoscaler thread and blocks through the join
        # handshake — wait for it rather than racing it
        _wait(lambda: grown, "spawner invoked")
        # idle: the window drains, the grown slot is drained + resized
        # away, the stopper reaps it. (The shrink implies the whole
        # grow path worked: resize → join — the drain REQUIRES the
        # grown replica in rotation before it may leave.)
        _wait(lambda: any(e.get("action") == "shrink"
                          for e in resilience.events("fleet_autoscale")),
              "autoscale shrink", timeout_s=WAIT_S)
        _wait(lambda: srv.state.n_hosts == 2, "group resized back to 2")
        _wait(lambda: len(router.routable()) == 1,
              "drained replica out of rotation")
        assert stopped == [2]
        assert any(e.get("member") == 2
                   for e in resilience.events("fleet_drained"))
        assert any(e.get("joined") == 2
                   for e in resilience.events("fleet_admit")), \
            "the grown replica never joined"
        # base tier intact and serving after the round trip
        status, _ = http_json("POST", router.url + "/infer",
                              {"feeds": {"x": xv}}, timeout_s=15.0)
        assert status == 200
        # the decisions land in the metrics contract too
        gauges = resilience.metrics()["gauges"]
        targets = [g for g in gauges
                   if g["name"].endswith("_fleet_target_replicas")]
        assert targets and targets[-1]["value"] == 1


# ---------------------------------------------------------------------------
# chaos over real servingsvc processes
# ---------------------------------------------------------------------------

def _svc_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in (env.get("PYTHONPATH"), ROOT) if p])
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _spawn_replica_proc(artifact, coord, n, rid, n_routers,
                        group_size=None, max_in_flight=None,
                        faults=None):
    cmd = [sys.executable, TOOL, "replica", "--coord", coord,
           "--n-replicas", str(n), "--replica-id", str(rid),
           "--n-routers", str(n_routers), "--artifact", artifact,
           "--ctl-interval-s", "0.05", "--hb-interval-s", "0.1",
           "--join-timeout-s", "30"]
    if group_size is not None:
        cmd += ["--group-size", str(group_size)]
    if max_in_flight is not None:
        cmd += ["--max-in-flight", str(max_in_flight)]
    env = _svc_env()
    if faults is not None:
        # env-driven fault injection (resilience.current_injector):
        # how a REAL subprocess replica gets e.g. a slowed serve
        env["PADDLE_TPU_FAULTS"] = faults
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env=env)


def _spawn_router_proc(coord, n, rid, n_routers, extra=()):
    cmd = [sys.executable, TOOL, "router", "--coord", coord,
           "--n-replicas", str(n), "--router-id", str(rid),
           "--n-routers", str(n_routers),
           "--ctl-interval-s", "0.05", "--hb-interval-s", "0.1",
           "--join-timeout-s", "30"] + list(extra)
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env=_svc_env())


class _Lines(object):
    """Background stdout reader so a chatty child never blocks on a
    full pipe and the test can poll for announced lines."""

    def __init__(self, proc):
        self._lines = []
        self._lock = threading.Lock()
        t = threading.Thread(target=self._drain, args=(proc,),
                             daemon=True)
        t.start()

    def _drain(self, proc):
        for ln in proc.stdout:
            with self._lock:
                self._lines.append(ln)

    def first_json(self):
        _wait(lambda: len(self.all()) > 0, "child announced itself")
        return json.loads(self.all()[0])

    def all(self):
        with self._lock:
            return list(self._lines)

    def find(self, frag):
        return [ln for ln in self.all() if frag in ln]


def _healthz(url):
    try:
        status, h = http_json("GET", url + "/healthz", timeout_s=2.0)
    except (OSError, ValueError):
        return None
    return h if status == 200 else None


def _leader_health(url):
    h = _healthz(url)
    return h if (h and h.get("leader")) else None


def _find_leader(urls):
    """Which router id currently claims the admission lease (None
    when no live claim yet)."""
    for r, u in urls.items():
        if _leader_health(u) is not None:
            return r
    return None


def _reap(procs):
    for p in procs:
        if p is not None and p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        if p is not None and p.poll() is None:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()


def test_servingsvc_client_mode_round_trip(artifact):
    """`servingsvc.py client`: stdin/stdout failover client over a
    router endpoint LIST — rotates past a dead endpoint, answers one
    JSON line per request, reports a malformed request as ok=False
    instead of dying."""
    with contextlib.ExitStack() as stack:
        _, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                  n_routers=2)
        proc = subprocess.Popen(
            [sys.executable, TOOL, "client", "--routers",
             ",".join(["127.0.0.1:9", routers[0].url,
                       routers[1].url]),
             "--deadline-s", "15"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=_svc_env())
        xv = np.ones((1, 6), np.float32).tolist()
        try:
            out, _ = proc.communicate(
                json.dumps({"feeds": {"x": xv}}) + "\n"
                + json.dumps({"feeds": {"nope": xv}}) + "\n",
                timeout=90)
        finally:
            if proc.poll() is None:
                proc.kill()
        lines = [json.loads(ln) for ln in out.splitlines()
                 if ln.strip()]
        assert lines[0]["ok"] is True and lines[0]["outputs"]
        assert lines[1]["ok"] is False
        assert lines[1]["kind"] == "ValueError"
        assert proc.returncode == 0


def test_chaos_double_failure_leader_router_and_replica(artifact):
    """Satellite chaos: SIGKILL the admission-leader router AND one
    replica in the same window under multi-client load. Zero failed
    requests (client failover + idempotent replay + sibling retry),
    the surviving router inherits admission with a bumped term, and
    the killed replica re-admits after restart — proving the new
    leader really can enact admissions."""
    srv = CoordServer(4, hb_deadline_s=1.0).start()
    procs = {}
    try:
        for r in range(2):
            procs["rep%d" % r] = _spawn_replica_proc(
                artifact, srv.address, 2, r, 2)
        reps = {r: _Lines(procs["rep%d" % r]) for r in range(2)}
        for r in range(2):
            assert reps[r].first_json()["replica_id"] == r
        for r in range(2):
            procs["rt%d" % r] = _spawn_router_proc(
                srv.address, 2, r, 2)
        routers = {r: _Lines(procs["rt%d" % r]) for r in range(2)}
        urls = {r: routers[r].first_json()["url"] for r in range(2)}
        _wait(lambda: all(
            len((_healthz(urls[r]) or {}).get("replicas", {})) == 2
            for r in range(2)), "both routers route 2 replicas")
        # whichever router claimed the admission lease first keeps it
        # (sticky incumbency — usually the lowest id, but a boot race
        # can elect the other): the chaos targets THE LEADER
        _wait(lambda: _find_leader(urls) is not None,
              "a leader emerges")
        lead = _find_leader(urls)
        surv = 1 - lead
        term0 = _leader_health(urls[lead])["leader_term"]

        client = FleetClient([urls[0], urls[1]],
                             request_deadline_s=20.0, backoff_s=0.02)
        xv = np.ones((2, 6), np.float32).tolist()
        stop, failures, served = threading.Event(), [], []
        lock = threading.Lock()

        def load():
            while not stop.is_set():
                t = time.monotonic()
                try:
                    resp = client.infer({"x": xv})
                except Exception as e:   # noqa: BLE001 - recorded
                    with lock:
                        failures.append(repr(e))
                else:
                    with lock:
                        served.append((t, resp["replica"]))
                time.sleep(0.004)

        loaders = [threading.Thread(target=load, daemon=True)
                   for _ in range(4)]
        for t in loaders:
            t.start()
        time.sleep(0.5)
        # the double failure, same window
        os.kill(procs["rt%d" % lead].pid, signal.SIGKILL)
        os.kill(procs["rep1"].pid, signal.SIGKILL)
        procs["rt%d" % lead].wait(timeout=10)
        procs["rep1"].wait(timeout=10)
        _wait(lambda: _leader_health(urls[surv]) is not None,
              "survivor inherits admission", timeout_s=10.0)
        assert _leader_health(urls[surv])["leader_term"] > term0
        time.sleep(0.5)          # sustained load on the survivors
        # restart the replica: re-admission needs the NEW leader
        procs["rep1b"] = _spawn_replica_proc(
            artifact, srv.address, 2, 1, 2)
        rep1b = _Lines(procs["rep1b"])
        assert rep1b.first_json()["replica_id"] == 1
        _wait(lambda: "1" in (_healthz(urls[surv]) or {}).get(
            "replicas", {}), "killed replica re-admitted")
        t_readmit = time.monotonic()
        time.sleep(0.7)          # traffic reaches the rejoined replica
        stop.set()
        for t in loaders:
            t.join(timeout=5)
        assert not failures, failures[:5]
        assert len(served) > 100
        assert any(rid == 1 for ts, rid in served if ts > t_readmit), \
            "re-admitted replica took no traffic"
    finally:
        _reap(list(procs.values()))
        srv.close()


def test_chaos_acceptance_router_ha_with_autoscale(artifact, tmp_path):
    """THE ISSUE-11 acceptance headline: 2 routers + 3 replicas as
    real servingsvc processes under sustained 4-thread client load.
    SIGKILL the admission-leader router → zero failed requests, the
    survivor leads within the heartbeat deadline, the restarted router
    rejoins as FOLLOWER, and a queue-depth surge drives one
    ``fleet_autoscale`` grow that adds a serving replica via dynamic
    resize (the spawned process announced by the leader, admitted
    through announce/admit/join, visible in the routing table)."""
    srv = CoordServer(5, hb_deadline_s=1.0).start()
    procs = {}
    template = (
        "%s %s replica --coord {coord} --n-replicas 3 --n-routers 2 "
        "--replica-id {replica_id} --group-size {group_size} "
        "--artifact %s --max-in-flight 1 --ctl-interval-s 0.05 "
        "--hb-interval-s 0.1 --join-timeout-s 30"
        % (sys.executable, TOOL, artifact))
    # the base replicas run an env-injected 30ms serve (the
    # subprocess twin of the PR 8 in-process "serve:slow" batteries),
    # putting honest fleet capacity well below the surge demand: the
    # router queue fills, dispatch passes find every replica at
    # max-in-flight shedding, and the terminal sheds — which
    # FleetClient retries, keeping the CLIENT failure count at zero —
    # trip the leader's queue-depth/shed-rate windows. The grown
    # replica inherits the ROUTER's clean env (no injected slowness),
    # so the grow visibly drains the backlog it was asked to fix
    auto_args = ["--autoscale", "--spawn-template", template,
                 "--autoscale-max", "4", "--autoscale-interval-s",
                 "0.05", "--autoscale-window", "8",
                 "--autoscale-queue-depth", "6",
                 "--autoscale-shed-rate", "0.05",
                 "--autoscale-hysteresis", "2",
                 "--autoscale-cooldown-s", "30",
                 "--max-batch", "4", "--batch-deadline-s", "0.02"]
    try:
        for r in range(3):
            procs["rep%d" % r] = _spawn_replica_proc(
                artifact, srv.address, 3, r, 2, max_in_flight=1,
                faults="serve:slow=0.03~1.0")
        reps = {r: _Lines(procs["rep%d" % r]) for r in range(3)}
        for r in range(3):
            assert reps[r].first_json()["replica_id"] == r
        for r in range(2):
            procs["rt%d" % r] = _spawn_router_proc(
                srv.address, 3, r, 2, extra=auto_args)
        routers = {r: _Lines(procs["rt%d" % r]) for r in range(2)}
        urls = {r: routers[r].first_json()["url"] for r in range(2)}
        _wait(lambda: all(
            len((_healthz(urls[r]) or {}).get("replicas", {})) == 3
            for r in range(2)), "both routers route 3 replicas")
        # sticky incumbency: target whichever router holds the lease
        _wait(lambda: _find_leader(urls) is not None,
              "a leader emerges")
        lead = _find_leader(urls)
        surv = 1 - lead

        # 60s deadline: the engineered overload window must cost the
        # foreground load LATENCY (shed → backoff → retry), never a
        # deadline-spent failure
        client = FleetClient([urls[0], urls[1]],
                             request_deadline_s=60.0, backoff_s=0.02)
        xv = np.ones((2, 6), np.float32).tolist()
        stop, failures, served = threading.Event(), [], []
        lock = threading.Lock()

        def load():
            while not stop.is_set():
                try:
                    resp = client.infer({"x": xv})
                except Exception as e:   # noqa: BLE001 - recorded
                    with lock:
                        failures.append(repr(e))
                else:
                    with lock:
                        served.append(resp["replica"])
                time.sleep(0.004)

        loaders = [threading.Thread(target=load, daemon=True)
                   for _ in range(4)]
        for t in loaders:
            t.start()
        time.sleep(0.5)
        # leader SIGKILL: zero failed requests, survivor leads within
        # the heartbeat deadline (+ lease/poll slack)
        t_kill = time.monotonic()
        os.kill(procs["rt%d" % lead].pid, signal.SIGKILL)
        procs["rt%d" % lead].wait(timeout=10)
        _wait(lambda: _leader_health(urls[surv]) is not None,
              "survivor becomes leader", timeout_s=15.0)
        takeover_s = time.monotonic() - t_kill
        assert takeover_s < 15.0, takeover_s
        term1 = _leader_health(urls[surv])["leader_term"]
        # restarted router rejoins as FOLLOWER with the agreed term
        procs["rt%db" % lead] = _spawn_router_proc(
            srv.address, 3, lead, 2, extra=auto_args)
        rt_back = _Lines(procs["rt%db" % lead])
        url_back = rt_back.first_json()["url"]
        _wait(lambda: len((_healthz(url_back) or {}).get(
            "replicas", {})) >= 3, "restarted router routable")
        h_back = _healthz(url_back)
        assert not h_back["leader"]
        assert _leader_health(urls[surv])["leader_term"] == term1
        assert h_back["leader_term"] == term1  # term gauges agree
        client.urls.append(url_back)
        # load surge: SIGKILL one replica (capacity drops to 2 slots
        # at max-in-flight 1 — its in-flight work retries on siblings,
        # still zero failures) and pound the LEADER with 24 senders;
        # dispatch passes find every slot busy, the terminal sheds
        # climb, and the leader's autoscaler grows the fleet
        os.kill(procs["rep2"].pid, signal.SIGKILL)
        procs["rep2"].wait(timeout=10)
        surge_client = FleetClient([urls[surv]],
                                   request_deadline_s=20.0)
        surge_stop = threading.Event()

        def pound():
            while not surge_stop.is_set():
                try:
                    surge_client.infer({"x": xv})
                except Exception:   # noqa: BLE001 - best-effort surge
                    pass

        burst = [threading.Thread(target=pound, daemon=True)
                 for _ in range(24)]
        for t in burst:
            t.start()
        try:
            _wait(lambda: srv.state.n_hosts == 6,
                  "dynamic resize grew the group", timeout_s=40.0)
        finally:
            surge_stop.set()
            for t in burst:
                t.join(timeout=5)
        assert routers[surv].find("autoscale_spawn"), \
            "leader did not announce the spawned replica"
        _wait(lambda: "5" in (_healthz(urls[surv]) or {}).get(
            "replicas", {}), "grown replica admitted and routable",
            timeout_s=WAIT_S)
        time.sleep(0.7)          # the grown replica takes traffic
        stop.set()
        for t in loaders:
            t.join(timeout=5)
        assert not failures, failures[:5]
        assert len(served) > 100
    finally:
        _reap(list(procs.values()))
        srv.close()


def test_autoscaler_reclaims_orphaned_grown_slot(artifact):
    """A fenced, unroutable TOP slot — a drain whose follow-up resize
    never landed, or a grown replica that died before joining — would
    wedge ALL future scale-in (only the top id is removable, and a
    fenced slot never becomes live on its own). An idle window
    reclaims it: the group resizes back down and the stopper reaps
    the process, even at the live floor where the ordinary shrink
    path is gated off."""
    with contextlib.ExitStack() as stack:
        srv, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                    n_routers=1)
        router = routers[0]
        _wait(lambda: router.is_leader(), "leader")

        # the orphan: grow the group one slot, spawn NOTHING — the
        # slot stays birth-fenced, exactly like a drained leftover
        def _grow():
            try:
                return router._co.resize(3) == 3
            except CoordinationError:    # control round in flight
                return False
        _wait(_grow, "grow to 3")
        stopped = []
        auto = Autoscaler(router, stopper=stopped.append,
                          min_replicas=1, max_replicas=2,
                          interval_s=0.03, window=4, hysteresis=2,
                          cooldown_s=0.05,
                          drain_timeout_s=WAIT_S).start()
        stack.callback(auto.close)
        _wait(lambda: srv.state.n_hosts == 2, "slot reclaimed")
        # the stopper runs on the autoscaler thread AFTER the resize
        # commits — wait for it rather than racing it
        _wait(lambda: stopped == [2], "stopper reaped the slot")
        rec, = [e for e in resilience.events("fleet_autoscale")
                if e.get("reclaimed")]
        assert rec["action"] == "shrink" and rec["member"] == 2
        # the base tier is untouched and serving
        assert len(router.routable()) == 1


def test_publish_retry_after_swallowed_put(artifact):
    """A put_info swallowed during a coordinator hiccup must be
    retried on the next poll: the publish signature is cached only
    once the put LANDS, so sibling routers never sit on a stale
    leader claim / in-flight map until the state happens to change
    again."""
    with contextlib.ExitStack() as stack:
        srv, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                    n_routers=1)
        router = routers[0]
        _wait(lambda: router.is_leader(), "leader")
        orig = router._co.put_info
        state = {"failed": 0}

        def flaky(info):
            if not state["failed"]:
                state["failed"] = 1
                raise CoordinationError("injected: failover window")
            return orig(info)

        router._co.put_info = flaky
        try:
            with router._members_lock:
                router._inflight[0] = 7   # changes the signature
            _wait(lambda: srv.state.info.get(router._host_id, {})
                  .get("inflight") == {"0": 7},
                  "swallowed publish retried")
            assert state["failed"] == 1   # the injected failure fired
        finally:
            router._co.put_info = orig
            with router._members_lock:
                router._inflight[0] = 0


def test_restarted_base_member_adopts_grown_group_size(artifact):
    """A base member restarted AFTER an autoscale grow re-runs its
    original command line, which froze the BASE group size — it must
    adopt the server's current (post-resize) size at preflight and
    rejoin, not be refused with the RESIZED mismatch error forever."""
    with contextlib.ExitStack() as stack:
        srv, reps, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                       n_routers=1)
        router = routers[0]
        _wait(lambda: router.is_leader(), "leader")

        def _grow():
            try:
                return router._co.resize(3) == 3
            except CoordinationError:    # control round in flight
                return False
        _wait(_grow, "grow to 3")
        reps[0].close()
        # the restart carries the BOOT-TIME layout (group_size=None
        # derives 1 replica + 1 router = 2) against the server's 3
        rep2 = ReplicaMember(artifact, srv.address, 1, 0,
                             n_routers=1, ctl_interval_s=0.05,
                             hb_interval_s=0.1,
                             join_timeout_s=WAIT_S).start()
        stack.callback(rep2.close)
        assert rep2.group_size == 3
        adopt, = [e for e in
                  resilience.events("fleet_adopt_group_size")
                  if e.get("member") == 0]
        assert adopt["configured"] == 2 and adopt["adopted"] == 3
        _wait(lambda: 0 in router.routable(), "replica back in rotation")
        xv = np.ones((1, 6), np.float32).tolist()
        status, resp = http_json("POST", router.url + "/infer",
                                 {"feeds": {"x": xv}}, timeout_s=15.0)
        assert status == 200


def test_grow_ceiling_counts_allocated_slots(artifact):
    """max_replicas is enforced against ALLOCATED slots, not just
    live replicas: a grown slot whose replica died before joining
    must still count, or sustained pressure over a broken spawner
    grows the group one phantom slot per cooldown without bound."""
    with contextlib.ExitStack() as stack:
        srv, _, routers = _ha_fleet(stack, artifact, n_replicas=1,
                                    n_routers=1)
        router = routers[0]
        _wait(lambda: router.is_leader(), "leader")

        def _grow():
            try:
                return router._co.resize(3) == 3
            except CoordinationError:
                return False
        _wait(_grow, "grow to 3")     # slot 2: fenced, never joins
        spawned = []
        auto = Autoscaler(router, spawner=lambda *a: spawned.append(a),
                          min_replicas=1, max_replicas=2)
        auto._grow(n_live=1)          # n_live < max_replicas, but the
        assert srv.state.n_hosts == 3  # slot ceiling refuses the grow
        assert not spawned
        defer, = [e for e in
                  resilience.events("fleet_autoscale_deferred")
                  if e.get("error") == "replica_slot_ceiling"]
        assert defer["action"] == "grow" and defer["group"] == 3


def test_leader_autoscaler_sees_follower_overload(artifact):
    """Clients pin one endpoint, so overload routinely lands on a
    FOLLOWER router — the leader's autoscaler must read the sibling's
    queue/shed from its info blob (process-local counters are
    invisible across a real multi-process tier) and still grow."""
    with contextlib.ExitStack() as stack:
        srv, _, routers = _ha_fleet(
            stack, artifact, n_replicas=1, n_routers=2,
            router_kw=dict(max_queue=2, max_batch=1,
                           batch_deadline_s=0.001))
        leader, follower = routers
        _wait(lambda: leader.is_leader(), "leader")
        assert not follower.is_leader()
        auto = Autoscaler(leader, min_replicas=1, max_replicas=2,
                          interval_s=0.03, window=8,
                          grow_queue_depth=3.0, grow_shed_rate=0.05,
                          hysteresis=2, cooldown_s=5.0).start()
        stack.callback(auto.close)
        # pound ONLY the follower: the leader's own queue/shed stay 0
        xv = np.ones((1, 6), np.float32).tolist()
        surge_stop = threading.Event()

        def pound():
            while not surge_stop.is_set():
                try:
                    http_json("POST", follower.url + "/infer",
                              {"feeds": {"x": xv}}, timeout_s=15.0)
                except (OSError, ValueError):
                    pass

        ts = [threading.Thread(target=pound, daemon=True)
              for _ in range(8)]
        for t in ts:
            t.start()
        try:
            _wait(lambda: any(
                e.get("action") == "grow"
                for e in resilience.events("fleet_autoscale")),
                "grow from follower-side overload", timeout_s=WAIT_S)
        finally:
            surge_stop.set()
            for t in ts:
                t.join(timeout=5)


def test_template_spawner_stop_reaps_grown_process():
    """The servingsvc autoscale wiring's stopper: a drained,
    resized-away grown replica's PROCESS must be reaped — without it
    every grow/shrink cycle leaks a listener + heartbeat thread."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import servingsvc
    finally:
        sys.path.pop(0)
    tmpl = ("%s -c \"import time; time.sleep({group_size}0)\""
            % sys.executable)
    spawn = servingsvc._template_spawner(tmpl, "127.0.0.1:0")
    p = spawn(2, 3)
    try:
        assert p.poll() is None
        spawn.stop(2)
        assert p.poll() is not None
        # idempotent: a second stop (or an unknown id) is a no-op
        spawn.stop(2)
        spawn.stop(99)
    finally:
        if p.poll() is None:
            p.kill()
