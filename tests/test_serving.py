"""StableHLO serving artifact: export -> load -> predict parity
(reference capability: C++ PaddlePredictor, paddle_api.h:148)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build_and_train():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6], dtype="float32")
        h = layers.fc(x, 8, act="relu")
        y = layers.softmax(layers.fc(h, 3))
    exe = pt.Executor()
    exe.run(startup)
    return main, exe, y


def test_stablehlo_export_roundtrip_matches_predictor(tmp_path):
    """export -> load_serving_artifact -> run must match BOTH the live
    Executor and the in-process Predictor bit-for-bit-ish (VERDICT r4
    next #6 'done' criterion)."""
    main, exe, y = _build_and_train()
    xv = np.random.RandomState(0).rand(5, 6).astype(np.float32)
    ref, = exe.run(main, feed={"x": xv}, fetch_list=[y])

    pt.save_inference_model(str(tmp_path), ["x"], [y], exe,
                            main_program=main, format="stablehlo",
                            batch_sizes=(1, 8))
    # artifact files exist: serialized export + MLIR text per bucket
    sdir = os.path.join(str(tmp_path), "serving")
    meta = json.load(open(os.path.join(sdir, "meta.json")))
    assert meta["dynamic_batch"] is True
    for b in (1, 8):
        assert os.path.exists(os.path.join(sdir, "export_b%d.bin" % b))
        mlir = open(os.path.join(sdir, "module_b%d.mlir" % b)).read()
        assert "stablehlo" in mlir or "func.func" in mlir

    from paddle_tpu.serving import load_serving_artifact
    pred = load_serving_artifact(str(tmp_path))
    assert pred.get_input_names() == ["x"]
    out, = pred.run({"x": xv})          # batch 5 -> bucket 8, sliced back
    assert out.shape == (5, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # parity with the in-process Predictor path on the same artifact dir
    from paddle_tpu.inference import Config, create_predictor
    inproc = create_predictor(Config(str(tmp_path)))
    out2, = inproc.run({"x": xv})
    np.testing.assert_allclose(out, out2, rtol=1e-5, atol=1e-6)

    # batch larger than every exported bucket: named error
    with pytest.raises(ValueError, match="largest exported bucket"):
        pred.run({"x": np.zeros((9, 6), np.float32)})


def test_stablehlo_export_weights_are_frozen(tmp_path):
    """The artifact must bake the weights at export time: training the
    live model afterwards must NOT change the artifact's predictions."""
    from paddle_tpu import optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        y = layers.fc(x, 2)
        test_prog = main.clone(for_test=True)
        lbl = layers.data("lbl", [2], dtype="float32")
        loss = layers.reduce_mean(layers.square_error_cost(y, lbl))
        optimizer.SGD(0.5).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    xv = np.random.RandomState(1).rand(2, 4).astype(np.float32)

    pt.save_inference_model(str(tmp_path), ["x"], [y], exe,
                            main_program=test_prog, format="stablehlo",
                            batch_sizes=(2,))
    from paddle_tpu.serving import load_serving_artifact
    pred = load_serving_artifact(str(tmp_path))
    before, = pred.run({"x": xv})
    ref, = exe.run(test_prog, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before, ref, rtol=1e-5, atol=1e-6)

    for _ in range(3):
        exe.run(main, feed={"x": xv,
                            "lbl": np.ones((2, 2), np.float32)},
                fetch_list=[loss])
    after_live, = exe.run(test_prog, feed={"x": xv}, fetch_list=[y])
    assert not np.allclose(after_live, ref)     # live model moved
    again, = pred.run({"x": xv})
    np.testing.assert_allclose(again, before)   # artifact frozen


def test_stablehlo_export_batch_factor_feeds(tmp_path):
    """Feeds whose leading dim is a MULTIPLE of the batch (BERT's flat
    mask_pos = batch * max_preds) export and reload correctly when an
    example_feed teaches the factors."""
    from paddle_tpu.models import bert
    from paddle_tpu.framework.scope import Scope, scope_guard

    cfg = bert.BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                          num_heads=2, ff_size=64, max_position=32)
    batch, seq, preds = 4, 16, 4
    main, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch, seq, preds, optimizer_fn=None, is_test=True)
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.synthetic_batch(cfg, batch, seq, preds)
        ref, = exe.run(main, feed=feed, fetch_list=[fetch["loss"]])
        pt.save_inference_model(str(tmp_path), list(feed.keys()),
                                [fetch["loss"]], exe, main_program=main,
                                format="stablehlo", batch_sizes=(batch,),
                                example_feed=feed)
    from paddle_tpu.serving import load_serving_artifact
    pred = load_serving_artifact(str(tmp_path))
    meta = pred._meta
    factors = dict(zip(meta["feed_var_names"], meta["feed_batch_factor"]))
    assert factors["mask_pos"] == preds         # batch*preds leading dim
    assert factors["src_ids"] == 1
    out, = pred.run({k: np.asarray(v) for k, v in feed.items()})
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# quantized serving artifacts (ISSUE 16 satellite, ROADMAP 3c): the
# EQuARX-grounded q8 block codec from the checkpoint/state-ship path
# reused for the serving export — weights ride BESIDE the .bin as
# block-quantized int8 and are dequantized once at load
# ---------------------------------------------------------------------------

def test_q8_export_shrinks_and_roundtrips(tmp_path):
    """weight_compress='q8': the .bin holds no baked weights (the
    artifact shrinks ~4x on weight-dominated exports), the predictor
    dequantizes at load, and predictions match the full-precision
    export within the codec's block-quantization tolerance."""
    main, exe, y = _build_and_train()
    xv = np.random.RandomState(0).rand(5, 6).astype(np.float32)

    fp = str(tmp_path / "fp32")
    q8 = str(tmp_path / "q8")
    pt.save_inference_model(fp, ["x"], [y], exe, main_program=main,
                            format="stablehlo", batch_sizes=(8,))
    pt.save_inference_model(q8, ["x"], [y], exe, main_program=main,
                            format="stablehlo", batch_sizes=(8,),
                            weight_compress="q8")

    from paddle_tpu.serving import (SERVING_FORMAT_VERSION,
                                    WEIGHTS_Q8_FILE,
                                    load_serving_artifact)
    meta = json.load(open(os.path.join(q8, "serving", "meta.json")))
    assert meta["format_version"] == SERVING_FORMAT_VERSION == 3
    assert meta["weight_compress"] == "q8"
    assert sorted(meta["weight_names"])
    assert os.path.exists(os.path.join(q8, "serving", WEIGHTS_Q8_FILE))
    # the bins carry the computation only; the weights moved into the
    # int8 npz — the EXPORT pair proves the ship-bytes shrink
    bin_fp = os.path.getsize(os.path.join(fp, "serving",
                                          "export_b8.bin"))
    bin_q8 = os.path.getsize(os.path.join(q8, "serving",
                                          "export_b8.bin"))
    assert bin_q8 < bin_fp

    ref_pred = load_serving_artifact(fp)
    q8_pred = load_serving_artifact(q8)
    assert ref_pred.weight_compress is None
    assert q8_pred.weight_compress == "q8"
    ref, = ref_pred.run({"x": xv})
    out, = q8_pred.run({"x": xv})
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2)


def test_q8_artifact_wire_bytes_shrink(tmp_path):
    """The state-ship accounting a q8 replica reports: the artifact's
    (raw, wire) byte pair — what _load_predictor feeds the stateship
    counters — must SHRINK vs the full-precision export of the same
    model, not just be assumed to.  Uses a weight-dominated model:
    the codec only block-quantizes arrays past its block size, and
    the fixed MLIR/meta overhead must not mask the weight savings."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [64], dtype="float32")
        h = layers.fc(x, 256, act="relu")
        y = layers.softmax(layers.fc(h, 8))
    exe = pt.Executor()
    exe.run(startup)
    fp = str(tmp_path / "fp32")
    q8 = str(tmp_path / "q8")
    pt.save_inference_model(fp, ["x"], [y], exe, main_program=main,
                            format="stablehlo", batch_sizes=(8,))
    pt.save_inference_model(q8, ["x"], [y], exe, main_program=main,
                            format="stablehlo", batch_sizes=(8,),
                            weight_compress="q8")
    from paddle_tpu.serving_fleet import _artifact_wire_bytes
    raw_fp, wire_fp = _artifact_wire_bytes(fp)
    raw_q8, wire_q8 = _artifact_wire_bytes(q8)
    assert raw_q8 < raw_fp
    assert wire_q8 < wire_fp


def test_q8_format_fences(tmp_path):
    """The lossy export is fenced both ways: an unknown compression
    scheme is refused at export AND at load (a v3 artifact from a
    newer codec must never be served as garbage), while a PLAIN
    export stays format_version 2 — old loaders keep working."""
    main, exe, y = _build_and_train()
    plain = str(tmp_path / "plain")
    pt.save_inference_model(plain, ["x"], [y], exe, main_program=main,
                            format="stablehlo", batch_sizes=(8,))
    meta = json.load(open(os.path.join(plain, "serving", "meta.json")))
    assert meta["format_version"] == 2
    assert "weight_compress" not in meta

    with pytest.raises(ValueError, match="weight_compress"):
        pt.save_inference_model(str(tmp_path / "bad"), ["x"], [y],
                                exe, main_program=main,
                                format="stablehlo", batch_sizes=(8,),
                                weight_compress="zstd")

    q8 = str(tmp_path / "q8")
    pt.save_inference_model(q8, ["x"], [y], exe, main_program=main,
                            format="stablehlo", batch_sizes=(8,),
                            weight_compress="q8")
    mpath = os.path.join(q8, "serving", "meta.json")
    meta = json.load(open(mpath))
    meta["weight_compress"] = "zstd9"
    with open(mpath, "w") as f:
        json.dump(meta, f)
    from paddle_tpu.serving import load_serving_artifact
    with pytest.raises(ValueError, match="weight_compress"):
        load_serving_artifact(q8)


def test_q8_artifact_still_verified_at_load(tmp_path, monkeypatch):
    """progcheck at load survives the codec: a q8 artifact shipping a
    CORRUPT program IR refuses to load exactly like a full-precision
    one — compression must not open a verification bypass."""
    main, exe, y = _build_and_train()
    q8 = str(tmp_path / "q8")
    pt.save_inference_model(q8, ["x"], [y], exe, main_program=main,
                            format="stablehlo", batch_sizes=(8,),
                            weight_compress="q8")
    model_path = os.path.join(q8, "__model__.json")
    assert os.path.exists(model_path)
    meta = json.load(open(model_path))
    # first op loses its type: the verifier's strict walk must refuse
    meta["program"]["blocks"][0]["ops"][0].pop("type", None)
    with open(model_path, "w") as f:
        json.dump(meta, f)
    from paddle_tpu.serving import load_serving_artifact
    with pytest.raises(ValueError):
        load_serving_artifact(q8)
