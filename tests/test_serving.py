"""StableHLO serving artifact: export -> load -> predict parity
(reference capability: C++ PaddlePredictor, paddle_api.h:148)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build_and_train():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6], dtype="float32")
        h = layers.fc(x, 8, act="relu")
        y = layers.softmax(layers.fc(h, 3))
    exe = pt.Executor()
    exe.run(startup)
    return main, exe, y


def test_stablehlo_export_roundtrip_matches_predictor(tmp_path):
    """export -> load_serving_artifact -> run must match BOTH the live
    Executor and the in-process Predictor bit-for-bit-ish (VERDICT r4
    next #6 'done' criterion)."""
    main, exe, y = _build_and_train()
    xv = np.random.RandomState(0).rand(5, 6).astype(np.float32)
    ref, = exe.run(main, feed={"x": xv}, fetch_list=[y])

    pt.save_inference_model(str(tmp_path), ["x"], [y], exe,
                            main_program=main, format="stablehlo",
                            batch_sizes=(1, 8))
    # artifact files exist: serialized export + MLIR text per bucket
    sdir = os.path.join(str(tmp_path), "serving")
    meta = json.load(open(os.path.join(sdir, "meta.json")))
    assert meta["dynamic_batch"] is True
    for b in (1, 8):
        assert os.path.exists(os.path.join(sdir, "export_b%d.bin" % b))
        mlir = open(os.path.join(sdir, "module_b%d.mlir" % b)).read()
        assert "stablehlo" in mlir or "func.func" in mlir

    from paddle_tpu.serving import load_serving_artifact
    pred = load_serving_artifact(str(tmp_path))
    assert pred.get_input_names() == ["x"]
    out, = pred.run({"x": xv})          # batch 5 -> bucket 8, sliced back
    assert out.shape == (5, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # parity with the in-process Predictor path on the same artifact dir
    from paddle_tpu.inference import Config, create_predictor
    inproc = create_predictor(Config(str(tmp_path)))
    out2, = inproc.run({"x": xv})
    np.testing.assert_allclose(out, out2, rtol=1e-5, atol=1e-6)

    # batch larger than every exported bucket: named error
    with pytest.raises(ValueError, match="largest exported bucket"):
        pred.run({"x": np.zeros((9, 6), np.float32)})


def test_stablehlo_export_weights_are_frozen(tmp_path):
    """The artifact must bake the weights at export time: training the
    live model afterwards must NOT change the artifact's predictions."""
    from paddle_tpu import optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        y = layers.fc(x, 2)
        test_prog = main.clone(for_test=True)
        lbl = layers.data("lbl", [2], dtype="float32")
        loss = layers.reduce_mean(layers.square_error_cost(y, lbl))
        optimizer.SGD(0.5).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    xv = np.random.RandomState(1).rand(2, 4).astype(np.float32)

    pt.save_inference_model(str(tmp_path), ["x"], [y], exe,
                            main_program=test_prog, format="stablehlo",
                            batch_sizes=(2,))
    from paddle_tpu.serving import load_serving_artifact
    pred = load_serving_artifact(str(tmp_path))
    before, = pred.run({"x": xv})
    ref, = exe.run(test_prog, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before, ref, rtol=1e-5, atol=1e-6)

    for _ in range(3):
        exe.run(main, feed={"x": xv,
                            "lbl": np.ones((2, 2), np.float32)},
                fetch_list=[loss])
    after_live, = exe.run(test_prog, feed={"x": xv}, fetch_list=[y])
    assert not np.allclose(after_live, ref)     # live model moved
    again, = pred.run({"x": xv})
    np.testing.assert_allclose(again, before)   # artifact frozen


def test_stablehlo_export_batch_factor_feeds(tmp_path):
    """Feeds whose leading dim is a MULTIPLE of the batch (BERT's flat
    mask_pos = batch * max_preds) export and reload correctly when an
    example_feed teaches the factors."""
    from paddle_tpu.models import bert
    from paddle_tpu.framework.scope import Scope, scope_guard

    cfg = bert.BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                          num_heads=2, ff_size=64, max_position=32)
    batch, seq, preds = 4, 16, 4
    main, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch, seq, preds, optimizer_fn=None, is_test=True)
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.synthetic_batch(cfg, batch, seq, preds)
        ref, = exe.run(main, feed=feed, fetch_list=[fetch["loss"]])
        pt.save_inference_model(str(tmp_path), list(feed.keys()),
                                [fetch["loss"]], exe, main_program=main,
                                format="stablehlo", batch_sizes=(batch,),
                                example_feed=feed)
    from paddle_tpu.serving import load_serving_artifact
    pred = load_serving_artifact(str(tmp_path))
    meta = pred._meta
    factors = dict(zip(meta["feed_var_names"], meta["feed_batch_factor"]))
    assert factors["mask_pos"] == preds         # batch*preds leading dim
    assert factors["src_ids"] == 1
    out, = pred.run({k: np.asarray(v) for k, v in feed.items()})
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
