"""Predictor API + dygraph optimizer tests."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def test_predictor_bucketing(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        y = layers.softmax(layers.fc(x, 3))
    exe = pt.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).rand(3, 4).astype(np.float32)
    ref, = exe.run(main, feed={"x": xv}, fetch_list=[y])

    pt.save_inference_model(str(tmp_path), ["x"], [y], exe,
                            main_program=main)
    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(str(tmp_path)))
    assert pred.get_input_names() == ["x"]
    out, = pred.run({"x": xv})           # batch 3 -> bucket 4, sliced back
    assert out.shape == (3, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    out2, = pred.run({"x": xv[:1]})      # bucket 1
    np.testing.assert_allclose(out2, ref[:1], rtol=1e-5)


def test_dygraph_adam_converges():
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph import Linear, to_variable
    from paddle_tpu.dygraph.optimizers import Adam
    from paddle_tpu.dygraph.nn import run_op
    rng = np.random.RandomState(0)
    x = rng.rand(64, 4).astype(np.float32)
    w_true = rng.randn(4, 1).astype(np.float32)
    t = x @ w_true

    with dygraph.guard():
        layer = Linear(4, 1)
        opt = Adam(0.05)
        losses = []
        for _ in range(40):
            def loss_fn(out):
                diff = out - to_variable(t)
                return run_op("reduce_mean",
                              {"X": [run_op("square",
                                            {"X": [diff]})["Out"]]},
                              {"reduce_all": True})["Out"]
            loss, grads = layer.loss_and_grad(loss_fn, x)
            opt.minimize(layer)
            losses.append(float(loss.numpy()))
    assert losses[-1] < 0.05 * losses[0], losses[::8]


def test_predictor_batch_factor_feeds(tmp_path):
    """The in-process Predictor handles feeds whose leading dim is a
    MULTIPLE of the batch (BERT-style flat mask_pos) — same contract as
    the v2 serving artifact."""
    from paddle_tpu.models import bert
    from paddle_tpu.framework.scope import Scope, scope_guard

    cfg = bert.BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                          num_heads=2, ff_size=64, max_position=32)
    batch, seq, preds = 4, 16, 4
    main, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch, seq, preds, optimizer_fn=None, is_test=True)
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.synthetic_batch(cfg, batch, seq, preds)
        ref, = exe.run(main, feed=feed, fetch_list=[fetch["loss"]])
        pt.save_inference_model(str(tmp_path), list(feed.keys()),
                                [fetch["loss"]], exe, main_program=main)
    from paddle_tpu.inference import Config, create_predictor
    cfg2 = Config(str(tmp_path))
    cfg2.batch_buckets = (batch,)    # exact bucket: parity with ref run
    pred = create_predictor(cfg2)
    out, = pred.run({k: np.asarray(v) for k, v in feed.items()})
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
