"""Detection long-tail op tests (numpy oracles, OpTest-style).

Mirrors reference tests/unittests/test_{anchor_generator,bipartite_match,
target_assign,multiclass_nms,roi_align,roi_pool,yolov3_loss,...}_op.py.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.ops.registry import get_op


class _Ctx:
    program = None

    def rng(self):
        return jax.random.PRNGKey(0)


def _run(op, ins, attrs=None):
    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in ins.items()}
    return get_op(op).fn(_Ctx(), ins, attrs or {})


# ---------------------------------------------------------------- anchors

def test_anchor_generator_matches_reference_loop():
    feat = np.zeros((1, 8, 2, 3), np.float32)
    sizes, ratios, stride, offset = [32., 64.], [0.5, 1.0], [16., 16.], 0.5
    out = _run("anchor_generator", {"Input": [feat]},
               {"anchor_sizes": sizes, "aspect_ratios": ratios,
                "stride": stride, "offset": offset})
    anchors = np.asarray(out["Anchors"])
    assert anchors.shape == (2, 3, 4, 4)
    # oracle: direct transcription of the documented semantics
    import math
    ref = np.zeros_like(anchors)
    for hi in range(2):
        for wi in range(3):
            xc = wi * stride[0] + offset * (stride[0] - 1)
            yc = hi * stride[1] + offset * (stride[1] - 1)
            idx = 0
            for ar in ratios:
                for s in sizes:
                    bw = round(math.sqrt(stride[0] * stride[1] / ar))
                    bh = round(bw * ar)
                    aw = s / stride[0] * bw
                    ah = s / stride[1] * bh
                    ref[hi, wi, idx] = [xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                                        xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1)]
                    idx += 1
    np.testing.assert_allclose(anchors, ref, rtol=1e-5)


def test_density_prior_box_shapes_and_range():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 64, 64), np.float32)
    out = _run("density_prior_box", {"Input": [feat], "Image": [img]},
               {"fixed_sizes": [16.0], "fixed_ratios": [1.0, 2.0],
                "densities": [2]})
    boxes = np.asarray(out["Boxes"])
    assert boxes.shape == (4, 4, 2 * 4, 4)
    assert (boxes >= 0).all() and (boxes <= 1).all()
    assert (boxes[..., 2] >= boxes[..., 0]).all()


# ------------------------------------------------------------- matching

def _np_bipartite(dist, match_type="bipartite", th=0.5):
    r, c = dist.shape
    match = np.full((c,), -1, np.int32)
    mdist = np.zeros((c,), np.float32)
    rows = set(range(r))
    while rows:
        best = (-1, -1, -1.0)
        for i in rows:
            for j in range(c):
                if match[j] == -1 and dist[i, j] > 1e-6 and \
                        dist[i, j] > best[2]:
                    best = (i, j, dist[i, j])
        if best[0] < 0:
            break
        match[best[1]] = best[0]
        mdist[best[1]] = best[2]
        rows.remove(best[0])
    if match_type == "per_prediction":
        for j in range(c):
            if match[j] == -1:
                i = int(np.argmax(dist[:, j]))
                if dist[i, j] > th:
                    match[j] = i
                    mdist[j] = dist[i, j]
    return match, mdist


@pytest.mark.parametrize("match_type", ["bipartite", "per_prediction"])
def test_bipartite_match_matches_numpy(match_type):
    rng = np.random.RandomState(0)
    dist = rng.rand(4, 7).astype(np.float32)
    out = _run("bipartite_match", {"DistMat": [dist]},
               {"match_type": match_type, "dist_threshold": 0.5})
    m = np.asarray(out["ColToRowMatchIndices"])[0]
    d = np.asarray(out["ColToRowMatchDist"])[0]
    rm, rd = _np_bipartite(dist, match_type)
    np.testing.assert_array_equal(m, rm)
    np.testing.assert_allclose(d, rd, rtol=1e-5)


def test_target_assign():
    x = np.arange(24, dtype=np.float32).reshape(1, 6, 4)
    match = np.array([[2, -1, 0, 5]], np.int32)
    out = _run("target_assign", {"X": [x], "MatchIndices": [match]},
               {"mismatch_value": 9.0})
    o = np.asarray(out["Out"])
    w = np.asarray(out["OutWeight"])
    np.testing.assert_allclose(o[0, 0], x[0, 2])
    np.testing.assert_allclose(o[0, 1], [9.0] * 4)
    np.testing.assert_allclose(o[0, 3], x[0, 5])
    np.testing.assert_allclose(w[0, :, 0], [1, 0, 1, 1])


# ------------------------------------------------------------------ nms

def _np_nms(boxes, scores, iou_th):
    order = np.argsort(-scores)
    keep, alive = [], np.ones(len(boxes), bool)
    for i in order:
        if not alive[i]:
            continue
        keep.append(i)
        for j in order:
            if alive[j] and j != i and scores[j] <= scores[i]:
                xx1 = max(boxes[i, 0], boxes[j, 0])
                yy1 = max(boxes[i, 1], boxes[j, 1])
                xx2 = min(boxes[i, 2], boxes[j, 2])
                yy2 = min(boxes[i, 3], boxes[j, 3])
                inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
                a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
                a2 = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
                if inter / (a1 + a2 - inter + 1e-10) > iou_th:
                    alive[j] = False
    return sorted(keep)


def test_multiclass_nms_against_numpy():
    rng = np.random.RandomState(1)
    m, c = 12, 3
    boxes = np.sort(rng.rand(m, 4).astype(np.float32) * 10, axis=-1)[:, [0, 1, 2, 3]]
    boxes = np.stack([boxes[:, 0], boxes[:, 1],
                      boxes[:, 0] + boxes[:, 2] + 1,
                      boxes[:, 1] + boxes[:, 3] + 1], -1)
    scores = rng.rand(c, m).astype(np.float32)
    out = _run("multiclass_nms", {"BBoxes": [boxes[None]],
                                  "Scores": [scores[None]]},
               {"score_threshold": 0.3, "nms_threshold": 0.4,
                "keep_top_k": 20, "background_label": 0})
    res = np.asarray(out["Out"])[0]
    got = {(int(r[0]), round(float(r[1]), 5)) for r in res if r[1] > 0}
    want = set()
    for cls in range(1, c):   # class 0 = background, excluded
        keep = _np_nms(boxes, scores[cls], 0.4)
        for i in keep:
            if scores[cls, i] > 0.3:
                want.add((cls, round(float(scores[cls, i]), 5)))
    assert got == want


def test_multiclass_nms_top_k_smaller_than_keep():
    """keep_top_k > C*nms_top_k must clamp, not crash (regression)."""
    rng = np.random.RandomState(9)
    boxes = np.sort(rng.rand(1, 50, 4).astype(np.float32) * 10, -1)
    scores = rng.rand(1, 2, 50).astype(np.float32)
    out = _run("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
               {"score_threshold": 0.0, "nms_threshold": 0.4,
                "nms_top_k": 10, "keep_top_k": 40, "background_label": -1})
    res = np.asarray(out["Out"])
    assert res.shape == (1, 20, 6)
    idx = np.asarray(out["Index"])[0]
    # Index points back into the BBoxes rows for every live detection
    for k in range(res.shape[1]):
        if res[0, k, 1] > 0:
            np.testing.assert_allclose(res[0, k, 2:], boxes[0, idx[k]],
                                       rtol=1e-5)


# ------------------------------------------------------------------ rois

def _np_roi_align(x, rois, bidx, ph, pw, scale, sr):
    r = rois.shape[0]
    n, c, h, w = x.shape
    out = np.zeros((r, c, ph, pw), np.float32)

    def bil(img, y, xx):
        y = min(max(y, 0.0), h - 1.0)
        xx = min(max(xx, 0.0), w - 1.0)
        y0, x0 = int(np.floor(y)), int(np.floor(xx))
        y1, x1 = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
        fy, fx = y - y0, xx - x0
        return (img[:, y0, x0] * (1 - fy) * (1 - fx) +
                img[:, y0, x1] * (1 - fy) * fx +
                img[:, y1, x0] * fy * (1 - fx) +
                img[:, y1, x1] * fy * fx)

    for ri in range(r):
        x1, y1, x2, y2 = rois[ri] * scale
        rw = max(x2 - x1, 1.0)
        rh = max(y2 - y1, 1.0)
        bh, bw = rh / ph, rw / pw
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(c, np.float32)
                for iy in range(sr):
                    for ix in range(sr):
                        yy = y1 + (i + (iy + 0.5) / sr) * bh
                        xx = x1 + (j + (ix + 0.5) / sr) * bw
                        acc += bil(x[bidx[ri]], yy, xx)
                out[ri, :, i, j] = acc / (sr * sr)
    return out


def test_roi_align_matches_numpy():
    rng = np.random.RandomState(2)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 7, 7], [2, 2, 6, 5], [1, 0, 3, 3]], np.float32)
    rois_num = np.array([2, 1], np.int32)
    out = _run("roi_align", {"X": [x], "ROIs": [rois],
                             "RoisNum": [rois_num]},
               {"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0,
                "sampling_ratio": 2})
    ref = _np_roi_align(x, rois, [0, 0, 1], 2, 2, 1.0, 2)
    np.testing.assert_allclose(np.asarray(out["Out"]), ref, rtol=1e-4,
                               atol=1e-5)


def test_roi_align_gradient_flows():
    x = jnp.asarray(np.random.RandomState(0).rand(1, 2, 6, 6)
                    .astype(np.float32))
    rois = jnp.asarray(np.array([[1, 1, 4, 4]], np.float32))

    def f(xx):
        return _run("roi_align", {"X": [xx], "ROIs": [rois]},
                    {"pooled_height": 2, "pooled_width": 2})["Out"].sum()

    g = jax.grad(f)(x)
    assert np.isfinite(np.asarray(g)).all() and float(jnp.abs(g).sum()) > 0


def _np_roi_pool(x, rois, bidx, ph, pw, scale):
    r = rois.shape[0]
    n, c, h, w = x.shape
    out = np.zeros((r, c, ph, pw), np.float32)
    for ri in range(r):
        x1 = int(round(rois[ri, 0] * scale))
        y1 = int(round(rois[ri, 1] * scale))
        x2 = int(round(rois[ri, 2] * scale))
        y2 = int(round(rois[ri, 3] * scale))
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        for i in range(ph):
            for j in range(pw):
                hs = y1 + int(np.floor(i * rh / ph))
                he = y1 + int(np.ceil((i + 1) * rh / ph))
                ws = x1 + int(np.floor(j * rw / pw))
                we = x1 + int(np.ceil((j + 1) * rw / pw))
                hs, he = max(hs, 0), min(he, h)
                ws, we = max(ws, 0), min(we, w)
                if he > hs and we > ws:
                    out[ri, :, i, j] = x[bidx[ri], :, hs:he, ws:we].max((1, 2))
    return out


def test_roi_pool_matches_numpy():
    rng = np.random.RandomState(3)
    x = rng.rand(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 7, 7], [1, 2, 5, 6]], np.float32)
    out = _run("roi_pool", {"X": [x], "ROIs": [rois]},
               {"pooled_height": 3, "pooled_width": 3, "spatial_scale": 1.0})
    ref = _np_roi_pool(x, rois, [0, 0], 3, 3, 1.0)
    np.testing.assert_allclose(np.asarray(out["Out"]), ref, rtol=1e-5)


# ---------------------------------------------------------------- losses

def test_sigmoid_focal_loss_matches_reference_formula():
    rng = np.random.RandomState(4)
    x = rng.randn(5, 3).astype(np.float32)
    label = np.array([[1], [0], [3], [-1], [2]], np.int32)
    fg = np.array([4], np.int32)
    gamma, alpha = 2.0, 0.25
    out = np.asarray(_run("sigmoid_focal_loss",
                          {"X": [x], "Label": [label], "FgNum": [fg]},
                          {"gamma": gamma, "alpha": alpha})["Out"])
    ref = np.zeros_like(x)
    for a in range(5):
        for d in range(3):
            g = label[a, 0]
            c_pos = float(g == d + 1)
            c_neg = float((g != -1) and (g != d + 1))
            fgn = max(fg[0], 1)
            p = 1.0 / (1.0 + np.exp(-x[a, d]))
            term_pos = (1 - p) ** gamma * np.log(max(p, 1e-37))
            xx = x[a, d]
            term_neg = p ** gamma * (-xx * (xx >= 0) -
                                     np.log(1 + np.exp(xx - 2 * xx * (xx >= 0))))
            ref[a, d] = -c_pos * term_pos * alpha / fgn \
                - c_neg * term_neg * (1 - alpha) / fgn
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)


def test_yolov3_loss_finite_and_grads():
    rng = np.random.RandomState(5)
    n, mask, cnum, h = 2, 3, 4, 4
    x = rng.randn(n, mask * (5 + cnum), h, h).astype(np.float32) * 0.1
    gt_box = np.array([[[0.3, 0.3, 0.2, 0.2], [0.7, 0.6, 0.4, 0.3],
                        [0, 0, 0, 0]],
                       [[0.5, 0.5, 0.5, 0.5], [0, 0, 0, 0],
                        [0, 0, 0, 0]]], np.float32)
    gt_label = np.array([[1, 2, 0], [3, 0, 0]], np.int32)
    attrs = {"anchors": [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119],
             "anchor_mask": [0, 1, 2], "class_num": cnum,
             "ignore_thresh": 0.7, "downsample_ratio": 32}
    out = _run("yolov3_loss", {"X": [x], "GTBox": [gt_box],
                               "GTLabel": [gt_label]}, attrs)
    loss = np.asarray(out["Loss"])
    assert loss.shape == (n,) and np.isfinite(loss).all() and (loss > 0).all()
    match = np.asarray(out["GTMatchMask"])
    assert match.shape == (n, 3)
    assert (match[gt_box[..., 2] <= 1e-6] == -1).all()

    def f(xx):
        return _run("yolov3_loss", {"X": [xx], "GTBox": [gt_box],
                                    "GTLabel": [gt_label]}, attrs)["Loss"].sum()

    g = np.asarray(jax.grad(f)(jnp.asarray(x)))
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_ssd_loss_positive_and_decreases():
    """A matched prediction trained toward its encoded target drives the
    loss down; padding gts are ignored."""
    rng = np.random.RandomState(6)
    n, p, c, g = 1, 8, 3, 2
    prior = np.stack([np.linspace(0, 0.8, p), np.full(p, 0.1),
                      np.linspace(0.2, 1.0, p), np.full(p, 0.4)],
                     -1).astype(np.float32)
    gt_box = np.array([[[0.0, 0.1, 0.25, 0.4], [0, 0, 0, 0]]], np.float32)
    gt_label = np.array([[1, 0]], np.int32)
    loc = rng.randn(n, p, 4).astype(np.float32) * 0.1
    conf = rng.randn(n, p, c).astype(np.float32) * 0.1
    loss0 = np.asarray(_run(
        "ssd_loss", {"Location": [loc], "Confidence": [conf],
                     "GtBox": [gt_box], "GtLabel": [gt_label],
                     "PriorBox": [prior]}, {})["Loss"])
    assert np.isfinite(loss0).all() and loss0.sum() > 0

    def f(lc, cf):
        return _run("ssd_loss", {"Location": [lc], "Confidence": [cf],
                                 "GtBox": [gt_box], "GtLabel": [gt_label],
                                 "PriorBox": [prior]}, {})["Loss"].sum()

    lj, cj = jnp.asarray(loc), jnp.asarray(conf)
    for _ in range(25):
        gl, gc = jax.grad(f, argnums=(0, 1))(lj, cj)
        lj -= 0.1 * gl
        cj -= 0.1 * gc
    assert float(f(lj, cj)) < float(loss0.sum())


# -------------------------------------------------------------- misc ops

def test_box_clip():
    boxes = np.array([[[-5, -5, 30, 40], [5, 5, 10, 10]]], np.float32)
    im_info = np.array([[20, 25, 1.0]], np.float32)
    out = np.asarray(_run("box_clip", {"Input": [boxes],
                                       "ImInfo": [im_info]}, {})["Output"])
    np.testing.assert_allclose(out[0, 0], [0, 0, 24, 19])
    np.testing.assert_allclose(out[0, 1], [5, 5, 10, 10])


def test_polygon_box_transform():
    x = np.ones((1, 4, 2, 3), np.float32)
    out = np.asarray(_run("polygon_box_transform",
                          {"Input": [x]}, {})["Output"])
    for ci in range(4):
        for hi in range(2):
            for wi in range(3):
                want = 4 * wi - 1 if ci % 2 == 0 else 4 * hi - 1
                assert out[0, ci, hi, wi] == want


def test_generate_proposals_static():
    rng = np.random.RandomState(7)
    n, a, h, w = 1, 3, 4, 4
    scores = rng.rand(n, a, h, w).astype(np.float32)
    deltas = rng.randn(n, a * 4, h, w).astype(np.float32) * 0.1
    im_info = np.array([[64, 64, 1.0]], np.float32)
    anchors = np.abs(rng.rand(h, w, a, 4).astype(np.float32)) * 8
    anchors[..., 2:] += anchors[..., :2] + 8
    var = np.full((h, w, a, 4), 1.0, np.float32)
    out = _run("generate_proposals",
               {"Scores": [scores], "BboxDeltas": [deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [var]},
               {"pre_nms_topN": 20, "post_nms_topN": 10, "nms_thresh": 0.7,
                "min_size": 1.0})
    rois = np.asarray(out["RpnRois"])
    num = int(np.asarray(out["RpnRoisNum"])[0])
    assert rois.shape == (1, 10, 4)
    assert 0 < num <= 10
    live = rois[0, :num]
    assert (live[:, 2] >= live[:, 0]).all() and (live[:, 3] >= live[:, 1]).all()
    assert (live >= 0).all() and (live <= 63).all()


def test_distribute_and_collect_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],      # small -> low level
                     [0, 0, 300, 300],    # large -> high level
                     [0, 0, 60, 60],
                     [0, 0, 150, 150]], np.float32)
    out = _run("distribute_fpn_proposals", {"FpnRois": [rois]},
               {"min_level": 2, "max_level": 5, "refer_level": 4,
                "refer_scale": 224})
    nums = [int(np.asarray(v)[0]) for v in out["MultiLevelRoIsNum"]]
    assert sum(nums) == 4
    restore = np.asarray(out["RestoreIndex"])[:, 0]
    # reference convention (distribute_fpn_proposals_op.h:136):
    # restore[orig] = concat position, so gather(concat, restore) == rois
    concat = []
    for lvl_rois, cnt in zip(out["MultiFpnRois"], nums):
        concat.append(np.asarray(lvl_rois)[:cnt])
    concat = np.concatenate(concat, 0)
    np.testing.assert_allclose(concat[restore], rois)

    scores = [np.linspace(0.1, 0.9, 4).astype(np.float32)[: max(c, 1)]
              for c in nums]
    # collect: use the distributed rois plus fake per-level scores
    multi = [np.asarray(v) for v in out["MultiFpnRois"]]
    msc = [np.pad(s, (0, multi[i].shape[0] - len(s)))
           for i, s in enumerate(scores)]
    nums_in = [np.array([c], np.int32) for c in nums]
    col = _run("collect_fpn_proposals",
               {"MultiLevelRois": multi, "MultiLevelScores": msc,
                "MultiLevelRoisNum": nums_in},
               {"post_nms_topN": 3})
    assert np.asarray(col["FpnRois"]).shape == (3, 4)
    assert int(np.asarray(col["RoisNum"])[0]) == 3


def test_distribute_fpn_ignores_padding_rois():
    """Zero-padded rois past RoisNum must not count toward any level."""
    rois = np.array([[0, 0, 10, 10], [0, 0, 60, 60],
                     [0, 0, 0, 0], [0, 0, 0, 0]], np.float32)
    out = _run("distribute_fpn_proposals",
               {"FpnRois": [rois], "RoisNum": [np.array([2], np.int32)]},
               {"min_level": 2, "max_level": 5, "refer_level": 4,
                "refer_scale": 224})
    nums = [int(np.asarray(v)[0]) for v in out["MultiLevelRoIsNum"]]
    assert sum(nums) == 2
    restore = np.asarray(out["RestoreIndex"])[:, 0]
    concat = np.concatenate(
        [np.asarray(v)[:c] for v, c in zip(out["MultiFpnRois"], nums)], 0)
    np.testing.assert_allclose(concat[restore[:2]], rois[:2])


def test_mine_hard_examples():
    cls_loss = np.array([[5, 4, 3, 2, 1, 0.5]], np.float32)
    match = np.array([[0, -1, -1, -1, -1, -1]], np.int32)
    dist = np.array([[0.9, 0.1, 0.2, 0.1, 0.1, 0.6]], np.float32)
    out = _run("mine_hard_examples",
               {"ClsLoss": [cls_loss], "MatchIndices": [match],
                "MatchDist": [dist]},
               {"neg_pos_ratio": 2.0, "neg_dist_threshold": 0.5})
    neg = np.asarray(out["NegIndices"])[0]
    # 1 positive -> 2 negatives; highest-loss eligible negs are idx 1, 2
    # (idx 5 excluded: dist 0.6 >= 0.5)
    np.testing.assert_array_equal(neg, [0, 1, 1, 0, 0, 0])
    upd = np.asarray(out["UpdatedMatchIndices"])[0]
    assert upd[0] == 0 and upd[1] == -1


def test_box_decoder_and_assign_shapes():
    rng = np.random.RandomState(8)
    m, c = 4, 3
    prior = np.abs(rng.rand(m, 4).astype(np.float32)) * 10
    prior[:, 2:] += prior[:, :2] + 5
    var = np.full((4,), 1.0, np.float32)
    deltas = rng.randn(m, 4 * c).astype(np.float32) * 0.1
    score = rng.rand(m, c).astype(np.float32)
    out = _run("box_decoder_and_assign",
               {"PriorBox": [prior], "PriorBoxVar": [var],
                "TargetBox": [deltas], "BoxScore": [score]},
               {"box_clip": 4.135})
    assert np.asarray(out["DecodeBox"]).shape == (m, 4 * c)
    assert np.asarray(out["OutputAssignBox"]).shape == (m, 4)
    # assigned box equals the decoded box of the argmax class
    dec = np.asarray(out["DecodeBox"]).reshape(m, c, 4)
    best = np.asarray(score).argmax(1)
    np.testing.assert_allclose(np.asarray(out["OutputAssignBox"]),
                               dec[np.arange(m), best], rtol=1e-5)


# ------------------------------------------------------- layer-level API

def test_detection_layers_build_and_run():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        feat = layers.data("feat", (8, 4, 4), "float32")
        img = layers.data("img", (3, 64, 64), "float32")
        box, var = layers.prior_box(feat, img, min_sizes=[16.0],
                                    aspect_ratios=[2.0], flip=True)
        anchors, avar = layers.anchor_generator(
            feat, anchor_sizes=[32.], aspect_ratios=[1.0], stride=[16., 16.])
        x = layers.data("x", (4, 4, 4), "float32")
        poly = layers.polygon_box_transform(x)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    outs = exe.run(main, feed={"feat": rng.rand(1, 8, 4, 4).astype(np.float32),
                               "img": rng.rand(1, 3, 64, 64).astype(np.float32),
                               "x": rng.rand(1, 4, 4, 4).astype(np.float32)},
                   fetch_list=[box, anchors, poly])
    assert outs[0].shape == (4, 4, 3, 4)   # ars [1.0, 2.0, 0.5]
    assert outs[1].shape == (4, 4, 1, 4)
    assert outs[2].shape == (1, 4, 4, 4)


def test_sigmoid_focal_loss_trains():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        feat = layers.data("f", [4], "float32")
        logits = layers.fc(feat, size=3)
        lbl = layers.data("lb", [1], "int32")
        fg = layers.data("fg", (1,), "int32", append_batch_size=False)
        loss = layers.reduce_sum(layers.sigmoid_focal_loss(logits, lbl, fg))
        optimizer.SGD(0.5).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(1)
    feed = {"f": rng.rand(6, 4).astype(np.float32),
            "lb": np.array([[1], [2], [3], [1], [2], [3]], np.int32),
            "fg": np.array([6], np.int32)}
    l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    for _ in range(15):
        l1 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    assert l1 < l0


def test_roi_align_layer_trains():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", (3, 8, 8), "float32")
        conv = layers.conv2d(x, 4, 3, padding=1)
        rois = layers.data("rois", (2, 4), "float32",
                           append_batch_size=False)
        pooled = layers.roi_align(conv, rois, pooled_height=2,
                                  pooled_width=2, spatial_scale=1.0,
                                  sampling_ratio=2)
        loss = layers.reduce_mean(layers.square(pooled))
        optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(2)
    feed = {"x": rng.rand(1, 3, 8, 8).astype(np.float32),
            "rois": np.array([[0, 0, 7, 7], [1, 1, 5, 6]], np.float32)}
    l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    for _ in range(10):
        l1 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    assert l1 < l0


def test_rpn_target_assign_dense():
    """RPN targets (ref rpn_target_assign_op): high-IoU anchors become
    fg, far anchors bg, targets encode the matched gt."""
    anchors = np.array([[0, 0, 10, 10], [0, 0, 9, 11], [50, 50, 60, 60],
                        [100, 100, 120, 120]], np.float32)
    gts = np.zeros((1, 3, 4), np.float32)
    gts[0, 0] = [0, 0, 10, 10]            # matches anchors 0/1
    gts[0, 1] = [101, 101, 119, 121]      # matches anchor 3
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        a = layers.data("a", [4, 4], "float32", append_batch_size=False)
        av = layers.data("av", [4, 4], "float32",
                         append_batch_size=False)
        g = layers.data("g", [1, 3, 4], "float32",
                        append_batch_size=False)
        bp = layers.data("bp", [1, 4, 4], "float32",
                         append_batch_size=False)
        cl = layers.data("cl", [1, 4, 1], "float32",
                         append_batch_size=False)
        sp, lp, labels, tgt, inw = layers.rpn_target_assign(
            bp, cl, a, av, g, use_random=False)
    exe = pt.Executor()
    exe.run(startup)
    lab, t, w = exe.run(main, feed={
        "a": anchors, "av": np.ones_like(anchors), "g": gts,
        "bp": np.zeros((1, 4, 4), np.float32),
        "cl": np.zeros((1, 4, 1), np.float32)},
        fetch_list=[labels, tgt, inw])
    lab = np.asarray(lab)[0]
    t = np.asarray(t)[0]
    w = np.asarray(w)[0]
    assert lab[0] == 1 and lab[3] == 1          # matched anchors fg
    assert lab[2] == 0                          # isolated anchor bg
    assert np.all(w[lab == 1] == 1.0) and np.all(w[lab != 1] == 0.0)
    # anchor 0 == its gt exactly: zero regression target
    np.testing.assert_allclose(t[0], 0.0, atol=1e-5)
    assert np.abs(t[3]).sum() > 0               # anchor 3 offset gt


def test_retinanet_target_assign_classes_and_fg_num():
    anchors = np.array([[0, 0, 10, 10], [40, 40, 50, 50],
                        [200, 200, 210, 210]], np.float32)
    gts = np.zeros((1, 2, 4), np.float32)
    gts[0, 0] = [0, 0, 10, 10]
    gts[0, 1] = [41, 41, 49, 49]
    gl = np.array([[3, 7]], np.int64)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        a = layers.data("a", [3, 4], "float32", append_batch_size=False)
        av = layers.data("av", [3, 4], "float32",
                         append_batch_size=False)
        g = layers.data("g", [1, 2, 4], "float32",
                        append_batch_size=False)
        glv = layers.data("gl", [1, 2], "int64",
                          append_batch_size=False)
        bp = layers.data("bp", [1, 3, 4], "float32",
                         append_batch_size=False)
        cl = layers.data("cl", [1, 3, 1], "float32",
                         append_batch_size=False)
        _, _, labels, tgt, inw, fg = layers.retinanet_target_assign(
            bp, cl, a, av, g, glv)
    exe = pt.Executor()
    exe.run(startup)
    lab, fgn = exe.run(main, feed={
        "a": anchors, "av": np.ones_like(anchors), "g": gts, "gl": gl,
        "bp": np.zeros((1, 3, 4), np.float32),
        "cl": np.zeros((1, 3, 1), np.float32)},
        fetch_list=[labels, fg])
    lab = np.asarray(lab)[0]
    assert lab[0] == 3 and lab[1] == 7          # class-carrying labels
    assert lab[2] == 0                          # background
    assert int(np.asarray(fgn).reshape(-1)[0]) == 2


def test_generate_proposal_labels_dense():
    rois = np.zeros((1, 4, 4), np.float32)
    rois[0] = [[0, 0, 10, 10], [1, 1, 11, 11], [60, 60, 70, 70],
               [200, 200, 230, 230]]
    gts = np.zeros((1, 1, 4), np.float32)
    gts[0, 0] = [0, 0, 10, 10]
    cls = np.array([[5]], np.int64)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        r = layers.data("r", [1, 4, 4], "float32",
                        append_batch_size=False)
        g = layers.data("g", [1, 1, 4], "float32",
                        append_batch_size=False)
        c = layers.data("c", [1, 1], "int64", append_batch_size=False)
        rois_o, labels, tgt, inw, outw = layers.generate_proposal_labels(
            r, c, None, g, batch_size_per_im=4, fg_fraction=0.5)
    exe = pt.Executor()
    exe.run(startup)
    lab, = exe.run(main, feed={"r": rois, "g": gts, "c": cls},
                   fetch_list=[labels])
    lab = np.asarray(lab)[0]
    assert lab[0] == 5 and lab[1] == 5          # fg rois carry gt class
    assert (lab[2] in (0, -1)) and (lab[3] in (0, -1))


def test_locality_aware_nms_merges_neighbors():
    # two heavily-overlapping consecutive boxes merge into one detection
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0, 10.5, 10],
                       [50, 50, 60, 60]]], np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # (1, C=1, 3)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        b = layers.data("b", [1, 3, 4], "float32",
                        append_batch_size=False)
        s = layers.data("s", [1, 1, 3], "float32",
                        append_batch_size=False)
        out = layers.locality_aware_nms(b, s, score_threshold=0.1,
                                        nms_top_k=10, keep_top_k=5,
                                        nms_threshold=0.5)
    exe = pt.Executor()
    exe.run(startup)
    o, = exe.run(main, feed={"b": boxes, "s": scores},
                 fetch_list=[out])
    o = np.asarray(o)[0]
    kept = o[o[:, 1] > 0]
    assert len(kept) == 2                       # merged pair + far box
    # merged box x1 between the two originals, score = pair average
    assert 0.0 < kept[0, 2] < 0.5
    assert abs(kept[0, 1] - 0.85) < 1e-5


def test_retinanet_detection_output():
    anchors = np.array([[0, 0, 10, 10], [20, 20, 34, 34]], np.float32)
    deltas = np.zeros((1, 2, 4), np.float32)       # no offset: boxes = anchors
    scores = np.array([[[0.9, 0.01], [0.02, 0.8]]], np.float32)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        d = layers.data("d", [1, 2, 4], "float32",
                        append_batch_size=False)
        s = layers.data("s", [1, 2, 2], "float32",
                        append_batch_size=False)
        a = layers.data("a", [2, 4], "float32", append_batch_size=False)
        ii = layers.data("ii", [1, 3], "float32",
                         append_batch_size=False)
        out = layers.retinanet_detection_output(
            [d], [s], [a], ii, score_threshold=0.1, keep_top_k=4)
    exe = pt.Executor()
    exe.run(startup)
    o, = exe.run(main, feed={"d": deltas, "s": scores, "a": anchors,
                             "ii": np.array([[64, 64, 1.0]],
                                            np.float32)},
                 fetch_list=[out])
    o = np.asarray(o)[0]
    kept = o[o[:, 1] > 0]
    assert len(kept) == 2
    # class labels are 1-based; best detection is class 1 @ 0.9
    assert kept[0, 0] == 1 and abs(kept[0, 1] - 0.9) < 1e-6
    np.testing.assert_allclose(kept[0, 2:], [0, 0, 10, 10], atol=1e-4)
    assert kept[1, 0] == 2 and abs(kept[1, 1] - 0.8) < 1e-6


def test_roi_perspective_transform_identity_quad():
    """An axis-aligned quad covering a known patch reproduces it."""
    img = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    # quad == the exact 4x4 patch corners (clockwise from top-left)
    rois = np.array([[[1, 1, 4, 1, 4, 4, 1, 4]]], np.float32)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [1, 1, 6, 6], "float32",
                        append_batch_size=False)
        r = layers.data("r", [1, 1, 8], "float32",
                        append_batch_size=False)
        out = layers.roi_perspective_transform(x, r, 4, 4)
    exe = pt.Executor()
    exe.run(startup)
    o, = exe.run(main, feed={"x": img, "r": rois}, fetch_list=[out])
    o = np.asarray(o)[0, 0, 0]
    np.testing.assert_allclose(o, img[0, 0, 1:5, 1:5], atol=1e-3)


def test_generate_mask_labels_dense():
    B, G, S, R, NC, RES = 1, 1, 8, 2, 3, 4
    gt_boxes = np.array([[[0, 0, 8, 8]]], np.float32)
    # gt mask: left half on
    seg = np.zeros((B, G, S, S), np.float32)
    seg[0, 0, :, :4] = 1.0
    rois = np.array([[[0, 0, 8, 8], [100, 100, 110, 110]]], np.float32)
    labels = np.array([[2, 0]], np.int32)     # roi0 fg class 2, roi1 bg
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ii = layers.data("ii", [B, 3], "float32",
                         append_batch_size=False)
        gc = layers.data("gc", [B, G], "int64", append_batch_size=False)
        gs = layers.data("gs", [B, G, S, S], "float32",
                         append_batch_size=False)
        rr = layers.data("rr", [B, R, 4], "float32",
                         append_batch_size=False)
        lb = layers.data("lb", [B, R], "int32", append_batch_size=False)
        gb = layers.data("gb", [B, G, 4], "float32",
                         append_batch_size=False)
        mrois, has, mask = layers.generate_mask_labels(
            ii, gc, None, gs, rr, lb, num_classes=NC, resolution=RES,
            gt_boxes=gb)
    exe = pt.Executor()
    exe.run(startup)
    hv, mv = exe.run(main, feed={
        "ii": np.array([[64, 64, 1.0]], np.float32),
        "gc": np.array([[2]], np.int64), "gs": seg, "rr": rois,
        "lb": labels, "gb": gt_boxes}, fetch_list=[has, mask])
    hv = np.asarray(hv)[0]
    mv = np.asarray(mv)[0].reshape(R, NC, RES, RES)
    assert hv.tolist() == [1, 0]
    # fg roi: class-2 slot has the left-half pattern, others ignored
    assert np.all(mv[0, 2, :, :2] == 1) and np.all(mv[0, 2, :, 2:] == 0)
    assert np.all(mv[0, 1] == -1)
    assert np.all(mv[1] == -1)                # bg roi fully ignored


def test_force_positive_survives_gt_padding():
    """Review regression: a valid gt whose best anchor is index 0 must
    get its forced positive even when padded gt rows also argmax to
    anchor 0 (duplicate-index scatter)."""
    anchors = np.array([[0, 0, 10, 10], [50, 50, 60, 60]], np.float32)
    gts = np.zeros((1, 2, 4), np.float32)
    gts[0, 0] = [12, 0, 22, 10]          # IoU < thresholds, best anchor 0
    gl = np.array([[5, 0]], np.int64)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        a = layers.data("a", [2, 4], "float32", append_batch_size=False)
        av = layers.data("av", [2, 4], "float32",
                         append_batch_size=False)
        g = layers.data("g", [1, 2, 4], "float32",
                        append_batch_size=False)
        glv = layers.data("gl", [1, 2], "int64",
                          append_batch_size=False)
        bp = layers.data("bp", [1, 2, 4], "float32",
                         append_batch_size=False)
        cl = layers.data("cl", [1, 2, 1], "float32",
                         append_batch_size=False)
        _, _, rl, _, _ = layers.rpn_target_assign(
            bp, cl, a, av, g, use_random=False, rpn_straddle_thresh=-1)
        _, _, tl, _, _, _ = layers.retinanet_target_assign(
            bp, cl, a, av, g, glv)
    exe = pt.Executor()
    exe.run(startup)
    feed = {"a": anchors, "av": np.ones_like(anchors), "g": gts,
            "gl": gl, "bp": np.zeros((1, 2, 4), np.float32),
            "cl": np.zeros((1, 2, 1), np.float32)}
    rlv, tlv = exe.run(main, feed=feed, fetch_list=[rl, tl])
    assert np.asarray(rlv)[0, 0] == 1
    assert np.asarray(tlv)[0, 0] == 5


def test_fg_fraction_zero_samples_nothing():
    rois = np.array([[[0, 0, 10, 10], [1, 1, 11, 11]]], np.float32)
    gts = np.array([[[0, 0, 10, 10]]], np.float32)
    cls = np.array([[4]], np.int64)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        r = layers.data("r", [1, 2, 4], "float32",
                        append_batch_size=False)
        g = layers.data("g", [1, 1, 4], "float32",
                        append_batch_size=False)
        c = layers.data("c", [1, 1], "int64", append_batch_size=False)
        _, labels, _, _, _ = layers.generate_proposal_labels(
            r, c, None, g, batch_size_per_im=2, fg_fraction=0.0)
    exe = pt.Executor()
    exe.run(startup)
    lab, = exe.run(main, feed={"r": rois, "g": gts, "c": cls},
                   fetch_list=[labels])
    assert not np.any(np.asarray(lab) > 0)   # no stray fg sample
