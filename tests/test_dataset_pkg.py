"""Tests for the paddle_tpu.dataset corpus package (ref
python/paddle/dataset/tests/*): record schemas, determinism, and the
reader-decorator interop the book chapters rely on."""
import itertools

import numpy as np
import pytest

from paddle_tpu import dataset
from paddle_tpu.reader import decorator


def take(reader, n):
    return list(itertools.islice(reader(), n))


def test_mnist_schema_and_determinism():
    a = take(dataset.mnist.train(), 5)
    b = take(dataset.mnist.train(), 5)
    for (xa, ya), (xb, yb) in zip(a, b):
        assert xa.shape == (784,) and xa.dtype == np.float32
        assert xa.min() >= -1.0 and xa.max() <= 1.0
        assert 0 <= ya < 10
        np.testing.assert_array_equal(xa, xb)
        assert ya == yb


def test_mnist_classes_separable():
    # class-conditional means must differ (the synthetic prototypes)
    by_class = {}
    for x, y in take(dataset.mnist.train(), 400):
        by_class.setdefault(y, []).append(x)
    means = {c: np.mean(v, 0) for c, v in by_class.items() if len(v) > 5}
    cs = list(means)
    gaps = [np.abs(means[c1] - means[c2]).max()
            for c1, c2 in itertools.combinations(cs, 2)]
    assert min(gaps) > 0.1


def test_cifar_schema():
    for x, y in take(dataset.cifar.train10(), 3):
        assert x.shape == (3072,) and 0 <= y < 10
    for x, y in take(dataset.cifar.test100(), 3):
        assert x.shape == (3072,) and 0 <= y < 100


def test_cifar_cycle():
    r = dataset.cifar.train10(cycle=True)()
    n = dataset.cifar.TRAIN_SIZE
    first = next(r)
    for _ in range(min(n, 50) - 1):
        next(r)  # cycle reader keeps yielding past one epoch on small take
    assert first[0].shape == (3072,)


def test_uci_housing_linear_fit():
    xs, ys = zip(*take(dataset.uci_housing.train(), 200))
    X = np.stack(xs)
    y = np.stack(ys)[:, 0]
    w, res, _, _ = np.linalg.lstsq(
        np.concatenate([X, np.ones((len(X), 1))], 1), y, rcond=None)
    pred = np.concatenate([X, np.ones((len(X), 1))], 1) @ w
    # synthetic truth is linear + unit noise: residual std must be ~1
    assert np.std(pred - y) < 2.0


def test_imdb_dict_and_polarity():
    wd = dataset.imdb.word_dict()
    assert '<unk>' in wd
    samples = take(dataset.imdb.train(wd), 50)
    labels = {l for _, l in samples}
    assert labels == {0, 1}
    for ids, _ in samples:
        assert all(0 <= i < len(wd) for i in ids)


def test_imikolov_ngram_and_seq():
    d = dataset.imikolov.build_dict(5)
    ng = take(dataset.imikolov.train(d, 5), 10)
    assert all(len(t) == 5 for t in ng)
    sq = take(dataset.imikolov.train(
        d, 0, dataset.imikolov.DataType.SEQ), 5)
    for src, trg in sq:
        assert len(src) == len(trg)
        assert src[0] == d['<s>'] and trg[-1] == d['<e>']


def test_movielens_meta_and_samples():
    s = next(dataset.movielens.train())
    # [uid, gender, age_bucket, job, mid, [cats], [title], [rating]]
    assert len(s) == 8
    assert isinstance(s[5], list) and isinstance(s[6], list)
    assert 1.0 <= s[7][0] <= 5.0
    assert dataset.movielens.max_user_id() == 600
    assert dataset.movielens.max_movie_id() == 400
    assert len(dataset.movielens.movie_categories()) == 18
    info = dataset.movielens.movie_info()[1]
    assert "MovieInfo" in str(info)


def test_conll05_alignment():
    word_dict, verb_dict, label_dict = dataset.conll05.get_dict()
    emb = dataset.conll05.get_embedding()
    assert emb.shape == (len(word_dict), dataset.conll05.EMB_DIM)
    for s in take(dataset.conll05.test(), 5):
        assert len(s) == 9
        T = len(s[0])
        assert all(len(slot) == T for slot in s)
        assert label_dict['B-V'] in s[8]  # every sample has a predicate


def test_wmt14_teacher_forcing_triplet():
    for src, trg, trg_next in take(dataset.wmt14.train(60), 10):
        assert src[0] == 0 and src[-1] == 1  # <s> ... <e>
        assert trg[0] == 0 and trg_next[-1] == 1
        assert trg[1:] == trg_next[:-1]
        assert max(src) < 60 and max(trg_next) < 60
    sd, td = dataset.wmt14.get_dict(60, reverse=True)
    assert sd[0] == "<s>" and td[1] == "<e>"


def test_wmt16_splits_and_dicts():
    with pytest.raises(ValueError):
        dataset.wmt16.train(50, 50, src_lang="fr")
    tr = take(dataset.wmt16.train(50, 50), 5)
    va = take(dataset.wmt16.validation(50, 50), 5)
    assert tr and va and tr[0] != va[0]
    d = dataset.wmt16.get_dict("de", 50)
    assert d["<unk>"] == 2 and len(d) == 50


def test_mq2007_formats():
    feats, score = next(dataset.mq2007.train(format="pointwise"))
    assert feats.shape == (46,) and score in (0, 1, 2)
    hi, lo = next(dataset.mq2007.train(format="pairwise"))
    assert hi.shape == lo.shape == (46,)
    scores, feats = next(dataset.mq2007.train(format="listwise"))
    assert feats.shape == (len(scores), 46)


def test_mq2007_pairwise_orders_by_truth():
    # hi must outscore lo under the generating linear model
    w = dataset.mq2007.synthetic.rng_for("mq2007", "w").normal(0, 1, 46)
    better = 0
    pairs = list(itertools.islice(
        dataset.mq2007.train(format="pairwise"), 100))
    for hi, lo in pairs:
        better += float(hi @ w > lo @ w)
    assert better / len(pairs) > 0.7


def test_sentiment():
    wd = dataset.sentiment.get_word_dict()
    tr = take(dataset.sentiment.train(), 10)
    assert all(l in (0, 1) for _, l in tr)
    assert all(all(i < len(wd) for i in ids) for ids, _ in tr)


def test_voc2012_masks():
    img, lab = next(dataset.voc2012.train()())
    assert img.dtype == np.uint8 and img.shape[0] == 3
    assert lab.shape == img.shape[1:]
    classes = set(np.unique(lab)) - {255}
    assert classes <= set(range(21))


def test_flowers():
    img, lab = next(dataset.flowers.train(use_xmap=False)())
    assert img.shape == (3 * 64 * 64,) and 0 <= lab < 102
    img2, _ = next(dataset.flowers.valid(use_xmap=False)())
    assert img2.shape == img.shape


def test_image_transforms():
    im = np.random.RandomState(0).randint(
        0, 255, (80, 60, 3)).astype(np.uint8)
    r = dataset.image.resize_short(im, 64)
    assert min(r.shape[:2]) == 64
    c = dataset.image.center_crop(r, 48)
    assert c.shape[:2] == (48, 48)
    f = dataset.image.left_right_flip(c)
    np.testing.assert_array_equal(f[:, ::-1, :], c)
    t = dataset.image.simple_transform(im, 70, 64, False,
                                       mean=[1.0, 2.0, 3.0])
    assert t.shape == (3, 64, 64) and t.dtype == np.float32


def test_reader_decorator_interop():
    wd = dataset.imdb.word_dict()
    batched = decorator.batch(
        decorator.shuffle(dataset.imdb.train(wd), buf_size=64),
        batch_size=8)
    b = next(batched())
    assert len(b) == 8 and isinstance(b[0][0], list)


def test_common_split_and_cluster_reader(tmp_path):
    def reader():
        for i in range(25):
            yield (i, i * i)

    suffix = str(tmp_path / "part-%05d.pickle")
    dataset.common.split(reader, 10, suffix=suffix)
    r0 = dataset.common.cluster_files_reader(
        str(tmp_path / "part-*.pickle"), 2, 0)
    r1 = dataset.common.cluster_files_reader(
        str(tmp_path / "part-*.pickle"), 2, 1)
    got = sorted(list(r0()) + list(r1()))
    assert got == [(i, i * i) for i in range(25)]


def test_common_download_offline(tmp_path, monkeypatch):
    monkeypatch.setattr(dataset.common, "DATA_HOME", str(tmp_path))
    with pytest.raises(RuntimeError, match="no network egress"):
        dataset.common.download("http://x/y.tar", "mod", None)
    d = tmp_path / "mod"
    d.mkdir()
    (d / "y.tar").write_bytes(b"abc")
    assert dataset.common.download("http://x/y.tar", "mod", None) == \
        str(d / "y.tar")


def test_dataset_api_reexports():
    # fluid Dataset API still reachable at the old import path
    from paddle_tpu.dataset import DatasetFactory, InMemoryDataset
    assert DatasetFactory().create_dataset("InMemoryDataset") is not None
