"""Pallas kernel library oracle batteries (interpret mode on CPU).

Every kernel is checked fwd+bwd against its pure-JAX reference — the
same oracle pattern as test_flash_attention — plus:
  * the no-materialization property of the fused MLM head (no
    [tokens, vocab] aval anywhere in the fwd or bwd jaxpr),
  * use_pallas dispatch through the op registry / CompiledProgram
    (loss-curve parity vs the XLA lowering, compile-cache-token
    regression: toggling use_pallas re-lowers),
  * autotune cache round-trip, tuned-config override and the
    XLA-fallback verdict routing, and the tools/autotune.py --dry-run
    CLI smoke (the sweep harness itself can never rot untested).
"""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.framework.compiler import BuildStrategy, CompiledProgram
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.ops import pallas_dispatch as pd
from paddle_tpu.ops.pallas.blockwise_ce import (
    blockwise_softmax_cross_entropy, fused_mlm_head_loss, fit_blocks)
from paddle_tpu.ops.pallas.fused_adam import fused_adam
from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm
from paddle_tpu.ops.pallas import autotune as at

pytestmark = pytest.mark.pallas

ALL_OPS = frozenset(pd.PALLAS_OPS)


# ---------------------------------------------------------------------------
# blockwise cross-entropy
# ---------------------------------------------------------------------------

def _ce_ref(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 5e-2)])
def test_blockwise_ce_fwd_bwd_parity(rng, dtype, tol):
    t, v = 48, 320
    logits = jnp.asarray(rng.randn(t, v), dtype)
    labels = jnp.asarray(rng.randint(0, v, (t,)), jnp.int32)
    cot = jnp.asarray(rng.randn(t).astype(np.float32))

    loss = blockwise_softmax_cross_entropy(logits, labels, block_t=8,
                                           block_v=64)
    np.testing.assert_allclose(np.asarray(loss),
                               np.asarray(_ce_ref(logits, labels)),
                               atol=tol, rtol=tol)

    gp = jax.grad(lambda lg: jnp.sum(blockwise_softmax_cross_entropy(
        lg, labels, block_t=8, block_v=64) * cot))(logits)
    gx = jax.grad(lambda lg: jnp.sum(
        _ce_ref(lg, labels) * cot))(logits)
    assert gp.dtype == logits.dtype
    np.testing.assert_allclose(np.asarray(gp, np.float32),
                               np.asarray(gx, np.float32),
                               atol=tol, rtol=tol)


def test_blockwise_ce_untileable_returns_none(rng):
    # vocab < 8: no tile fits -> the caller's XLA fallback
    logits = jnp.asarray(rng.randn(16, 7).astype(np.float32))
    labels = jnp.zeros((16,), jnp.int32)
    assert blockwise_softmax_cross_entropy(logits, labels) is None
    assert fit_blocks(16, 7, 128, 512, True) is None
    assert fit_blocks(4, 64, 128, 512, True) is None
    # an odd axis still tiles as ONE block when >= 8 (interpret mode)
    assert fit_blocks(16, 31, 128, 512, True) == (16, 31)
    assert fit_blocks(16, 64, 128, 512, True) == (16, 64)
    # compiled Mosaic needs the 128-lane alignment
    assert fit_blocks(16, 64, 128, 512, False) is None


# ---------------------------------------------------------------------------
# fused MLM head
# ---------------------------------------------------------------------------

def _head_ref(h, w, b, labels):
    logits = h.astype(jnp.float32) @ w.astype(jnp.float32) + b[None, :]
    return _ce_ref(logits, labels)


def test_fused_head_fwd_bwd_parity(rng):
    t, d, v = 32, 64, 256
    h = jnp.asarray(rng.randn(t, d).astype(np.float32) * 0.3)
    w = jnp.asarray(rng.randn(d, v).astype(np.float32) * 0.2)
    b = jnp.asarray(rng.randn(v).astype(np.float32) * 0.1)
    labels = jnp.asarray(rng.randint(0, v, (t,)), jnp.int32)
    cot = jnp.asarray(rng.randn(t).astype(np.float32))

    loss = fused_mlm_head_loss(h, w, labels, bias=b, block_t=8,
                               block_v=64)
    np.testing.assert_allclose(np.asarray(loss),
                               np.asarray(_head_ref(h, w, b, labels)),
                               atol=1e-5, rtol=1e-5)

    gp = jax.grad(lambda *a: jnp.sum(fused_mlm_head_loss(
        a[0], a[1], labels, bias=a[2], block_t=8, block_v=64) * cot),
        argnums=(0, 1, 2))(h, w, b)
    gx = jax.grad(lambda *a: jnp.sum(_head_ref(*a, labels) * cot),
                  argnums=(0, 1, 2))(h, w, b)
    for a, c in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   atol=1e-5, rtol=1e-5)


def _collect_shapes(jaxpr, acc):
    for v in list(jaxpr.invars) + list(jaxpr.outvars) + \
            list(jaxpr.constvars):
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            acc.add(tuple(aval.shape))
    for eqn in jaxpr.eqns:
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                acc.add(tuple(aval.shape))
        for p in eqn.params.values():
            _recurse_param(p, acc)


def _recurse_param(p, acc):
    if isinstance(p, (list, tuple)):
        for x in p:
            _recurse_param(x, acc)
    elif hasattr(p, "jaxpr"):          # ClosedJaxpr
        _collect_shapes(p.jaxpr, acc)
    elif hasattr(p, "eqns"):           # raw Jaxpr
        _collect_shapes(p, acc)


def test_fused_head_never_materializes_logits(rng):
    """The acceptance property: no (tokens, vocab) aval ANYWHERE in the
    fwd or bwd jaxpr of the fused head — the logits tensor does not
    exist. The un-fused reference is the positive control (its jaxpr
    does carry the (T, V) intermediate)."""
    t, d, v = 64, 32, 512          # (64, 512) identifies the logits
    h = jnp.asarray(rng.randn(t, d).astype(np.float32))
    w = jnp.asarray(rng.randn(d, v).astype(np.float32))
    b = jnp.asarray(rng.randn(v).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, v, (t,)), jnp.int32)

    def pallas_loss(h, w, b):
        return jnp.sum(fused_mlm_head_loss(h, w, labels, bias=b,
                                           block_t=8, block_v=64))

    def ref_loss(h, w, b):
        return jnp.sum(_head_ref(h, w, b, labels))

    for fn in (pallas_loss,
               jax.grad(pallas_loss, argnums=(0, 1, 2))):
        shapes = set()
        _collect_shapes(jax.make_jaxpr(fn)(h, w, b).jaxpr, shapes)
        assert (t, v) not in shapes, \
            "fused head materialized a (%d, %d) logits buffer" % (t, v)
    control = set()
    _collect_shapes(jax.make_jaxpr(ref_loss)(h, w, b).jaxpr, control)
    assert (t, v) in control  # the detector actually detects


# ---------------------------------------------------------------------------
# fused adam
# ---------------------------------------------------------------------------

def _adam_ref(p, g, m1, m2, lr_t, b1=0.9, b2=0.999, eps=1e-8):
    gf = g.astype(jnp.float32)
    m1n = b1 * m1 + (1 - b1) * gf
    m2n = b2 * m2 + (1 - b2) * gf * gf
    pn = p.astype(jnp.float32) - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    return pn.astype(p.dtype), m1n, m2n


@pytest.mark.parametrize("shape,dtype", [
    ((40, 64), jnp.float32),      # 2-D, divides evenly
    ((2100,), jnp.float32),       # ragged: exercises lane padding
    ((33, 65), jnp.bfloat16),     # bf16 param, f32 moments
])
def test_fused_adam_parity(rng, shape, dtype):
    p = jnp.asarray(rng.randn(*shape), dtype)
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    m1 = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)
    m2 = jnp.asarray(np.abs(rng.randn(*shape)).astype(np.float32) * 0.1)
    lr_t = jnp.float32(0.01)
    out = fused_adam(p, g, m1, m2, lr_t, block_rows=8)
    assert out is not None and out[0].dtype == p.dtype
    ref = _adam_ref(p, g, m1, m2, lr_t)
    tol = 5e-2 if dtype == jnp.bfloat16 else 1e-6
    for a, c in zip(out, ref):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(c, np.float32), atol=tol)


def test_fused_adam_small_param_falls_back():
    z = jnp.zeros((64,), jnp.float32)
    assert fused_adam(z, z, z, z, jnp.float32(0.1)) is None


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------

def _ln_ref(x, sc, bi, eps=1e-5):
    m = jnp.mean(x, -1, keepdims=True)
    v = jnp.var(x, -1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * sc[None, :] + bi[None, :]


def test_fused_layer_norm_fwd_bwd_parity(rng):
    r, c = 36, 96                  # ragged rows: exercises row padding
    x = jnp.asarray(rng.randn(r, c).astype(np.float32))
    sc = jnp.asarray(rng.randn(c).astype(np.float32))
    bi = jnp.asarray(rng.randn(c).astype(np.float32))
    cot = jnp.asarray(rng.randn(r, c).astype(np.float32))

    y = fused_layer_norm(x, sc, bi, block_rows=8)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_ln_ref(x, sc, bi)),
                               atol=1e-5, rtol=1e-5)
    gp = jax.grad(lambda *a: jnp.sum(
        fused_layer_norm(*a, block_rows=8) * cot),
        argnums=(0, 1, 2))(x, sc, bi)
    gx = jax.grad(lambda *a: jnp.sum(_ln_ref(*a) * cot),
                  argnums=(0, 1, 2))(x, sc, bi)
    for a, c_ in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c_),
                                   atol=1e-4, rtol=1e-4)


# ---------------------------------------------------------------------------
# dispatch scope + registry wiring
# ---------------------------------------------------------------------------

def test_scope_enables_and_restores():
    assert pd.enabled("adam") is None
    cfg = pd.PallasConfig({"adam"})
    with pd.scope(cfg):
        assert pd.enabled("adam") is cfg
        assert pd.enabled("layer_norm") is None
        with pd.scope(pd.PallasConfig({"layer_norm"})):
            assert pd.enabled("adam") is None
            assert pd.enabled("layer_norm") is not None
        assert pd.enabled("adam") is cfg
    assert pd.enabled("adam") is None
    with pytest.raises(ValueError):
        pd.PallasConfig({"nonexistent_op"})


def test_registry_ce_wiring_parity(rng):
    """The softmax_with_cross_entropy op under the dispatch scope: same
    Softmax/Loss as the XLA lowering, incl. ignore_index; soft_label
    stays on the XLA path."""
    from paddle_tpu.ops.registry import get_op
    fn = get_op("softmax_with_cross_entropy").fn
    logits = jnp.asarray(rng.randn(16, 128).astype(np.float32))
    label = jnp.asarray(rng.randint(0, 128, (16, 1)).astype(np.int64))
    label = label.at[3, 0].set(-100)   # ignored token
    ins = {"Logits": [logits], "Label": [label]}
    base = fn(None, ins, {"ignore_index": -100})
    with pd.scope(pd.PallasConfig({"softmax_with_cross_entropy"})):
        pal = fn(None, ins, {"ignore_index": -100})
        soft = fn(None, {"Logits": [logits],
                         "Label": [jax.nn.softmax(logits)]},
                  {"soft_label": True})
    for slot in ("Softmax", "Loss"):
        np.testing.assert_allclose(np.asarray(pal[slot]),
                                   np.asarray(base[slot]), atol=1e-6)
    assert float(np.asarray(pal["Loss"])[3, 0]) == 0.0
    assert soft["Loss"].shape == (16, 1)


def test_registry_layer_norm_wiring_parity(rng):
    from paddle_tpu.ops.registry import get_op
    fn = get_op("layer_norm").fn
    x = jnp.asarray(rng.randn(4, 8, 32).astype(np.float32))
    sc = jnp.asarray(rng.randn(256).astype(np.float32))
    bi = jnp.asarray(rng.randn(256).astype(np.float32))
    ins = {"X": [x], "Scale": [sc], "Bias": [bi]}
    base = fn(None, ins, {"begin_norm_axis": 1})
    with pd.scope(pd.PallasConfig({"layer_norm"})):
        pal = fn(None, ins, {"begin_norm_axis": 1})
        # no Scale/Bias -> XLA path even under the scope
        plain = fn(None, {"X": [x]}, {"begin_norm_axis": 1})
    for slot in ("Y", "Mean", "Variance"):
        np.testing.assert_allclose(np.asarray(pal[slot]),
                                   np.asarray(base[slot]),
                                   atol=1e-5, rtol=1e-5)
        assert pal[slot].shape == base[slot].shape
    assert plain["Y"].shape == x.shape


def _build_train(classes=128):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [64], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=128, act="relu")
        h = layers.layer_norm(h)
        logits = layers.fc(h, size=classes)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        optimizer.Adam(1e-3).minimize(loss)
    return main, startup, loss


def _run_train(use_pallas, feed, steps=3, tune_cache=None):
    with scope_guard(Scope()):
        main, startup, loss = _build_train()
        exe = pt.Executor()
        exe.run(startup)
        bs = BuildStrategy()
        bs.mesh_axes = {"dp": min(8, len(jax.devices()))}
        bs.use_pallas = use_pallas
        bs.pallas_tune_cache = tune_cache
        comp = CompiledProgram(main, bs)
        curve = [float(np.asarray(
            exe.run(comp, feed=feed, fetch_list=[loss])[0]).reshape(()))
            for _ in range(steps)]
    return curve


def _feed(rng, n=16):
    return {"x": rng.rand(n, 64).astype(np.float32),
            "y": rng.randint(0, 128, (n, 1)).astype(np.int64)}


def test_compiled_program_pallas_parity(rng):
    """All three kernels engaged through BuildStrategy.use_pallas on a
    dp mesh: the loss trajectory matches the XLA lowering."""
    feed = _feed(rng)
    base = _run_train(frozenset(), feed)
    pal = _run_train(ALL_OPS, feed)
    np.testing.assert_allclose(pal, base, rtol=1e-5, atol=1e-5)
    assert base[0] > base[-1]      # it actually trained


def test_use_pallas_in_compile_cache_token(rng):
    """Toggling use_pallas must re-lower (a stale executable would keep
    the old lowering); returning to a seen setting re-uses its entry."""
    feed = _feed(rng)
    with scope_guard(Scope()):
        main, startup, loss = _build_train()
        exe = pt.Executor()
        exe.run(startup)
        for ops in (frozenset(), frozenset({"adam"}), frozenset()):
            bs = BuildStrategy()
            bs.mesh_axes = {"dp": min(8, len(jax.devices()))}
            bs.use_pallas = ops
            exe.run(CompiledProgram(main, bs), feed=feed,
                    fetch_list=[loss])
        assert exe.cache_misses == 2
        assert exe.cache_hits == 1


# ---------------------------------------------------------------------------
# autotune: cache round-trip, tuned override, XLA-fallback routing
# ---------------------------------------------------------------------------

def test_autotune_cache_roundtrip(tmp_path):
    path = str(tmp_path / "tune.json")
    cache = at.AutotuneCache(path)
    key = pd.cache_key("adam", (4096,), "float32", {"dp": 8}, "cpu")
    entry = {"impl": "pallas", "config": {"block_rows": 64},
             "pallas_s": 0.001, "xla_s": 0.002}
    cache.put(key, entry)
    cache.save()
    fresh = at.AutotuneCache(path)
    assert fresh.lookup(key) == entry
    assert len(fresh) == 1
    assert fresh.lookup("missing|key") is None
    # corrupt file tolerated (treated empty, trace time never bricks)
    with open(path, "w") as f:
        f.write("{torn")
    assert at.AutotuneCache(path).lookup(key) is None


def test_autotune_cache_sees_resweep_of_same_file(tmp_path):
    """A live process holding an AutotuneCache must see a re-run of
    tools/autotune.py rewriting the same file (stat-based reload), and
    the executor compile token must change with the contents."""
    path = str(tmp_path / "tune.json")
    held = at.AutotuneCache(path)
    assert held.lookup("k") is None          # loads the missing file
    writer = at.AutotuneCache(path)
    writer.put("k", {"impl": "xla"})
    writer.save()
    assert held.lookup("k") == {"impl": "xla"}
    # unsaved local puts survive (no reload while dirty)
    held.put("local", {"impl": "pallas"})
    assert held.lookup("local") is not None

    bs = BuildStrategy()
    bs.mesh_axes = {"dp": 1}
    bs.use_pallas = frozenset({"adam"})
    bs.pallas_tune_cache = path
    comp = CompiledProgram(pt.Program(), bs)
    tok1 = comp._cache_token()
    writer.put("k2", {"impl": "xla"})
    writer.save()
    assert comp._cache_token() != tok1


def test_autotune_all_failed_interpret_sweep_never_says_xla(tmp_path):
    """Dry/interpret sweeps must not poison the cache with an
    unmeasured "xla" verdict: when every candidate fails to tile, the
    entry stays impl:"pallas" with no config (kernel defaults, whose
    own size guards still fall back dynamically)."""
    cache = at.AutotuneCache(str(tmp_path / "tune.json"))
    # 512 elements -> 4 lane rows < 8: every adam candidate raises
    summary = at.autotune_op("adam", (512,), probes=1, interpret=True,
                             cache=cache)
    assert all(r["status"] == "failed"
               for r in summary["results"].values())
    assert summary["entry"]["impl"] == "pallas"
    assert summary["entry"]["config"] is None


def test_choose_applies_tuned_config_and_xla_fallback(tmp_path):
    cache = at.AutotuneCache(str(tmp_path / "tune.json"))
    cfg = pd.PallasConfig({"adam", "layer_norm"}, tuning=cache,
                          mesh_axes={"dp": 8}, backend="cpu")
    cache.put(pd.cache_key("adam", (4096,), "float32", {"dp": 8}, "cpu"),
              {"impl": "pallas", "config": {"block_rows": 64}})
    cache.put(pd.cache_key("layer_norm", (32, 128), "float32", {"dp": 8},
                           "cpu"),
              {"impl": "xla"})
    assert pd.choose(cfg, "adam", (4096,), "float32") == \
        ("pallas", {"block_rows": 64})
    # the sweep said XLA wins here -> the wiring takes its XLA branch
    assert pd.choose(cfg, "layer_norm", (32, 128), "float32") == \
        ("xla", None)
    # unseen key / no cache -> pallas at defaults
    assert pd.choose(cfg, "adam", (8192,), "float32") == ("pallas", None)
    assert pd.choose(pd.PallasConfig({"adam"}), "adam", (4096,),
                     "float32") == ("pallas", None)


def test_xla_fallback_verdict_through_program(rng, tmp_path):
    """An impl:"xla" cache entry for the exact program shape routes the
    op back to XLA under use_pallas — and the run still matches."""
    cache = at.AutotuneCache(str(tmp_path / "tune.json"))
    n_dev = min(8, len(jax.devices()))
    # the train program's adam params are keyed on their FLATTENED size
    # (what the kernel tiles): route every size the program owns to xla
    for size in (64 * 128, 128, 128 * 128):
        cache.put(pd.cache_key("adam", (size,), "float32",
                               {"dp": n_dev}, "cpu"),
                  {"impl": "xla"})
    cache.save()
    feed = _feed(rng)
    base = _run_train(frozenset(), feed)
    routed = _run_train(frozenset({"adam"}), feed,
                        tune_cache=str(tmp_path / "tune.json"))
    np.testing.assert_allclose(routed, base, rtol=1e-6, atol=1e-6)


def test_autotune_op_dry_sweep_persists_winner(tmp_path):
    cache = at.AutotuneCache(str(tmp_path / "tune.json"))
    summary = at.autotune_op("layer_norm", (32, 128), probes=1,
                             interpret=True, cache=cache)
    entry = summary["entry"]
    assert entry["impl"] == "pallas"      # interpret sweeps never say xla
    assert entry["config"] in at.DRY_CANDIDATES["layer_norm"]
    assert os.path.exists(cache.path)
    fresh = at.AutotuneCache(cache.path)
    assert fresh.lookup(summary["key"])["config"] == entry["config"]
    assert all(r["status"] == "ok" and
               isinstance(r["measured_s"], float)
               for r in summary["results"].values())
    # the winner's per-candidate rows are banked for future model fits
    assert entry["results"] and all(
        isinstance(s, float) for s in entry["results"].values())


def test_tools_autotune_cli_dry_run(tmp_path, capsys):
    """tools/autotune.py --dry-run end-to-end in-process: the tier-1
    smoke that keeps the sweep harness itself from rotting."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_autotune_cli", os.path.join(root, "tools", "autotune.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    cache = str(tmp_path / "dry.json")
    rc = mod.main(["--dry-run", "--ops", "adam,layer_norm",
                   "--cache", cache])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["metric"] == "pallas_autotune" and report["ok"]
    assert report["dry_run"] and report["entries"] == 2
    data = json.load(open(cache))
    # versioned envelope (tools/tunecheck.py's format contract)
    assert data["format_version"] == at.FORMAT_VERSION
    assert len(data["entries"]) == 2
    for entry in data["entries"].values():
        assert entry["impl"] == "pallas" and entry["interpret"]
    # bad op name is a usage error, not a crash
    with pytest.raises(SystemExit):
        mod.main(["--ops", "nope"])
    capsys.readouterr()


# ---------------------------------------------------------------------------
# fused_mlm_head_loss model-head wiring (PR 10 satellite: the registry op
# that bert/gpt heads now emit — ROADMAP item 2 remainder)
# ---------------------------------------------------------------------------

def _head_program(t=32, d=16, v=512):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("hx", [d], dtype="float32")
        lbl = layers.data("hl", [1], dtype="int64")
        h = layers.fc(x, size=d, act="tanh",
                      param_attr=pt.ParamAttr(name="head_fc_w"),
                      bias_attr=pt.ParamAttr(name="head_fc_b"))
        emb = layers.create_parameter([v, d], "float32", name="head_emb")
        bias = layers.create_parameter([v], "float32", name="head_bias")
        ce = layers.fused_mlm_head_loss(h, emb, lbl, bias=bias)
        loss = layers.mean(ce)
        optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def test_fused_head_op_registry_wiring_parity(rng):
    """The fused_mlm_head_loss registry op trains identically with the
    Pallas lowering on (interpret) and off — and toggling use_pallas
    re-lowers (cache-token regression)."""
    t, d, v = 32, 16, 512
    xv = rng.rand(t, d).astype(np.float32)
    lv = rng.randint(0, v, (t, 1)).astype(np.int64)

    def run(use_pallas, steps=4):
        with scope_guard(Scope()):
            main, startup, loss = _head_program(t, d, v)
            exe = pt.Executor()
            exe.run(startup)
            bs = BuildStrategy()
            bs.mesh_axes = {"dp": 8}
            if use_pallas:
                bs.use_pallas = frozenset({"fused_mlm_head_loss"})
            os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
            try:
                comp = CompiledProgram(main, bs)
                out = [float(exe.run(comp, feed={"hx": xv, "hl": lv},
                                     fetch_list=[loss])[0][0])
                       for _ in range(steps)]
            finally:
                os.environ.pop("PADDLE_TPU_PALLAS_INTERPRET", None)
            w = pt.global_scope().get_numpy("head_emb").copy()
        return out, w

    ref, w_ref = run(False)
    got, w_got = run(True)
    assert ref[-1] < ref[0]
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(w_got, w_ref, rtol=1e-3, atol=1e-5)


def test_fused_head_op_never_materializes_logits_in_program_grad(rng):
    """Through the REGISTRY op (what the model heads emit), the Pallas
    route keeps the (T, V) logits out of the fwd+bwd jaxpr; the XLA
    fallback (positive control) materializes them."""
    from paddle_tpu.ops.registry import get_op
    # v = 4x the default block_v, so the kernel's (bt, bv) tile can
    # never be mistaken for the full (t, v) logits by the shape walk
    t, d, v = 32, 16, 2048
    h = jnp.asarray(rng.rand(t, d).astype(np.float32))
    w = jnp.asarray(rng.rand(v, d).astype(np.float32) * 0.1)
    b = jnp.zeros((v,), jnp.float32)
    lbl = jnp.asarray(rng.randint(0, v, (t, 1)).astype(np.int32))
    kern = get_op("fused_mlm_head_loss").fn

    class _Ctx(object):
        def rng(self):
            return jax.random.PRNGKey(0)

    def make_grad():
        # a FRESH function object per trace: jax caches traced jaxprs
        # by function identity, which would let the in-scope trace
        # leak into the control
        def loss_of(h, w, b):
            out = kern(_Ctx(), {"Hidden": [h], "Weight": [w],
                                "Bias": [b], "Label": [lbl]}, {})
            return jnp.sum(out["Loss"])
        return jax.grad(loss_of, argnums=(0, 1, 2))

    cfg = pd.PallasConfig({"fused_mlm_head_loss"}, interpret=True)
    shapes = set()
    with pd.scope(cfg):
        _collect_shapes(jax.make_jaxpr(make_grad())(h, w, b).jaxpr,
                        shapes)
    assert (t, v) not in shapes
    # migration seam: a pre-PR-10 config that enabled the blockwise CE
    # by its OLD op name still routes the (now fused) model heads
    # through Pallas
    legacy = pd.PallasConfig({"softmax_with_cross_entropy"},
                             interpret=True)
    shapes_legacy = set()
    with pd.scope(legacy):
        _collect_shapes(jax.make_jaxpr(make_grad())(h, w, b).jaxpr,
                        shapes_legacy)
    assert (t, v) not in shapes_legacy
    control = set()
    _collect_shapes(jax.make_jaxpr(make_grad())(h, w, b).jaxpr, control)
    assert (t, v) in control


def test_bert_and_gpt_heads_emit_the_fused_op():
    """models/bert + models/gpt pretrain programs route their LM heads
    through fused_mlm_head_loss (the ROADMAP 'registry op still
    receives materialized logits' gap is closed at the MODEL level)."""
    from paddle_tpu.models import bert as bert_mod
    from paddle_tpu.models import gpt as gpt_mod
    cfg = bert_mod.BertConfig(vocab_size=128, hidden_size=16,
                              num_layers=1, num_heads=2, ff_size=32,
                              max_position=32)
    main, _, _, _ = bert_mod.bert_pretrain_program(cfg, 2, 8,
                                                   max_preds_per_seq=2)
    ops = [op.type for op in main.global_block().ops]
    assert "fused_mlm_head_loss" in ops
    gcfg = gpt_mod.GPTConfig(vocab_size=128, hidden_size=16,
                             num_layers=1, num_heads=2, ff_size=32,
                             max_position=32)
    gmain, _, _, _ = gpt_mod.gpt_pretrain_program(gcfg, 2, 8)
    gops = [op.type for op in gmain.global_block().ops]
    assert "fused_mlm_head_loss" in gops
