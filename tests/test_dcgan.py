"""DCGAN: one fused adversarial step; optimizer scoping via
parameter_list keeps G fixed under d_loss and D fixed under g_loss."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.models import dcgan


def test_dcgan_adversarial_step_trains():
    cfg = dcgan.DCGANConfig(noise_dim=16, base_channels=8, image_size=16)
    with pt.unique_name.guard():
        main, startup, feeds, fetch = dcgan.dcgan_train_program(cfg)
    batch = dcgan.synthetic_batch(cfg, batch_size=8)
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        losses = []
        for i in range(8):
            d, g = exe.run(main, feed=batch,
                           fetch_list=[fetch["d_loss"], fetch["g_loss"]])
            losses.append((float(np.asarray(d).reshape(-1)[0]),
                           float(np.asarray(g).reshape(-1)[0])))
        assert all(np.isfinite(v) for pair in losses for v in pair)
        # the discriminator learns to separate real from (early) fakes
        assert losses[-1][0] < losses[0][0]


def test_dcgan_parameter_list_scoping():
    """minimize(parameter_list=D) must leave generator WEIGHTS
    bit-identical: a program containing ONLY the d optimizer."""
    from paddle_tpu import layers, optimizer
    cfg = dcgan.DCGANConfig(noise_dim=8, base_channels=4, image_size=8)
    with pt.unique_name.guard():
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            real = layers.data("real", [1, 8, 8], dtype="float32")
            noise = layers.data("noise", [8], dtype="float32")
            fake = dcgan.generator(noise, cfg, is_test=True)
            d_real = dcgan.discriminator(real, cfg)
            d_fake = dcgan.discriminator(fake, cfg)
            d_loss = layers.elementwise_add(
                dcgan._bce_logits(d_real, 1.0),
                dcgan._bce_logits(d_fake, 0.0))
            d_params = [p for p in main.global_block().all_parameters()
                        if p.name.startswith("disc_")]
            optimizer.Adam(2e-3).minimize(d_loss,
                                          parameter_list=d_params)
    batch = dcgan.synthetic_batch(cfg, batch_size=4, seed=1)
    sc = Scope()
    with scope_guard(sc):
        exe = pt.Executor()
        exe.run(startup)
        before = {p.name: np.asarray(sc.find_var(p.name)).copy()
                  for p in main.global_block().all_parameters()}
        exe.run(main, feed=batch, fetch_list=[d_loss])
        moved = {n for n, v in before.items()
                 if not np.array_equal(v, np.asarray(sc.find_var(n)))}
    assert any(n.startswith("disc_") for n in moved)
    # the generator's trainable weights must be untouched (the d step
    # backprops THROUGH G but must not update it)
    gen_weights = {n for n in before if n.startswith("gen_")}
    assert gen_weights and not (moved & gen_weights), moved & gen_weights


def test_conv2d_transpose_output_size_honored():
    """output_size attr reaches the kernel: runtime tensor matches the
    requested (valid-range) size, not only the declared shape."""
    from paddle_tpu import layers
    with pt.unique_name.guard():
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("ct_x", [2, 16, 16], dtype="float32",
                            append_batch_size=False)
            x4 = layers.reshape(x, [1, 2, 16, 16])
            y = layers.conv2d_transpose(x4, 3, filter_size=3, stride=2,
                                        padding=1, output_size=32)
            assert tuple(y.shape[2:]) == (32, 32)
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        out, = exe.run(main, feed={
            "ct_x": np.random.RandomState(0).rand(2, 16, 16).astype(
                np.float32)}, fetch_list=[y])
    assert np.asarray(out).shape == (1, 3, 32, 32)
