"""Program IR verifier batteries (framework/analysis.py).

The adversarial corpus: >= 3 deliberately-broken programs PER PASS,
each pinning the exact diagnostic (pass name, op index, severity);
plus the wiring contract — strict raises with ALL violations listed,
warn logs + exports metrics, "off" is inert on the compile path — and
the strict-mode sweep over the model zoo programs.
"""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.distributed.pipeline_program import pp_stage_guard
from paddle_tpu.framework import analysis, resilience
from paddle_tpu.framework.analysis import (
    PASS_DEF_USE, PASS_SHAPE, PASS_SHARDING, PASS_PIPELINE, PASS_DCE,
    ProgramVerificationError)
from paddle_tpu.framework.compiler import BuildStrategy, CompiledProgram
from paddle_tpu.framework.scope import Scope, scope_guard

pytestmark = [pytest.mark.analysis]


def _diags(result, pass_name, severity=None):
    return [d for d in result
            if d.pass_name == pass_name
            and (severity is None or d.severity == severity)]


def _find(result, pass_name, severity, op_idx):
    hits = [d for d in _diags(result, pass_name, severity)
            if d.op_idx == op_idx]
    assert hits, "no %s/%s diagnostic at op %r in:\n%s" % (
        pass_name, severity, op_idx, result.summary())
    return hits[0]


# ---------------------------------------------------------------------------
# pass 1: def_use — dangling reads, def-before-use, section ordering
# ---------------------------------------------------------------------------

def test_def_use_dangling_undeclared_read():
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="o", shape=[4], dtype="float32")
    blk.append_op("scale", inputs={"X": ["nope"]},
                  outputs={"Out": ["o"]}, attrs={"scale": 2.0})
    r = analysis.verify_program(main, feeds={})
    d = _find(r, PASS_DEF_USE, "error", 0)
    assert "nope" in d.vars and "dangling" in d.message


def test_def_use_read_never_produced_declared_var():
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="ghost", shape=[4], dtype="float32")
    blk.create_var(name="o", shape=[4], dtype="float32")
    blk.append_op("scale", inputs={"X": ["ghost"]},
                  outputs={"Out": ["o"]}, attrs={"scale": 2.0})
    # feeds known and do not include `ghost` -> a certain trace failure
    r = analysis.verify_program(main, feeds={})
    d = _find(r, PASS_DEF_USE, "error", 0)
    assert "ghost" in d.vars
    # feed set unknown -> it MIGHT be fed: degraded to a warning
    r2 = analysis.verify_program(main)
    _find(r2, PASS_DEF_USE, "warning", 0)


def test_def_use_def_before_use():
    main = pt.Program()
    blk = main.global_block()
    for n in ("a", "b", "t"):
        blk.create_var(name=n, shape=[4], dtype="float32")
    blk.append_op("scale", inputs={"X": ["t"]},      # op 0 reads t
                  outputs={"Out": ["a"]}, attrs={"scale": 1.0})
    blk.append_op("scale", inputs={"X": ["a"]},      # op 1 produces t
                  outputs={"Out": ["t"]}, attrs={"scale": 1.0})
    r = analysis.verify_program(main, feeds={})
    d = _find(r, PASS_DEF_USE, "error", 0)
    assert "before its producer" in d.message and "t" in d.vars


def test_def_use_backward_after_optimize_ordering():
    main = pt.Program()
    blk = main.global_block()
    for n in ("x", "y", "z"):
        blk.create_var(name=n, shape=[4], dtype="float32")
    blk.append_op("scale", inputs={"X": ["x"]}, outputs={"Out": ["y"]},
                  attrs={"scale": 1.0, "op_role": "optimize"})
    blk.append_op("scale", inputs={"X": ["y"]}, outputs={"Out": ["z"]},
                  attrs={"scale": 1.0, "op_role": "backward"})
    r = analysis.verify_program(main, feeds={"x": (4,)})
    # info, not error: gradients()-after-minimize and two-optimizer
    # adversarial steps interleave sections ON PURPOSE (test_dcgan,
    # test_ops_extra) — the report locates it without refusing it
    d = _find(r, PASS_DEF_USE, "info", 1)
    assert "forward < backward < optimize" in d.message


# ---------------------------------------------------------------------------
# pass 2: shape_dtype — wrong-width matmul, reshape mismatch, dtype mix
# ---------------------------------------------------------------------------

def _two_var_program(shape_x, shape_y, dtype_x="float32",
                     dtype_y="float32"):
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=shape_x, dtype=dtype_x, is_data=True)
    blk.create_var(name="y", shape=shape_y, dtype=dtype_y, is_data=True)
    blk.create_var(name="o", shape=None, dtype=None)
    return main, blk


def test_shape_matmul_contraction_mismatch():
    main, blk = _two_var_program([4, 8], [7, 3])
    blk.append_op("matmul", inputs={"X": ["x"], "Y": ["y"]},
                  outputs={"Out": ["o"]})
    r = analysis.verify_program(main, feeds={"x": (4, 8), "y": (7, 3)})
    d = _find(r, PASS_SHAPE, "error", 0)
    assert "contraction width mismatch" in d.message


def test_shape_reshape_element_mismatch():
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4, 16], dtype="float32", is_data=True)
    blk.create_var(name="o", shape=None, dtype=None)
    blk.append_op("reshape2", inputs={"X": ["x"]},
                  outputs={"Out": ["o"]}, attrs={"shape": [4, 15]})
    r = analysis.verify_program(main, feeds={"x": (4, 16)})
    d = _find(r, PASS_SHAPE, "error", 0)
    assert "element count mismatch" in d.message


def test_shape_mixed_float_dtype_add():
    main, blk = _two_var_program([4, 8], [4, 8], "float32", "float16")
    blk.append_op("elementwise_add", inputs={"X": ["x"], "Y": ["y"]},
                  outputs={"Out": ["o"]})
    r = analysis.verify_program(main, feeds={"x": (4, 8), "y": (4, 8)})
    # warning, not error: AMP mixes bf16/f32 on purpose (weak
    # promotion); strict mode must keep compiling those programs
    d = _find(r, PASS_SHAPE, "warning", 0)
    assert "mixes float dtypes" in d.message


def test_shape_ce_label_misalignment_and_broadcast():
    # wrong-width head: label rows disagree with the logits rows
    main, blk = _two_var_program([16, 4], [8, 1], dtype_y="int64")
    blk.append_op("softmax_with_cross_entropy",
                  inputs={"Logits": ["x"], "Label": ["y"]},
                  outputs={"Softmax": ["s"], "Loss": ["o"]})
    blk.create_var(name="s", shape=None, dtype=None)
    r = analysis.verify_program(main, feeds={"x": (16, 4), "y": (8, 1)})
    assert _find(r, PASS_SHAPE, "error", 0)
    # non-broadcastable elementwise
    main2, blk2 = _two_var_program([4, 8], [4, 7])
    blk2.append_op("elementwise_mul", inputs={"X": ["x"], "Y": ["y"]},
                   outputs={"Out": ["o"]})
    r2 = analysis.verify_program(main2, feeds={"x": (4, 8),
                                               "y": (4, 7)})
    d = _find(r2, PASS_SHAPE, "error", 0)
    assert "not broadcastable" in d.message


def test_shape_unknown_op_never_false_positives():
    """An op without a shape rule infers top; downstream checks that
    would need its output shape are skipped."""
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    for n in ("h", "o"):
        blk.create_var(name=n, shape=None, dtype=None)
    blk.append_op("definitely_not_an_op", inputs={"X": ["x"]},
                  outputs={"Out": ["h"]})
    blk.append_op("matmul", inputs={"X": ["h"], "Y": ["x"]},
                  outputs={"Out": ["o"]})
    r = analysis.verify_program(main, feeds={"x": (4, 8)},
                                passes=[PASS_SHAPE])
    assert not r.errors() and not r.warnings(), r.summary()


# ---------------------------------------------------------------------------
# pass 3: sharding feasibility
# ---------------------------------------------------------------------------

def _mesh_bs(**kw):
    bs = BuildStrategy(**kw)
    return bs


def test_sharding_quantize_needs_pure_dp():
    main = pt.Program()
    bs = _mesh_bs(quantize_collectives=True)
    bs.mesh_axes = {"dp": 2, "mp": 4}
    r = analysis.verify_program(main, build_strategy=bs)
    d = _diags(r, PASS_SHARDING, "error")
    assert d and "pure data-parallel" in d[0].message


def test_sharding_feed_batch_not_dp_divisible():
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[-1, 8], dtype="float32",
                   is_data=True)
    bs = _mesh_bs()
    bs.mesh_axes = {"dp": 2}
    r = analysis.verify_program(main, feeds={"x": (7, 8)},
                                build_strategy=bs)
    d = _diags(r, PASS_SHARDING, "warning")
    assert d and "does not divide" in d[0].message and \
        d[0].vars == ("x",)


def test_sharding_mp_axis_divisibility_and_unknown_axis():
    main = pt.Program()
    blk = main.global_block()
    v = blk.create_var(name="w", shape=[5, 8], dtype="float32")
    v.sharding = ("mp", None)
    bs = _mesh_bs()
    bs.mesh_axes = {"dp": 2, "mp": 2}
    r = analysis.verify_program(main, build_strategy=bs)
    warn = _diags(r, PASS_SHARDING, "warning")
    assert warn and "stays replicated" in warn[0].message
    # axis absent from the mesh -> info, mirroring _var_sharding's drop
    v.sharding = ("tp9", None)
    r2 = analysis.verify_program(main, build_strategy=bs)
    info = _diags(r2, PASS_SHARDING, "info")
    assert info and "does not have" in info[0].message


# ---------------------------------------------------------------------------
# pass 4: pipeline feasibility (pre-extract diagnostics list)
# ---------------------------------------------------------------------------

def _pp_bs(n_stage=2, schedule="1f1b", m=1):
    bs = BuildStrategy(pp_stages=n_stage, pp_micro_batches=m,
                       pp_schedule=schedule)
    bs.mesh_axes = {"pp": n_stage, "dp": 1}
    return bs


def _stamped_program(n_stage=2, heterogeneous=False, stages=None):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("pp_x", [8, 16], "float32",
                        append_batch_size=False)
        h = x
        for i in range(n_stage):
            with pp_stage_guard(stages[i] if stages else i):
                h = layers.fc(h, size=16,
                              act="relu" if heterogeneous and i else
                              "tanh")
        y = layers.data("pp_y", [8, 16], "float32",
                        append_batch_size=False)
        loss = layers.reduce_mean(layers.square(h - y))
        optimizer.SGD(0.1).minimize(loss)
    return main, loss


def test_pipeline_unminimized_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("pp_x", [8, 16], "float32",
                        append_batch_size=False)
        h = x
        for i in range(2):
            with pp_stage_guard(i):
                h = layers.fc(h, size=16, act="tanh")
    r = analysis.verify_program(main, build_strategy=_pp_bs())
    d = _diags(r, PASS_PIPELINE, "error")
    assert d and "minimize" in d[0].message


def test_pipeline_non_contiguous_stamps():
    main, _ = _stamped_program(stages=[0, 2])
    r = analysis.verify_program(main, build_strategy=_pp_bs())
    d = _diags(r, PASS_PIPELINE, "error")
    assert d and "contiguous" in d[0].message


def test_pipeline_heterogeneous_stages():
    main, _ = _stamped_program(heterogeneous=True)
    r = analysis.verify_program(main, build_strategy=_pp_bs())
    d = _diags(r, PASS_PIPELINE, "error")
    assert d and any("structurally identical" in x.message for x in d)


def test_pipeline_stage_count_vs_strategy_and_mesh():
    main, _ = _stamped_program(n_stage=2)
    bs = _pp_bs(n_stage=4)
    r = analysis.verify_program(main, build_strategy=bs)
    msgs = [d.message for d in _diags(r, PASS_PIPELINE, "error")]
    assert any("stamped with 2" in m for m in msgs), msgs
    # mesh pp axis disagreeing with pp_stages
    bs2 = _pp_bs(n_stage=2)
    bs2.mesh_axes = {"pp": 4, "dp": 1}
    r2 = analysis.verify_program(main, build_strategy=bs2)
    msgs2 = [d.message for d in _diags(r2, PASS_PIPELINE, "error")]
    assert any("does not match" in m for m in msgs2), msgs2


def test_pipeline_bad_schedule_and_micro_divisibility():
    main, _ = _stamped_program()
    bs = _pp_bs(schedule="zigzag", m=3)
    r = analysis.verify_program(main, feeds={"pp_x": (8, 16),
                                             "pp_y": (8, 16)},
                                build_strategy=bs)
    msgs = [d.message for d in _diags(r, PASS_PIPELINE, "error")]
    assert any("pp_schedule" in m for m in msgs), msgs
    assert any("pp_micro_batches" in m for m in msgs), msgs


def test_pipeline_reports_all_violations_in_one_shot():
    """The tentpole contract: N independent pp violations surface as N
    diagnostics, not first-error-wins."""
    main, _ = _stamped_program(heterogeneous=True)
    bs = _pp_bs(schedule="zigzag", m=3)
    r = analysis.verify_program(main, feeds={"pp_x": (8, 16),
                                             "pp_y": (8, 16)},
                                build_strategy=bs)
    errs = _diags(r, PASS_PIPELINE, "error")
    assert len(errs) >= 3, r.summary()


# ---------------------------------------------------------------------------
# pass 5: dce — dead ops against fetch/update/collective roots
# ---------------------------------------------------------------------------

def _dead_op_program():
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    for n in ("live", "dead1", "dead2"):
        blk.create_var(name=n, shape=[4], dtype="float32")
    blk.append_op("scale", inputs={"X": ["x"]}, outputs={"Out": ["live"]},
                  attrs={"scale": 2.0})                       # op 0
    blk.append_op("scale", inputs={"X": ["x"]},
                  outputs={"Out": ["dead1"]}, attrs={"scale": 3.0})  # op 1
    blk.append_op("scale", inputs={"X": ["dead1"]},
                  outputs={"Out": ["dead2"]}, attrs={"scale": 4.0})  # op 2
    return main


def test_dce_flags_dead_chain():
    r = analysis.verify_program(_dead_op_program(), feeds={"x": (4,)},
                                fetch_list=["live"])
    assert _find(r, PASS_DCE, "info", 1)
    assert _find(r, PASS_DCE, "info", 2)
    assert len(_diags(r, PASS_DCE)) == 2


def test_dce_needs_fetch_roots():
    # without fetch roots any leaf could be the fetch: no report
    r = analysis.verify_program(_dead_op_program(), feeds={"x": (4,)})
    assert not _diags(r, PASS_DCE)


def test_dce_persistable_and_collective_roots_stay_live():
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="w", shape=[4], dtype="float32",
                   persistable=True)
    blk.create_var(name="g", shape=[4], dtype="float32")
    blk.create_var(name="out", shape=[4], dtype="float32")
    # op 0: collective — live root even though `g` is never read
    blk.append_op("c_allreduce_sum", inputs={"X": ["x"]},
                  outputs={"Out": ["g"]})
    # op 1: persistable update — live root
    blk.append_op("scale", inputs={"X": ["x"]}, outputs={"Out": ["w"]},
                  attrs={"scale": 0.9})
    # op 2: genuinely dead
    blk.append_op("scale", inputs={"X": ["x"]}, outputs={"Out": ["out"]},
                  attrs={"scale": 1.0})
    r = analysis.verify_program(main, feeds={"x": (4,)}, fetch_list=[])
    dead = _diags(r, PASS_DCE)
    assert [d.op_idx for d in dead] == [2], r.summary()


# ---------------------------------------------------------------------------
# wiring: strict / warn / off on the compile path
# ---------------------------------------------------------------------------

def _train_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=16, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss, logits


def _feed(batch=16):
    rng = np.random.RandomState(0)
    return {"x": rng.rand(batch, 8).astype(np.float32),
            "y": rng.randint(0, 4, (batch, 1)).astype(np.int64)}


def test_strict_mode_raises_with_all_violations():
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    blk.create_var(name="y", shape=[3, 9], dtype="float32", is_data=True)
    for n in ("a", "b"):
        blk.create_var(name=n, shape=None, dtype=None)
    # two INDEPENDENT shape errors — both must be in the exception
    blk.append_op("matmul", inputs={"X": ["x"], "Y": ["y"]},
                  outputs={"Out": ["a"]})
    blk.append_op("reshape2", inputs={"X": ["x"]},
                  outputs={"Out": ["b"]}, attrs={"shape": [5, 5]})
    result = analysis.verify_program(main, feeds={"x": (4, 8),
                                                  "y": (3, 9)})
    assert len(result.errors()) == 2
    with pytest.raises(ProgramVerificationError) as ei:
        raise ProgramVerificationError(result)
    msg = str(ei.value)
    assert "contraction width" in msg and "element count" in msg


def test_compile_seam_strict_catches_malformed_program():
    """The executor's compile seam (not a direct verify call) fails a
    malformed program with located diagnostics under strict mode."""
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[-1, 8], dtype="float32",
                   is_data=True)
    blk.create_var(name="o", shape=None, dtype=None)
    blk.append_op("matmul", inputs={"X": ["x"], "Y": ["missing_w"]},
                  outputs={"Out": ["o"]})
    exe = pt.Executor()
    assert os.environ.get("PADDLE_TPU_VERIFY") == "strict"
    with pytest.raises(ProgramVerificationError, match="missing_w"):
        exe.run(main, feed={"x": np.zeros((4, 8), np.float32)},
                fetch_list=["o"])


def test_off_mode_is_inert_on_the_compile_path(monkeypatch):
    """verify_program='off' must never even CALL the verifier."""
    main, startup, loss, _ = _train_program()

    def _boom(*a, **kw):
        raise AssertionError("verifier ran in off mode")

    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        monkeypatch.setattr(analysis, "verify_program", _boom)
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "off")
        out = exe.run(main, feed=_feed(), fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()
        # CompiledProgram route honors the strategy knob the same way
        bs = BuildStrategy(verify_program="off")
        comp = CompiledProgram(main, bs).with_data_parallel(
            loss_name=loss.name)
        out2 = exe.run(comp, feed=_feed(), fetch_list=[loss])
        assert np.isfinite(np.asarray(out2[0])).all()


def test_warn_mode_logs_and_counts_but_does_not_raise(monkeypatch):
    resilience.clear_events()
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4, 16], dtype="float32",
                   is_data=True)
    blk.create_var(name="o", shape=None, dtype=None)
    blk.append_op("reshape2", inputs={"X": ["x"]},
                  outputs={"Out": ["o"]}, attrs={"shape": [4, 15]})
    from paddle_tpu.framework.compiler import verify_for_compile
    bs = BuildStrategy(verify_program="warn")
    result = verify_for_compile(main, bs, feeds={"x": (4, 16)},
                                fetch_names=["o"])
    assert result is not None and result.errors()
    totals = resilience.analysis_totals()
    assert totals.get((PASS_SHAPE, "error"), 0) >= 1
    evs = resilience.events("program_analysis")
    assert evs and evs[-1]["errors"] >= 1
    # ... and the counter rides the metrics exposition
    m = resilience.metrics()
    names = {(c["name"], tuple(sorted(c["labels"].items())))
             for c in m["counters"]}
    assert any("analysis_diagnostics_total" in n for n, _ in names)


def test_verify_memo_one_walk_per_program_version(monkeypatch):
    main, startup, loss, _ = _train_program()
    calls = []
    real = analysis.verify_program

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(analysis, "verify_program", counting)
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        for _ in range(4):
            exe.run(main, feed=_feed(), fetch_list=[loss])
    assert len(calls) == 1, "verifier must be memoized per version"


def test_allowlist_suppresses_a_pass():
    main = _dead_op_program()
    r = analysis.verify_program(main, feeds={"x": (4,)},
                                fetch_list=["live"])
    assert _diags(r, PASS_DCE)
    analysis.allowlist(main, PASS_DCE, reason="corpus: intentional")
    r2 = analysis.verify_program(main, feeds={"x": (4,)},
                                 fetch_list=["live"])
    assert not _diags(r2, PASS_DCE)


def test_verify_memo_is_per_strategy_not_just_per_program():
    """REGRESSION: two strategies sharing one Program must not share a
    memoized verdict — a clean verify under bs1 must not mask a
    quantize-on-mp error under bs2."""
    from paddle_tpu.framework.compiler import verify_for_compile
    main = pt.Program()
    bs1 = BuildStrategy(verify_program="strict")
    bs1.mesh_axes = {"dp": 2, "mp": 4}
    r1 = verify_for_compile(main, bs1)
    assert r1 is not None and not r1.errors()
    bs2 = BuildStrategy(verify_program="strict",
                        quantize_collectives=True)
    bs2.mesh_axes = {"dp": 2, "mp": 4}
    with pytest.raises(ProgramVerificationError,
                       match="pure data-parallel"):
        verify_for_compile(main, bs2)


def test_verify_cache_evicts_stale_versions():
    """REGRESSION: a mutate-run loop must not accumulate one verdict
    per historical program version."""
    from paddle_tpu.framework.compiler import verify_for_compile
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    bs = BuildStrategy(verify_program="strict")
    for i in range(5):
        blk.create_var(name="o%d" % i, shape=[4], dtype="float32")
        blk.append_op("scale", inputs={"X": ["x"]},
                      outputs={"Out": ["o%d" % i]}, attrs={"scale": 1.0})
        verify_for_compile(main, bs, feeds={"x": (4,)},
                           fetch_names=["o%d" % i])
    versions = {k[0] for k in main._verify_cache}
    assert versions == {main._version}, versions


def test_allowlist_survives_clone_and_prune():
    """REGRESSION: clone(for_test=True) / _prune keep the vetted
    exemptions — an eval program must not re-flag (or strict-fail) a
    diagnostic the train program already allowlisted."""
    main = _dead_op_program()
    analysis.allowlist(main, PASS_DCE, reason="test: vetted dead ops")
    for derived in (main.clone(), main.clone(for_test=True),
                    main._prune(["x"], ["live"])):
        r = analysis.verify_program(derived, feeds={"x": (4,)},
                                    fetch_list=["live"])
        assert not _diags(r, PASS_DCE), r.summary()


def test_pp_run_seam_checks_micro_divisibility():
    """REGRESSION: the REAL pp execution route (exe.run on a pp
    CompiledProgram) verifies with the actual feed shapes, so a batch
    not divisible by pp_micro_batches is a located diagnostic, not a
    mid-lowering error."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("pp_x", [6, 16], "float32",
                        append_batch_size=False)
        h = x
        for i in range(2):
            with pp_stage_guard(i):
                h = layers.fc(h, size=16, act="tanh")
        y = layers.data("pp_y", [6, 16], "float32",
                        append_batch_size=False)
        loss = layers.reduce_mean(layers.square(h - y))
        optimizer.SGD(0.1).minimize(loss)
    bs = BuildStrategy(pp_stages=2, pp_micro_batches=4,
                       verify_program="strict")
    bs.mesh_axes = {"pp": 2, "dp": 1}
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        comp = CompiledProgram(main, bs)
        feed = {"pp_x": np.zeros((6, 16), np.float32),
                "pp_y": np.zeros((6, 16), np.float32)}
        with pytest.raises(ProgramVerificationError,
                           match="pp_micro_batches"):
            exe.run(comp, feed=feed, fetch_list=[loss])


def test_shape_squared_l2_norm_is_rank0():
    """The rule mirrors the kernel's reshape(()) — rank 0, not (1,)."""
    from paddle_tpu.ops.registry import get_shape_rule
    from paddle_tpu.ops.shape_rules import TensorMeta

    class _Op(object):
        type = "squared_l2_norm"
    out = get_shape_rule("squared_l2_norm")(
        _Op(), {"X": [TensorMeta((4, 8), "float32")]}, {})
    assert out["Out"][0].shape == ()


def test_allowlist_applied_after_first_compile_takes_effect():
    """REGRESSION: the compile seam memoizes verdicts per program
    version — an allowlist applied AFTER a strict failure must
    invalidate the memo, not wait for an unrelated version bump."""
    from paddle_tpu.framework.compiler import verify_for_compile
    main = pt.Program()
    blk = main.global_block()
    blk.create_var(name="x", shape=[4, 16], dtype="float32",
                   is_data=True)
    blk.create_var(name="o", shape=None, dtype=None)
    blk.append_op("reshape2", inputs={"X": ["x"]},
                  outputs={"Out": ["o"]}, attrs={"shape": [4, 15]})
    bs = BuildStrategy(verify_program="strict")
    with pytest.raises(ProgramVerificationError):
        verify_for_compile(main, bs, feeds={"x": (4, 16)},
                           fetch_names=["o"])
    analysis.allowlist(main, PASS_SHAPE,
                       reason="test: vetted reshape")
    r = verify_for_compile(main, bs, feeds={"x": (4, 16)},
                           fetch_names=["o"])
    assert r is not None and not r.errors()


# ---------------------------------------------------------------------------
# strict sweep over the model zoo programs
# ---------------------------------------------------------------------------

def test_models_verify_clean_in_strict_mode():
    """Representative model-zoo programs verify with ZERO errors —
    the no-false-positive acceptance bar (the rest of the zoo rides
    the compile seam across the whole strict-mode suite)."""
    from paddle_tpu.models import bert, gpt, simple
    cases = []
    cfg = bert.BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                          num_heads=2, ff_size=64, max_position=64)
    main, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch_size=4, seq_len=16, max_preds_per_seq=4)
    cases.append(("bert", main, feeds, fetch))
    gcfg = gpt.GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                         num_heads=2, max_position=64)
    gmain, gstartup, gfeeds, gfetch = gpt.gpt_pretrain_program(
        gcfg, batch_size=4, seq_len=16)
    cases.append(("gpt", gmain, gfeeds, gfetch))
    smain, sstartup, sfeeds, sfetch = simple.mlp_classifier_program(
        input_dim=16, hidden=(8,), classes=4)
    cases.append(("mlp", smain, sfeeds, sfetch))
    for name, prog, feeds_, fetch_ in cases:
        feed_names = list(feeds_.values() if isinstance(feeds_, dict)
                          else feeds_)
        feed_names = [getattr(f, "name", f) for f in feed_names]
        fetch_list = list(fetch_.values()) if isinstance(fetch_, dict) \
            else list(fetch_)
        r = analysis.verify_program(prog, feeds=feed_names,
                                    fetch_list=fetch_list)
        assert not r.errors(), "%s: %s" % (name, r.summary())


# ---------------------------------------------------------------------------
# progcheck CLI
# ---------------------------------------------------------------------------

def _tools():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if path not in sys.path:
        sys.path.insert(0, path)


def test_progcheck_green_on_exported_model(tmp_path):
    _tools()
    import progcheck
    from paddle_tpu import io
    main, startup, _loss, logits = _train_program()
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        io.save_inference_model(str(tmp_path), ["x"], [logits], exe,
                                main_program=main)
    assert progcheck.main([str(tmp_path)]) == 0
    # corrupt the exported IR: point an op input at a renamed var
    model = tmp_path / "__model__.json"
    meta = json.loads(model.read_text())
    prog = meta["program"]
    patched = False
    for op in prog["blocks"][0]["ops"]:
        for slot, names in op["inputs"].items():
            if "x" in names:
                op["inputs"][slot] = ["x_renamed_by_corruption"
                                      if n == "x" else n for n in names]
                patched = True
                break
        if patched:
            break
    assert patched
    model.write_text(json.dumps(meta))
    assert progcheck.main([str(tmp_path)]) == 2    # exit = max severity
    # unreadable envelope is as fatal
    bad = tmp_path / "garbage.json"
    bad.write_text("{not json")
    assert progcheck.main([str(bad)]) == 2


def test_progcheck_json_output(tmp_path, capsys):
    _tools()
    import progcheck
    main = _dead_op_program()
    p = tmp_path / "prog.json"
    p.write_text(main.to_json())
    rc = progcheck.main([str(p), "--fetch", "live", "--json"])
    assert rc == 0     # dead ops are info-severity: clean exit
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "progcheck"
    assert out["programs"][0]["counts"]["info"] == 2


# ---------------------------------------------------------------------------
# serving-artifact verification at predictor load
# ---------------------------------------------------------------------------

def test_serving_predictor_refuses_corrupt_artifact(tmp_path):
    from paddle_tpu import io
    from paddle_tpu.serving import ServingPredictor
    main, startup, _loss, logits = _train_program()
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        io.save_inference_model(str(tmp_path), ["x"], [logits], exe,
                                main_program=main, format="stablehlo",
                                batch_sizes=(2,))
        pred = ServingPredictor(str(tmp_path))       # clean: loads
        assert pred.get_input_names() == ["x"]
        # corrupt the shipped IR
        model = tmp_path / "__model__.json"
        meta = json.loads(model.read_text())
        ops = meta["program"]["blocks"][0]["ops"]
        ops[0]["inputs"] = {k: ["gone_var"] for k in ops[0]["inputs"]}
        model.write_text(json.dumps(meta))
        with pytest.raises(ValueError, match="program verification"):
            ServingPredictor(str(tmp_path))
