"""Elastic data plane, trainer level: ShardedFeed-driven training with
checkpointed cursors, membership-aware stream re-balancing and
exact-batch resume (framework/coordination.ElasticTrainer feed mode +
resilience.ResilientTrainer feed mode).

tests/test_elastic.py proves the PARAMETER side of elastic recovery;
this battery proves the data side finally matches it: a host death
mid-epoch re-homes its stream ranges onto the survivors with a
full-epoch census of exactly-once consumption, and a consensus rewind
restores the dataset cursor with the params so the replayed batch
sequence is identical — including when the restoring topology differs
from the saving one."""
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer
from paddle_tpu.framework import resilience
from paddle_tpu.framework.coordination import (ElasticTrainer,
                                               LocalCoordinator,
                                               PodResilientTrainer)
from paddle_tpu.framework.resilience import ResilientTrainer, RetryPolicy
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.reader import ShardedFeed

pytestmark = [pytest.mark.faultinject, pytest.mark.pod, pytest.mark.data]

POD_TIMEOUT_S = 300.0
FEATURES = 6


@pytest.fixture(autouse=True)
def _clean_resilience():
    resilience.install(None)
    resilience.clear_events()
    yield
    resilience.install(None)
    resilience.clear_events()


def _fast_policy():
    return RetryPolicy(base_delay_s=0.0, jitter=0.0, sleep=lambda s: None)


def _data_program():
    """Plain Program (replicated math — elasticity is pure control/data
    plane): fc regression + a sample-id passthrough fetch for the
    census."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [FEATURES], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        sid = layers.data("sid", [1], dtype="float32")
        pred = layers.fc(x, size=1,
                         param_attr=pt.ParamAttr(name="ed_w"),
                         bias_attr=pt.ParamAttr(name="ed_b"))
        loss = layers.reduce_mean(layers.square(pred - y))
        optimizer.Adam(0.05).minimize(loss)
    return main, startup, loss, sid


def _sample_files(n_files, per_file, seed=0):
    """Files of dict samples with globally unique ids riding along."""
    rng = np.random.RandomState(seed)
    w = rng.randn(FEATURES, 1).astype(np.float32)
    files = []
    for f in range(n_files):
        rows = []
        for i in range(per_file):
            xv = rng.randn(FEATURES).astype(np.float32)
            rows.append({"x": xv, "y": (xv @ w).astype(np.float32),
                         "sid": np.float32([f * per_file + i])})
        files.append(rows)
    return files


def _make_feed_pod(tmp_path, tag, files, n_hosts, batch=2, epochs=1,
                   checkpoint_every=2, rejoin=True, seed=5, **elastic_kw):
    main, startup, loss, sid = _data_program()
    trainers = []
    for h in range(n_hosts):
        sc, exe = Scope(), pt.Executor()
        with scope_guard(sc):
            exe.run(startup)
        feed = ShardedFeed(files, n_hosts, h, seed=seed,
                           batch_size=batch, epochs=epochs)
        trainers.append(ResilientTrainer(
            exe, main, str(tmp_path / tag / ("h%d" % h)),
            fetch_list=[loss, sid], checkpoint_every=checkpoint_every,
            scope=sc, retry_policy=_fast_policy(), feed=feed))
    pod = ElasticTrainer(
        trainers, LocalCoordinator(n_hosts, timeout_s=POD_TIMEOUT_S),
        rejoin=rejoin, **elastic_kw)
    return pod, trainers, loss


def _census(outs_by_host):
    ids = []
    for outs in outs_by_host:
        if outs is None:
            continue
        for o in outs:
            ids.extend(int(s) for s in np.asarray(o[1]).ravel())
    return sorted(ids)


def _losses(outs):
    return np.asarray([float(np.asarray(o[0]).ravel()[0]) for o in outs])


# ---------------------------------------------------------------------------
# single host: cursor through save/restore (resilience.ResilientTrainer)
# ---------------------------------------------------------------------------

def test_single_host_feed_exact_resume(tmp_path):
    """A preemption mid-epoch restores params AND cursor: the committed
    batch stream is identical to the uninterrupted run, sample for
    sample and loss for loss."""
    files = _sample_files(4, 6)

    def run_one(tag, spec=None):
        main, startup, loss, sid = _data_program()
        sc, exe = Scope(), pt.Executor()
        with scope_guard(sc):
            exe.run(startup)
        feed = ShardedFeed(files, 1, 0, seed=3, batch_size=3, epochs=1)
        tr = ResilientTrainer(exe, main, str(tmp_path / tag),
                              fetch_list=[loss, sid],
                              checkpoint_every=2, scope=sc,
                              retry_policy=_fast_policy(), feed=feed)
        if spec:
            with resilience.inject(spec):
                return tr.run(steps=50)
        return tr.run(steps=50)

    ref = run_one("ref")
    assert len(ref) == 8                       # 24 samples / batch 3
    resilience.clear_events()
    got = run_one("chaos", spec="step:preempt@4")
    assert resilience.events("restore")        # a real rewind happened
    np.testing.assert_array_equal(_losses(got), _losses(ref))
    assert _census([got]) == _census([ref]) == list(range(24))


def test_feed_mode_validation(tmp_path):
    main, startup, loss, _sid = _data_program()
    sc, exe = Scope(), pt.Executor()
    with scope_guard(sc):
        exe.run(startup)
    tr = ResilientTrainer(exe, main, str(tmp_path / "v"),
                          fetch_list=[loss], scope=sc)
    with pytest.raises(ValueError, match="attached ShardedFeed"):
        tr.run(steps=4)
    feed = ShardedFeed(_sample_files(2, 2), 1, 0)
    tr2 = ResilientTrainer(exe, main, str(tmp_path / "v2"),
                           fetch_list=[loss], scope=sc, feed=feed)
    with pytest.raises(ValueError, match="steps"):
        tr2.run()
    # pods refuse mixed feed/feed-less trainers
    with pytest.raises(ValueError, match="ShardedFeed attached"):
        PodResilientTrainer([tr, tr2], LocalCoordinator(2))
    pod, _, _ = _make_feed_pod(tmp_path, "v3", _sample_files(4, 2), 2)
    with pytest.raises(ValueError, match="steps"):
        pod.run(None)
    # mismatched feed topology / copy-pasted host slots are loud
    files = _sample_files(4, 2)

    def pod_with_feeds(tag, feeds):
        trainers = []
        for h, fd in enumerate(feeds):
            sc2, exe2 = Scope(), pt.Executor()
            trainers.append(ResilientTrainer(
                exe2, main, str(tmp_path / tag / str(h)),
                fetch_list=[loss], scope=sc2, feed=fd))
        return PodResilientTrainer(trainers, LocalCoordinator(2))

    with pytest.raises(ValueError, match="built for 4 hosts"):
        pod_with_feeds("v4", [ShardedFeed(files, 4, h)
                              for h in range(2)])
    with pytest.raises(ValueError, match="host slot"):
        pod_with_feeds("v5", [ShardedFeed(files, 2, 0),
                              ShardedFeed(files, 2, 0)])


# ---------------------------------------------------------------------------
# pod: consensus rewind replays the identical batch sequence
# ---------------------------------------------------------------------------

def test_pod_rewind_replays_identical_batches(tmp_path):
    """ACCEPTANCE (exact resume): kill + consensus rewind with cursor
    restore replays the identical batch sequence — per-step loss
    equality against the uninterrupted run, on every host."""
    files = _sample_files(6, 4)
    ref_pod, _, _ = _make_feed_pod(tmp_path, "ref", files, 2, batch=3)
    ref = ref_pod.run(None, steps=50)

    resilience.clear_events()
    pod, _, _ = _make_feed_pod(tmp_path, "chaos", files, 2, batch=3)
    with resilience.inject("step:preempt@5"):
        out = pod.run(None, steps=50)
    assert resilience.events("pod_restore")
    assert not resilience.events("elastic_shrink")
    for h in range(2):
        np.testing.assert_array_equal(_losses(out[h]), _losses(ref[h]))
        assert _census([out[h]]) == _census([ref[h]])
    assert _census(out) == list(range(24))


def test_plain_pod_feed_rewind(tmp_path):
    """The non-elastic PodResilientTrainer threads the cursor through
    its rewind too (feed-driven windows, drain consensus)."""
    files = _sample_files(4, 4)
    main, startup, loss, sid = _data_program()

    def mk(tag):
        trainers = []
        for h in range(2):
            sc, exe = Scope(), pt.Executor()
            with scope_guard(sc):
                exe.run(startup)
            feed = ShardedFeed(files, 2, h, seed=5, batch_size=2,
                               epochs=1)
            trainers.append(ResilientTrainer(
                exe, main, str(tmp_path / tag / ("h%d" % h)),
                fetch_list=[loss, sid], checkpoint_every=2, scope=sc,
                retry_policy=_fast_policy(), feed=feed))
        return PodResilientTrainer(
            trainers, LocalCoordinator(2, timeout_s=POD_TIMEOUT_S))

    ref = mk("ref").run(None, steps=50)
    resilience.clear_events()
    with resilience.inject("step:preempt@3"):
        out = mk("chaos").run(None, steps=50)
    assert resilience.events("pod_restore")
    for h in range(2):
        np.testing.assert_array_equal(_losses(out[h]), _losses(ref[h]))
    assert _census(out) == list(range(16))


# ---------------------------------------------------------------------------
# the chaos acceptance: die mid-epoch -> shrink -> rejoin -> census
# ---------------------------------------------------------------------------

def test_elastic_census_die_shrink_rejoin_full_mesh(tmp_path):
    """ACCEPTANCE (census): a host dies mid-epoch; survivors absorb its
    stream ranges and keep training (no rewind); the host rejoins and
    takes its lanes back; the full-epoch census shows every sample
    consumed exactly once across shrink -> rejoin -> full mesh."""
    files = _sample_files(8, 4)                # 32 samples
    pod, trainers, _ = _make_feed_pod(tmp_path, "census", files, 4)
    with resilience.inject("step:die@10"):     # ~window 3 of 4-host run
        out = pod.run(None, steps=40)

    kinds = [e["kind"] for e in resilience.events()]
    assert "pod_restore" not in kinds and "restore" not in kinds
    assert resilience.events("elastic_shrink")
    grow = resilience.events("elastic_grow")
    assert grow and grow[-1]["capacity"] == "4/4"
    assert resilience.events("rejoin")
    rebalances = resilience.events("feed_rebalance")
    assert {e["capacity"] for e in rebalances} >= {"3/4", "4/4"}
    died = {e["host"] for e in resilience.events("host_death")}
    assert len(died) == 1
    # EVERY sample exactly once, across the whole membership story —
    # the dead host's pre-death committed batches plus the re-homed
    # remainder on the survivors plus the joiner's post-rejoin batches
    assert _census(out) == list(range(32))
    # lanes returned home: at full membership the map is the identity
    for h, t in enumerate(trainers):
        assert t._feed._own == [h]
    # feed gauges surfaced through the boundary metrics hook
    m = resilience.metrics()
    names = {c["name"] for c in m["counters"]}
    assert "paddle_tpu_resilience_feed_rebalance_total" in names
    assert any(g["name"] == "paddle_tpu_resilience_feed_epoch"
               for g in m["gauges"])


def test_agreed_lags_assembled_from_frozen_verdicts():
    """The weighted-rebalance agreement input: every host assembles the
    SAME {host: lag} map from the frozen window verdicts (each peer's
    exchange_state()["lag"]); exchanges without the key (pre-upgrade
    peers) yield None so rebalance falls back to its local default."""
    verdicts = {
        0: ["ok", [], {"lanes": {}, "drained": False, "lag": 7}, False],
        1: ["ok", [], {"lanes": {}, "drained": False, "lag": 0}, False],
    }
    assert ElasticTrainer._agreed_lags(verdicts) == {0: 7.0, 1: 0.0}
    assert ElasticTrainer._agreed_lags(
        {0: ["ok", [], {"lanes": {}, "drained": False}, False]}) is None
    assert ElasticTrainer._agreed_lags({0: ["ok", [], None, False]}) \
        is None


def test_weighted_rebalance_rides_the_window_exchange(tmp_path):
    """AGREEMENT caveat closed: an ElasticTrainer shrink re-balances a
    weighted_rebalance feed with the lag map carried ON the window
    status exchange — the placement is weighted even though the local
    event log holds no feed_stream_lag gauges at shrink time (which is
    exactly the divergent-local-logs situation of a socket pod), and
    the census stays exactly-once."""
    files = _sample_files(8, 4)                # 32 samples
    main, startup, loss, sid = _data_program()
    trainers = []
    for h in range(4):
        sc, exe = Scope(), pt.Executor()
        with scope_guard(sc):
            exe.run(startup)
        feed = ShardedFeed(files, 4, h, seed=5, batch_size=2, epochs=1,
                           weighted_rebalance=True)
        trainers.append(ResilientTrainer(
            exe, main, str(tmp_path / "wl" / ("h%d" % h)),
            fetch_list=[loss, sid], checkpoint_every=2, scope=sc,
            retry_policy=_fast_policy(), feed=feed))
    pod = ElasticTrainer(trainers,
                         LocalCoordinator(4, timeout_s=POD_TIMEOUT_S))
    assert not resilience.events("feed_lag")   # no local gauges exist
    with resilience.inject("step:die@10"):
        out = pod.run(None, steps=40)
    shrinks = [e for e in resilience.events("feed_rebalance")
               if e["capacity"] == "3/4"]
    assert shrinks and all(e["weighted"] for e in shrinks), shrinks
    assert _census(out) == list(range(32))


def test_topology_change_resume_census(tmp_path):
    """Exact resume ACROSS a topology change: the pod shrinks 3 -> 2
    mid-epoch (no rejoin), then a transient fault rewinds the survivors
    to the step-0 checkpoint — whose cursor map was written at FULL
    topology. The restore re-maps the 3-lane cursor onto the 2
    survivors, and their replayed epoch serves every sample exactly
    once (the fenced host's pre-rewind output is retroactively
    superseded)."""
    files = _sample_files(6, 4)                # 24 samples
    # checkpoint_every huge: the only common checkpoint is step 0, so
    # the rewind MUST cross the membership change
    # buddy=False: the point is the DISK rewind crossing a membership
    # change (a 3-lane cursor map re-mapped onto 2 survivors); the
    # buddy tier would restore the newer post-shrink boundary instead
    pod, trainers, _ = _make_feed_pod(tmp_path, "topo", files, 3,
                                      checkpoint_every=100,
                                      rejoin=False, buddy=False)
    with resilience.inject("step:die@7;step:preempt@12"):
        out = pod.run(None, steps=60)
    assert resilience.events("elastic_shrink")
    restores = resilience.events("pod_restore")
    assert restores and restores[-1]["step"] == 0
    died = {e["host"] for e in resilience.events("host_death")}
    assert len(died) == 1
    survivors = [out[h] for h in range(3) if h not in died]
    assert _census(survivors) == list(range(24))


# ---------------------------------------------------------------------------
# LR rescale on capacity change (satellite)
# ---------------------------------------------------------------------------

def _lr_value(trainer):
    sc = trainer._scope
    names = [n for n in sc.keys() if "learning_rate" in n]
    assert names, "optimizer learning-rate var not found"
    return float(np.asarray(sc.find_var(names[0])).ravel()[0])


def test_lr_rescale_on_shrink(tmp_path):
    """Fixed-per-host-batch regime: losing 1 of 3 hosts shrinks the
    global batch by 1/3, so lr_rescale=True scales the LR to 2/3 — with
    the capacity-labelled lr_rescale event."""
    files = _sample_files(6, 4)
    pod, trainers, _ = _make_feed_pod(tmp_path, "lr", files, 3,
                                      rejoin=False, lr_rescale=True)
    with resilience.inject("step:die@7"):
        pod.run(None, steps=60)
    died = {e["host"] for e in resilience.events("host_death")}
    ev = resilience.events("lr_rescale")
    assert ev and ev[-1]["capacity"] == "2/3"
    assert abs(ev[-1]["factor"] - 2.0 / 3.0) < 1e-6
    for h in range(3):
        if h not in died:
            assert abs(_lr_value(trainers[h]) - 0.05 * 2 / 3) < 1e-6


def test_lr_rescale_returns_to_one_on_rejoin(tmp_path):
    """Shrink scales down, the rejoin's grow scales back: after the full
    mesh is restored every host (including the re-absorbed one) runs at
    the original LR."""
    files = _sample_files(8, 4)
    pod, trainers, _ = _make_feed_pod(tmp_path, "lr2", files, 4,
                                      lr_rescale=True)
    with resilience.inject("step:die@10"):
        pod.run(None, steps=40)
    caps = [e["capacity"] for e in resilience.events("lr_rescale")]
    assert "3/4" in caps and "4/4" in caps
    for t in trainers:
        assert abs(_lr_value(t) - 0.05) < 1e-6


def test_lr_rescale_gradient_merge_compensation(tmp_path):
    """Gradient-merge-aware: an operator who doubles the accumulation
    steps when capacity halves keeps the effective global batch — and
    the LR must NOT move (factor 1.0, no event)."""
    files = _sample_files(4, 4)
    pod, trainers, _ = _make_feed_pod(
        tmp_path, "lr3", files, 2, rejoin=False, lr_rescale=True,
        grad_merge_steps=lambda live: 2 // live)
    with resilience.inject("step:die@3"):
        pod.run(None, steps=40)
    assert not resilience.events("lr_rescale")
    died = {e["host"] for e in resilience.events("host_death")}
    for h in range(2):
        if h not in died:
            assert abs(_lr_value(trainers[h]) - 0.05) < 1e-9

# ---------------------------------------------------------------------------
# feed-driven CompiledProgram pods (PR 10 satellite: ShardedFeed batches
# assembled through a dp-sharded CompiledProgram — the carried-over
# ROADMAP follow-on; lr_rescale applies on the sharded path)
# ---------------------------------------------------------------------------

def _make_compiled_feed_pod(tmp_path, tag, files, n_hosts, dp=4,
                            batch=4, **elastic_kw):
    """_make_feed_pod with each trainer targeting a dp-sharded
    CompiledProgram: every host draws its OWN lanes' batch and shards it
    over its dp axis (the per-host batch-assembly convention: host h's
    ShardedFeed rows ARE its replica's global batch; a real multi-host
    mesh assembles the rows via the process-local feed path)."""
    from paddle_tpu.framework.compiler import CompiledProgram
    main, startup, loss, sid = _data_program()
    trainers = []
    for h in range(n_hosts):
        sc, exe = Scope(), pt.Executor()
        with scope_guard(sc):
            exe.run(startup)
        feed = ShardedFeed(files, n_hosts, h, seed=5, batch_size=batch,
                           epochs=1)
        trainers.append(ResilientTrainer(
            exe, CompiledProgram(main).with_mesh({"dp": dp}),
            str(tmp_path / tag / ("h%d" % h)), fetch_list=[loss, sid],
            checkpoint_every=2, scope=sc, retry_policy=_fast_policy(),
            feed=feed))
    pod = ElasticTrainer(
        trainers, LocalCoordinator(n_hosts, timeout_s=POD_TIMEOUT_S),
        **elastic_kw)
    return pod, trainers, loss


def test_feed_driven_compiled_pod_matches_plain(tmp_path):
    """The dp-sharded CompiledProgram path is semantics-neutral for a
    feed-driven pod: identical committed losses + exactly-once census
    vs the plain-Program pod over the same lanes."""
    files = _sample_files(6, 4)
    pod_p, _, _ = _make_feed_pod(tmp_path, "fcp_plain", files, 3,
                                 batch=4, rejoin=False)
    ref = pod_p.run(None, steps=60)
    resilience.clear_events()
    pod_c, _, _ = _make_compiled_feed_pod(tmp_path, "fcp_comp", files, 3,
                                          rejoin=False)
    out = pod_c.run(None, steps=60)
    assert _census(out) == _census(ref)
    for h in range(3):
        np.testing.assert_allclose(_losses(out[h]), _losses(ref[h]),
                                   rtol=1e-5, atol=1e-7)


def test_feed_driven_compiled_pod_lr_rescale_on_shrink(tmp_path):
    """lr_rescale applies on the SHARDED path: losing 1 of 3 hosts in a
    compiled feed-driven pod shrinks each survivor's mesh (elastic
    re-shard) AND scales the LR vars inside the compiled step's state —
    the next windows train with the rescaled LR."""
    files = _sample_files(6, 4)
    pod, trainers, _ = _make_compiled_feed_pod(
        tmp_path, "fcp_lr", files, 3, dp=2, batch=2, rejoin=False,
        lr_rescale=True)
    with resilience.inject("step:die@7"):
        out = pod.run(None, steps=60)
    died = {e["host"] for e in resilience.events("host_death")}
    assert len(died) == 1
    ev = resilience.events("lr_rescale")
    assert ev and ev[-1]["capacity"] == "2/3"
    assert abs(ev[-1]["factor"] - 2.0 / 3.0) < 1e-6
    shrink = resilience.events("elastic_shrink")
    assert shrink and shrink[-1]["capacity"] == "2/3"
    for h in range(3):
        if h not in died:
            assert abs(_lr_value(trainers[h]) - 0.05 * 2 / 3) < 1e-6
    # exactly-once over the survivors + the pre-death commits
    ids = _census(out)
    assert len(ids) == len(set(ids))
