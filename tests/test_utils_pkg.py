"""paddle.utils tail parity: batch, preprocess_util/_img, plotcurve,
show_pb, torch2paddle, check_import_scipy."""
import io as _io
import json

import numpy as np
import pytest

import paddle_tpu as pt


def test_batch_decorator():
    import paddle_tpu.batch   # rebinds pt.batch to the module...
    from paddle_tpu.batch import batch

    def reader():
        return iter(range(7))

    assert [len(b) for b in batch(reader, 3)()] == [3, 3, 1]
    assert [len(b) for b in batch(reader, 3, drop_last=True)()] == [3, 3]
    # ...but the module is callable, so the paddle.batch(...) spelling
    # keeps working after the submodule import
    assert [len(b) for b in pt.batch(reader, 4)()] == [4, 3]


def test_check_import_scipy_noop_on_posix():
    from paddle_tpu.check_import_scipy import check_import_scipy
    check_import_scipy("posix")    # must not raise


def test_preprocess_util_corpus(tmp_path):
    from paddle_tpu.utils import preprocess_util as pu
    for split in ("train", "test"):
        for cls in ("cat", "dog"):
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            for i in range(3):
                (d / ("img%d.txt" % i)).write_text("x")
    labels = pu.get_label_set_from_dir(str(tmp_path / "train"))
    assert labels == {"cat": 0, "dog": 1}
    assert pu.list_files(str(tmp_path / "train" / "cat")) == [
        "img0.txt", "img1.txt", "img2.txt"]

    ds = pu.Dataset([("a", 0), ("b", 1), ("c", 0)], ["data", "label"])
    assert ds.check_valid() and len(ds) == 3
    ds.permute(seed=1)

    class Creater(pu.DatasetCreater):
        def create_dataset_from_dir(self, path, label_set=None):
            labels = (label_set if label_set is not None
                      else pu.get_label_set_from_dir(path))
            samples = [(f, lbl)
                       for cls, lbl in labels.items()
                       for f in pu.list_files(path + "/" + cls)]
            return pu.Dataset(samples, ["file", "label"])

    c = Creater(str(tmp_path))
    out = c.create_batches()
    import os
    assert os.path.exists(os.path.join(out, "train.list"))
    assert os.path.exists(os.path.join(out, "labels.pkl"))


def test_preprocess_img_resize():
    PIL = pytest.importorskip("PIL")
    from PIL import Image
    from paddle_tpu.utils.preprocess_img import resize_image
    img = Image.new("RGB", (100, 50))
    out = resize_image(img, 32)
    assert out.size == (64, 32)      # short side = 32, aspect kept


def test_plotcurve_extract():
    from paddle_tpu.utils.plotcurve import extract_curve
    log = [
        "step 10: loss=[0.9] acc=[0.4]",
        "step 20: loss=[0.5] acc=[0.6]",
        "AvgCost=0.33",
    ]
    curves = extract_curve(["loss", "AvgCost"], log)
    assert curves["loss"] == [0.9, 0.5]
    assert curves["AvgCost"] == [0.33]


def test_show_pb_summarizes_program(tmp_path, capsys):
    from paddle_tpu.utils import show_pb
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], "float32")
        layers.fc(x, size=2)
    p = tmp_path / "prog.json"
    p.write_text(main.to_json())
    buf = _io.StringIO()
    show_pb.show(str(p), out=buf)
    text = buf.getvalue()
    assert "Program:" in text and ("fc" in text or "mul" in text)
    with pytest.raises(NotImplementedError, match="JSON"):
        show_pb.read_proto(None)


def test_torch2paddle_linear_roundtrip():
    torch = pytest.importorskip("torch")
    from paddle_tpu.utils.torch2paddle import load_torch_parameters
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu import layers, optimizer

    tlin = torch.nn.Linear(4, 3)
    main, startup = pt.Program(), pt.Program()
    with pt.unique_name.guard(), pt.program_guard(main, startup):
        x = layers.data("x", [4], "float32")
        y = layers.fc(x, size=3, param_attr=pt.ParamAttr(name="fc_w"),
                      bias_attr=pt.ParamAttr(name="fc_b"))
    sc = Scope()
    with scope_guard(sc):
        exe = pt.Executor()
        exe.run(startup)
        load_torch_parameters(
            sc, tlin.state_dict(),
            {"weight": "fc_w", "bias": "fc_b"})
        xv = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        got, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    want = tlin(torch.from_numpy(xv)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_torch2paddle_save_dir_loads_via_io(tmp_path):
    torch = pytest.importorskip("torch")
    from paddle_tpu.utils.torch2paddle import save_net_parameters
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu import layers
    import paddle_tpu.io as pio

    tlin = torch.nn.Linear(4, 3)
    out = str(tmp_path / "converted")
    save_net_parameters(tlin.state_dict(),
                        {"weight": "cv_w", "bias": "cv_b"}, out,
                        transpose_names={"weight"})
    main, startup = pt.Program(), pt.Program()
    with pt.unique_name.guard(), pt.program_guard(main, startup):
        x = layers.data("x", [4], "float32")
        y = layers.fc(x, size=3, param_attr=pt.ParamAttr(name="cv_w"),
                      bias_attr=pt.ParamAttr(name="cv_b"))
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        pio.load_params(exe, out, main_program=main)
        xv = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        got, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    want = tlin(torch.from_numpy(xv)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_torch2paddle_square_weight_requires_explicit_choice():
    torch = pytest.importorskip("torch")
    from paddle_tpu.utils.torch2paddle import load_torch_parameters
    from paddle_tpu.framework.scope import Scope

    tlin = torch.nn.Linear(3, 3)
    sc = Scope()
    sc.set_var("w", np.zeros((3, 3), np.float32))
    with pytest.raises(ValueError, match="ambiguous"):
        load_torch_parameters(sc, tlin.state_dict(), {"weight": "w"})
    load_torch_parameters(sc, tlin.state_dict(), {"weight": "w"},
                          transpose_names={"weight"})
    np.testing.assert_allclose(
        np.asarray(sc.find_var("w")),
        tlin.weight.detach().numpy().T)
