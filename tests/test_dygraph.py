"""Dygraph tests (reference: tests/unittests/test_imperative_*)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import dygraph
from paddle_tpu.dygraph import (Linear, Conv2D, BatchNorm, Embedding,
                                LayerNorm, Sequential, to_variable)


def test_eager_math():
    with dygraph.guard():
        a = to_variable(np.array([1.0, 2.0], np.float32))
        b = to_variable(np.array([3.0, 4.0], np.float32))
        c = a * b + 2.0
        np.testing.assert_allclose(c.numpy(), [5.0, 10.0])


def test_linear_forward_and_grad():
    with dygraph.guard():
        layer = Linear(4, 2)
        x = np.random.RandomState(0).rand(3, 4).astype(np.float32)

        def loss_fn(out):
            from paddle_tpu.dygraph.nn import run_op
            return run_op("reduce_mean",
                          {"X": [out]}, {"reduce_all": True})["Out"]

        loss, grads = layer.loss_and_grad(loss_fn, x)
        assert np.isfinite(loss.numpy()).all()
        gw = layer.weight.gradient()
        # d(mean(xW+b))/dW = x.mean(0)/out_dim broadcast
        expect = np.tile(x.mean(0, keepdims=True).T / 2, (1, 2))
        np.testing.assert_allclose(gw, expect, rtol=1e-5)


def test_sequential_conv_bn():
    with dygraph.guard():
        model = Sequential(Conv2D(3, 8, 3, padding=1),
                           BatchNorm(8, act="relu"))
        x = to_variable(np.random.RandomState(0)
                        .rand(2, 3, 8, 8).astype(np.float32))
        out = model(x)
        assert out.shape == (2, 8, 8, 8)
        model.eval()
        out2 = model(x)
        assert out2.shape == (2, 8, 8, 8)


def test_embedding_layernorm():
    with dygraph.guard():
        emb = Embedding([50, 16])
        ln = LayerNorm(16)
        ids = to_variable(np.array([[1], [4]], np.int64))
        e = emb(ids)
        out = ln(e)
        assert out.shape == (2, 16)
        np.testing.assert_allclose(out.numpy().mean(-1), 0.0, atol=1e-5)


def test_state_dict_roundtrip(tmp_path):
    from paddle_tpu.dygraph import save_dygraph, load_dygraph
    with dygraph.guard():
        l1 = Linear(4, 2)
        sd = l1.state_dict()
        save_dygraph(sd, str(tmp_path / "model"))
        loaded, _ = load_dygraph(str(tmp_path / "model"))
        l2 = Linear(4, 2)
        l2.set_dict(loaded)
        np.testing.assert_allclose(l2.weight.numpy(), l1.weight.numpy())


def test_traced_layer_jit():
    from paddle_tpu.dygraph.jit import TracedLayer
    with dygraph.guard():
        layer = Linear(4, 2)
        x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        eager = layer(to_variable(x)).numpy()
        out, traced = TracedLayer.trace(layer, [x])
        np.testing.assert_allclose(out.numpy(), eager, rtol=1e-6)
        again = traced([x])
        np.testing.assert_allclose(again.numpy(), eager, rtol=1e-6)


def test_data_parallel_step():
    import jax
    if jax.device_count() < 8:
        import pytest
        pytest.skip("needs 8 devices")
    from paddle_tpu.dygraph import DataParallel
    from paddle_tpu.dygraph.optimizers import SGD
    from paddle_tpu.dygraph.nn import run_op
    rng = np.random.RandomState(0)
    x = rng.rand(32, 4).astype(np.float32)
    t = (x @ rng.randn(4, 1)).astype(np.float32)

    with dygraph.guard():
        layer = Linear(4, 1)
        dp = DataParallel(layer)
        opt = SGD(0.2)

        def loss_fn(out):
            # capture target shards is awkward; regress to zero instead
            return run_op("reduce_mean",
                          {"X": [run_op("square", {"X": [out]})["Out"]]},
                          {"reduce_all": True})["Out"]

        l0 = float(dp.train_step(loss_fn, opt, x).numpy())
        for _ in range(10):
            l1 = float(dp.train_step(loss_fn, opt, x).numpy())
        assert l1 < l0


def test_tape_backward_fluid_idiom():
    """The reference dygraph train-loop idiom runs UNMODIFIED:
    loss.backward(); opt.minimize(loss); layer.clear_gradients()
    (reference tests/unittests/test_imperative_mnist.py:155-181)."""
    from paddle_tpu import layers
    from paddle_tpu.dygraph.optimizers import SGDOptimizer

    rng = np.random.RandomState(0)
    x = rng.rand(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16, 1)).astype(np.int64)

    with dygraph.guard():
        net = Linear(8, 4, act="softmax")
        sgd = SGDOptimizer(learning_rate=0.5,
                           parameter_list=net.parameters())
        losses = []
        for _ in range(60):
            img, label = to_variable(x), to_variable(y)
            cost = net(img)
            loss = layers.cross_entropy(cost, label)
            avg_loss = layers.mean(loss)
            avg_loss.backward()
            sgd.minimize(avg_loss)
            net.clear_gradients()
            losses.append(float(avg_loss.numpy()))
        assert losses[-1] < losses[0] * 0.7


def test_tape_grads_match_functional():
    """Tape .backward() grads equal jax.value_and_grad over the same
    forward (the functional oracle)."""
    from paddle_tpu import layers

    rng = np.random.RandomState(1)
    x = rng.rand(4, 6).astype(np.float32)

    with dygraph.guard():
        net = Linear(6, 3)
        # functional reference
        _, fgrads = net.loss_and_grad(
            lambda o: layers.mean(layers.square(o)), x)
        fg = {pid: np.asarray(g) for pid, g in fgrads.items()}
        net.clear_gradients()
        # tape path
        out = net(to_variable(x))
        loss = layers.mean(layers.square(out))
        loss.backward()
        for p in net.parameters():
            np.testing.assert_allclose(np.asarray(p._grad), fg[id(p)],
                                       rtol=1e-5, atol=1e-6)


def test_tape_backward_conv_bn_chain():
    """backward() reaches through run_op kernels (conv/bn/pool) and the
    eager-dispatched static layers; stop_gradient inputs get no grad."""
    from paddle_tpu import layers
    from paddle_tpu.dygraph.nn import Conv2D, BatchNorm, Pool2D

    rng = np.random.RandomState(2)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)

    with dygraph.guard():
        conv = Conv2D(3, 4, 3, padding=1)
        bn = BatchNorm(4)
        pool = Pool2D(pool_size=2, pool_stride=2, pool_type="avg")
        xin = to_variable(x)
        xin.stop_gradient = True
        out = pool(bn(conv(xin)))
        loss = layers.mean(layers.square(out))
        loss.backward()
        assert conv.weight.gradient() is not None
        assert bn.weight.gradient() is not None
        assert float(np.abs(conv.weight.gradient()).sum()) > 0
        assert xin.gradient() is None


def test_tape_accumulates_until_clear():
    """Two backward() calls accumulate grads (reference semantics)."""
    with dygraph.guard():
        net = Linear(3, 2)
        x = to_variable(np.ones((2, 3), np.float32))
        from paddle_tpu import layers
        loss = layers.mean(net(x))
        loss.backward(retain_graph=True)
        g1 = net.weight.gradient().copy()
        loss.backward()
        np.testing.assert_allclose(net.weight.gradient(), 2 * g1,
                                   rtol=1e-6)
        net.clear_gradients()
        assert net.weight.gradient() is None


def test_dygraph_grad_clip_by_value_and_norm():
    from paddle_tpu.dygraph.grad_clip import (GradClipByValue,
                                              GradClipByNorm,
                                              GradClipByGlobalNorm)

    class P:
        def __init__(self, g):
            self._grad = np.asarray(g, np.float32)

    g = np.array([3.0, -4.0], np.float32)
    pairs = [(P(g), g)]
    (_, cv), = GradClipByValue(1.0)(pairs)
    np.testing.assert_allclose(np.asarray(cv), [1.0, -1.0])
    (_, cn), = GradClipByNorm(2.5)(pairs)   # |g|=5 -> scale 0.5
    np.testing.assert_allclose(np.asarray(cn), [1.5, -2.0], rtol=1e-6)
    g2 = np.array([0.0, 0.0], np.float32)
    pairs2 = [(P(g), g), (P(g2), None)]
    clipped = GradClipByGlobalNorm(2.5)(pairs2)
    np.testing.assert_allclose(np.asarray(clipped[0][1]), [1.5, -2.0],
                               rtol=1e-6)
    assert clipped[1][1] is None
    # norm below threshold: untouched
    (_, cu), = GradClipByGlobalNorm(100.0)([(P(g), g)])
    np.testing.assert_allclose(np.asarray(cu), g)


def test_dygraph_minimize_grad_clip_and_legacy_grads_typeerror():
    import pytest
    from paddle_tpu.dygraph.grad_clip import GradClipByGlobalNorm
    from paddle_tpu.dygraph import optimizers as dopt
    with dygraph.guard():
        layer = Linear(2, 1)
        x = np.ones((4, 2), np.float32)

        def loss_fn(out):
            from paddle_tpu.dygraph.nn import run_op
            return run_op("reduce_mean",
                          {"X": [out]}, {"reduce_all": True})["Out"]

        layer.loss_and_grad(loss_fn, x)
        w_before = np.asarray(layer.weight._value).copy()
        opt = dopt.SGD(learning_rate=1.0)
        opt.minimize(layer, grad_clip=GradClipByGlobalNorm(1e-8))
        # clipped to ~zero global norm: weights essentially unchanged
        np.testing.assert_allclose(np.asarray(layer.weight._value),
                                   w_before, atol=1e-6)
        with pytest.raises(TypeError):
            opt.minimize(layer, {"some": "grads"})


def test_dygraph_lr_schedulers():
    from paddle_tpu.dygraph import (PiecewiseDecay, NoamDecay,
                                    ExponentialDecay, LinearLrWarmup,
                                    CosineDecay)
    pw = PiecewiseDecay([3, 6], [1.0, 0.5, 0.1], begin=0)
    vals = [pw() for _ in range(8)]
    assert vals[:3] == [1.0] * 3 and vals[3:6] == [0.5] * 3
    assert vals[6:] == [0.1] * 2

    ex = ExponentialDecay(1.0, decay_steps=2, decay_rate=0.5,
                          staircase=True)
    vs = [ex() for _ in range(4)]
    assert abs(vs[0] - 1.0) < 1e-9 and abs(vs[2] - 0.5) < 1e-9

    nd = NoamDecay(d_model=64, warmup_steps=10)
    warm = [nd() for _ in range(20)]
    assert warm.index(max(warm)) in (9, 10)  # peak at warmup end

    lw = LinearLrWarmup(0.8, warmup_steps=4, start_lr=0.0, end_lr=0.8,
                        begin=0)
    ws = [lw() for _ in range(6)]
    assert abs(ws[0]) < 1e-9 and abs(ws[2] - 0.4) < 1e-9
    assert abs(ws[5] - 0.8) < 1e-9

    cd = CosineDecay(1.0, step_each_epoch=2, epochs=4)
    c0 = cd(); cd()
    c1 = cd()
    assert c0 == 1.0 and c1 < c0

    # drives a dygraph optimizer end-to-end
    import numpy as np
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph import optimizers
    with dygraph.guard():
        lin = dygraph.Linear(4, 2)
        sched = ExponentialDecay(0.1, decay_steps=1, decay_rate=0.5)
        opt = optimizers.SGDOptimizer(learning_rate=sched,
                                      parameter_list=lin.parameters())
        x = dygraph.to_variable(np.ones((2, 4), np.float32))
        before = np.asarray(lin.weight._value).copy()
        for i in range(3):
            out = lin(x)
            loss = out.reduce_mean() if hasattr(out, "reduce_mean") else out
            loss.backward()
            opt.minimize(lin)
            lin.clear_gradients()
        after = np.asarray(lin.weight._value)
        assert not np.allclose(before, after)
        assert sched.step_num >= 3


def test_dygraph_module_tail():
    """New dygraph modules (ref dygraph/nn.py: FC, Conv2DTranspose,
    Conv3D(+T), GroupNorm, SpectralNorm, PRelu, NCE, Bilinear, RowConv,
    SequenceConv, TreeConv): forward shapes + grads flow."""
    from paddle_tpu import dygraph
    rng = np.random.RandomState(0)
    with dygraph.guard():
        x4 = dygraph.to_variable(rng.randn(2, 3, 8, 8).astype(np.float32))
        ct = dygraph.Conv2DTranspose(3, 5, 3)
        o = ct(x4)
        assert np.asarray(o._value).shape == (2, 5, 10, 10)

        x5 = dygraph.to_variable(
            rng.randn(2, 3, 4, 6, 6).astype(np.float32))
        c3 = dygraph.Conv3D(3, 4, 3, padding=1)
        o3 = c3(x5)
        assert np.asarray(o3._value).shape == (2, 4, 4, 6, 6)
        c3t = dygraph.Conv3DTranspose(3, 4, 2, stride=2)
        o3t = c3t(x5)
        assert np.asarray(o3t._value).shape == (2, 4, 8, 12, 12)

        gn = dygraph.GroupNorm(channels=4, groups=2)
        go = gn(o3.detach() if hasattr(o3, "detach") else o3)
        g = np.asarray(go._value)
        assert abs(g.mean()) < 1e-4  # normalized

        fcm = dygraph.FC("fc", size=7, num_flatten_dims=2)
        xf = dygraph.to_variable(rng.randn(2, 3, 4, 5).astype(np.float32))
        fo = fcm(xf)
        assert np.asarray(fo._value).shape == (2, 3, 7)

        pr = dygraph.PRelu("channel", input_shape=[2, 3, 8, 8])
        po = np.asarray(pr(x4)._value)
        xv = np.asarray(x4._value)
        np.testing.assert_allclose(po[xv > 0], xv[xv > 0], rtol=1e-6)
        np.testing.assert_allclose(po[xv < 0], 0.25 * xv[xv < 0],
                                   rtol=1e-5)

        w = dygraph.to_variable(rng.randn(6, 4).astype(np.float32))
        sn = dygraph.SpectralNorm([6, 4], power_iters=5)
        wn = np.asarray(sn(w)._value)
        assert np.linalg.svd(wn, compute_uv=False)[0] < 1.6

        x1 = dygraph.to_variable(rng.randn(3, 4).astype(np.float32))
        y1 = dygraph.to_variable(rng.randn(3, 5).astype(np.float32))
        bl = dygraph.BilinearTensorProduct(4, 5, 6)
        assert np.asarray(bl(x1, y1)._value).shape == (3, 6)

        seq = dygraph.to_variable(rng.randn(2, 7, 5).astype(np.float32))
        rc = dygraph.RowConv("rc", future_context_size=2)
        assert np.asarray(rc(seq)._value).shape == (2, 7, 5)
        sc = dygraph.SequenceConv("sc", num_filters=6, filter_size=3)
        assert np.asarray(sc(seq)._value).shape == (2, 7, 6)

        nodes = dygraph.to_variable(rng.randn(1, 5, 4).astype(np.float32))
        edges = dygraph.to_variable(
            np.array([[[0, 1], [0, 2], [-1, -1]]], np.int64))
        tc = dygraph.TreeConv("tc", output_size=6, num_filters=2)
        assert np.asarray(tc(nodes, edges)._value).shape == (1, 5, 6, 2)

        feats = dygraph.to_variable(rng.randn(4, 8).astype(np.float32))
        labels = dygraph.to_variable(
            rng.randint(0, 20, (4, 1)).astype(np.int64))
        nce = dygraph.NCE(num_total_classes=20, dim=8)
        cost = nce(feats, labels)
        assert np.isfinite(np.asarray(cost._value)).all()

        # grads flow through a new module
        loss = fo * fo
        loss.backward()
        assert fcm.weight._grad is not None or \
            getattr(fcm.weight, "_grad", None) is not None
