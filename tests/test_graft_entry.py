"""The driver-facing entry points must be immune to the calling process's
backend state (round-3 postmortem: 3 rounds of MULTICHIP red because the
driver's process initialized the TPU plugin).

dryrun_multichip self-execs in a fresh subprocess with a guaranteed
CPU-only jax env; these tests pin that contract, including under hostile
TPU env vars like the ones the driver's shell carries.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_dryrun_multichip_subprocess_hostile_env(monkeypatch):
    # the driver's env: TPU plugin forced on, fabric possibly wedged —
    # the subprocess must drop every one of these and still go green
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("TPU_LIBRARY_PATH", "/nonexistent")
    monkeypatch.setenv("PJRT_DEVICE", "TPU")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    graft.dryrun_multichip(8, timeout=480)


def test_dryrun_env_filter_drops_tpu_keys():
    hostile = ["JAX_PLATFORMS", "TPU_LIBRARY_PATH", "PJRT_DEVICE",
               "PALLAS_AXON_POOL_IPS", "AXON_LOOPBACK_RELAY",
               "LIBTPU_INIT_ARGS", "MEGASCALE_COORDINATOR", "XLA_FLAGS",
               "CLOUD_TPU_TASK_ID"]
    for k in hostile:
        assert any(p in k.upper() for p in graft._TPU_ENV_PAT), k
    # benign keys survive the filter
    for k in ["PATH", "HOME", "PYTHONHASHSEED"]:
        assert not any(p in k.upper() for p in graft._TPU_ENV_PAT), k


def test_dryrun_failure_surfaces_child_tail():
    # a broken child must raise, not hang silently past the driver budget
    with pytest.raises(RuntimeError, match="dryrun_multichip"):
        graft.dryrun_multichip(8, timeout=0.001)
