"""Test config: force CPU backend with 8 virtual devices BEFORE jax import,
so sharding/collective tests run anywhere (mirrors how the driver validates
multi-chip via xla_force_host_platform_device_count)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the Program verifier (framework/analysis.py) runs STRICT across the
# whole suite: every program any test compiles must verify clean (or
# carry an explicit analysis.allowlist) — the acceptance bar for the
# verifier's no-false-positive contract. Respect an explicit override
# so `PADDLE_TPU_VERIFY=off pytest` can bisect verifier-vs-product
# failures.
os.environ.setdefault("PADDLE_TPU_VERIFY", "strict")

# site customizations (e.g. the axon TPU plugin) may force jax_platforms;
# override via config so tests always get the 8-device virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

# NO persistent XLA compile cache. It looks like an easy wall-time win
# (this box recompiles every step function each run), but on jax 0.4.37
# a DESERIALIZED multi-device CPU executable is broken: a cache hit on
# any of the 8-virtual-device SPMD step functions returns garbage
# fetches and then segfaults the interpreter at materialization,
# killing the rest of the suite (reproduce: populate a cache dir with
# jax_persistent_cache_min_compile_time_secs=0, run any dp/mp test
# twice). Correctness of the gate beats repeat-run speed; re-enable
# only behind a jax version check once serialized CPU collectives work.
if os.environ.get("PADDLE_TPU_TEST_COMPILE_CACHE"):   # opt-in escape hatch
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ["PADDLE_TPU_TEST_COMPILE_CACHE"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:  # pragma: no cover - older jax without the knob
        pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # registered markers so tier-1 (-m 'not slow') runs warning-free:
    # fast chaos tests carry `faultinject`; long soaks hide behind `slow`
    config.addinivalue_line(
        "markers", "slow: long soak/perf tests excluded from tier-1 runs")
    config.addinivalue_line(
        "markers",
        "faultinject: fast chaos tests driven by framework.resilience")
    config.addinivalue_line(
        "markers",
        "pod: pod-level coordinated-recovery tests (threaded "
        "LocalCoordinator only — tier-1-safe)")
    config.addinivalue_line(
        "markers",
        "data: elastic data plane tests (ShardedFeed cursors, "
        "membership re-balancing, exact-batch resume)")
    config.addinivalue_line(
        "markers",
        "procpod: REAL-process pod-transport tests (subprocesses over "
        "SocketCoordinator, SIGKILL chaos) — wall-bounded, tier-1-safe")
    config.addinivalue_line(
        "markers",
        "quant: quantized-collective / compressed-state-movement tests "
        "(block codec, quantize_collectives guardrails, compressed "
        "checkpoints, bench_micro perf gates)")
    config.addinivalue_line(
        "markers",
        "pallas: Pallas kernel-library oracle batteries (blockwise CE / "
        "fused MLM head, fused Adam, fused LayerNorm, autotune cache, "
        "use_pallas dispatch) — interpret mode on CPU, tier-1-safe")
    config.addinivalue_line(
        "markers",
        "fleet: serving-fleet batteries (micro-batching router + "
        "replica members over CoordServer; SIGKILL chaos under "
        "sustained load) — wall-bounded, tier-1-safe")
    config.addinivalue_line(
        "markers",
        "pp: pipeline-parallel CompiledProgram batteries (pp x dp mesh "
        "cut/lowering, GPipe/1F1B parity, elastic pp rewind) — CPU "
        "8-device mesh, tier-1-safe")
    config.addinivalue_line(
        "markers",
        "obs: distributed-tracing / step-phase-profiler batteries "
        "(obs spans engine, trace-context propagation across the "
        "fleet, traceview merge, tracing-overhead gate) — "
        "tier-1-safe")
    config.addinivalue_line(
        "markers",
        "analysis: Program IR verifier batteries (analysis-pass "
        "framework, adversarial broken-program corpus, progcheck/"
        "codelint tools, strict-mode model sweep) — tier-1-safe")


@pytest.fixture(autouse=True)
def disarmed_failpoints():
    """No test leaks an armed fault schedule (or stale hit counters)
    into the next — the fault-injection plane starts and ends cold."""
    from paddle_tpu.framework import faultinject
    faultinject.disarm()
    faultinject.reset_counters()
    yield
    faultinject.disarm()
    faultinject.reset_counters()


@pytest.fixture(autouse=True)
def fresh_programs():
    """Give every test fresh default programs + scope + name generator."""
    import paddle_tpu as pt
    from paddle_tpu.framework.program import (switch_main_program,
                                              switch_startup_program,
                                              Program)
    from paddle_tpu.framework.scope import Scope, _global_scope
    import paddle_tpu.framework.scope as scope_mod
    from paddle_tpu.framework import unique_name

    old_main = switch_main_program(Program())
    old_startup = switch_startup_program(Program())
    old_scope = scope_mod._global_scope
    scope_mod._global_scope = Scope()
    old_gen = unique_name.switch()
    yield
    switch_main_program(old_main)
    switch_startup_program(old_startup)
    scope_mod._global_scope = old_scope
    unique_name.switch(old_gen)


@pytest.fixture
def rng():
    return np.random.RandomState(42)
